"""Search contexts: scroll cursors + points-in-time over pinned readers.

Reference: `search/SearchService#createContext`, `ReaderContext` /
`LegacyReaderContext`, `RestSearchScrollAction`, `RestOpenPointInTime
Action` (SURVEY.md §2.1#36). A context pins each target shard's
ShardReader — an immutable snapshot (live masks are copied per reader,
so later deletes/refreshes never leak in) — under a keepalive lease;
scroll additionally carries the paging cursor. Contexts are node-local,
exactly like the reference's (the scroll id routes back to the node
that owns the context)."""

from __future__ import annotations

import base64
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (EsException,
                                             IllegalArgumentException)
from elasticsearch_tpu.common.units import TimeValue


class SearchContextMissingException(EsException):
    status = 404


MAX_KEEP_ALIVE_S = 24 * 3600.0


def parse_keep_alive(value: Any, what: str) -> float:
    seconds = TimeValue.parse(value).seconds
    if seconds <= 0 or seconds > MAX_KEEP_ALIVE_S:
        raise IllegalArgumentException(
            f"[{what}] keep_alive must be positive and at most 24h, "
            f"got [{value}]")
    return seconds


class PinnedContext:
    def __init__(self, ctx_id: str, names: List[str],
                 readers: Dict[Tuple[str, int], Any],
                 keep_alive_s: float,
                 scroll_state: Optional[Dict[str, Any]] = None):
        self.id = ctx_id
        self.names = names
        self.readers = readers
        self.keep_alive_s = keep_alive_s
        self.expires = time.monotonic() + keep_alive_s
        # scroll only: {"body": ..., "params": ..., "offset": int}
        self.scroll_state = scroll_state

    def touch(self, keep_alive_s: Optional[float] = None) -> None:
        if keep_alive_s is not None:
            self.keep_alive_s = keep_alive_s
        self.expires = time.monotonic() + self.keep_alive_s


class SearchContextManager:
    """Node-level registry of pinned contexts with keepalive reaping."""

    def __init__(self):
        self._lock = threading.Lock()
        self._contexts: Dict[str, PinnedContext] = {}

    # ---------------- lifecycle ----------------

    def create(self, indices_service, index_expr: Optional[str],
               keep_alive_s: float,
               scroll_state: Optional[Dict[str, Any]] = None,
               names: Optional[List[str]] = None) -> PinnedContext:
        if names is None:
            from elasticsearch_tpu.search.coordinator import \
                resolve_indices
            names = resolve_indices(indices_service, index_expr)
        readers: Dict[Tuple[str, int], Any] = {}
        for name in names:
            svc = indices_service.index(name)
            for shard_num, shard in sorted(svc.shards.items()):
                readers[(name, shard_num)] = shard.acquire_searcher()
        ctx_id = base64.urlsafe_b64encode(
            uuid.uuid4().bytes).decode("ascii").rstrip("=")
        ctx = PinnedContext(ctx_id, names, readers, keep_alive_s,
                            scroll_state)
        with self._lock:
            self._reap_locked()
            self._contexts[ctx_id] = ctx
        return ctx

    def get(self, ctx_id: str) -> PinnedContext:
        with self._lock:
            self._reap_locked()
            ctx = self._contexts.get(ctx_id)
        if ctx is None:
            raise SearchContextMissingException(
                f"No search context found for id [{ctx_id}]")
        return ctx

    def free(self, ctx_id: str, kind: Optional[str] = None) -> bool:
        """kind="scroll"/"pit" frees only that context type — scroll and
        PIT ids share a namespace, and clearing the wrong kind must not
        silently kill a live context of the other."""
        with self._lock:
            ctx = self._contexts.get(ctx_id)
            if ctx is None:
                return False
            if kind == "scroll" and ctx.scroll_state is None:
                return False
            if kind == "pit" and ctx.scroll_state is not None:
                return False
            del self._contexts[ctx_id]
            return True

    def free_all(self, scroll_only: bool = False) -> int:
        with self._lock:
            if not scroll_only:
                n = len(self._contexts)
                self._contexts.clear()
                return n
            victims = [c for c, ctx in self._contexts.items()
                       if ctx.scroll_state is not None]
            for c in victims:
                del self._contexts[c]
            return len(victims)

    def reap(self) -> None:
        """Periodic expiry sweep (called from the node's background
        cycle) — without it, expired contexts would pin segment readers
        on an idle node until the next API call."""
        with self._lock:
            self._reap_locked()

    def _reap_locked(self) -> None:
        now = time.monotonic()
        for cid in [c for c, ctx in self._contexts.items()
                    if ctx.expires < now]:
            del self._contexts[cid]

    def active_count(self) -> int:
        with self._lock:
            self._reap_locked()
            return len(self._contexts)
