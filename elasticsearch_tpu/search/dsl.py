"""Query DSL — the JSON query AST.

Reference: index/query/ (SURVEY.md §2.1#29): AbstractQueryBuilder
#parseInnerQueryBuilder dispatches on the single top-level key of a query
object to a named builder; builders rewrite + lower to executable form via
the per-shard context. The JSON grammar here matches the reference's:

  {"match": {"field": "text"}} | {"match": {"field": {"query": ..., "operator": ...}}}
  {"term": {"field": "value"}} | {"term": {"field": {"value": ...}}}
  {"terms": {"field": [v1, v2]}}
  {"range": {"field": {"gt|gte|lt|lte": v}}}
  {"bool": {"must": [...], "should": [...], "must_not": [...], "filter": [...],
            "minimum_should_match": n}}
  {"match_all": {}}
  {"match_phrase": {"field": "some phrase"}}
  {"exists": {"field": "name"}}
  {"ids": {"values": [...]}}
  {"constant_score": {"filter": {...}, "boost": b}}

Lowering to kernels happens in search/planner.py against a shard reader
(the QueryShardContext#toQuery analog).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import ParsingException


@dataclasses.dataclass
class QueryNode:
    boost: float = 1.0

    def query_name(self) -> str:
        raise NotImplementedError


@dataclasses.dataclass
class MatchAllQuery(QueryNode):
    def query_name(self) -> str:
        return "match_all"


@dataclasses.dataclass
class MatchQuery(QueryNode):
    field: str = ""
    query: str = ""
    operator: str = "or"          # "or" | "and"
    minimum_should_match: Optional[int] = None

    def query_name(self) -> str:
        return "match"


@dataclasses.dataclass
class MatchPhraseQuery(QueryNode):
    field: str = ""
    query: str = ""
    slop: int = 0

    def query_name(self) -> str:
        return "match_phrase"


@dataclasses.dataclass
class TermQuery(QueryNode):
    field: str = ""
    value: Any = None

    def query_name(self) -> str:
        return "term"


@dataclasses.dataclass
class TermsQuery(QueryNode):
    field: str = ""
    values: List[Any] = dataclasses.field(default_factory=list)

    def query_name(self) -> str:
        return "terms"


@dataclasses.dataclass
class RangeQuery(QueryNode):
    field: str = ""
    gt: Any = None
    gte: Any = None
    lt: Any = None
    lte: Any = None

    def query_name(self) -> str:
        return "range"


@dataclasses.dataclass
class ExistsQuery(QueryNode):
    field: str = ""

    def query_name(self) -> str:
        return "exists"


@dataclasses.dataclass
class IdsQuery(QueryNode):
    values: List[str] = dataclasses.field(default_factory=list)

    def query_name(self) -> str:
        return "ids"


@dataclasses.dataclass
class BoolQuery(QueryNode):
    must: List[QueryNode] = dataclasses.field(default_factory=list)
    should: List[QueryNode] = dataclasses.field(default_factory=list)
    must_not: List[QueryNode] = dataclasses.field(default_factory=list)
    filter: List[QueryNode] = dataclasses.field(default_factory=list)
    minimum_should_match: Optional[int] = None

    def query_name(self) -> str:
        return "bool"


@dataclasses.dataclass
class ConstantScoreQuery(QueryNode):
    filter_query: QueryNode = None  # type: ignore[assignment]

    def query_name(self) -> str:
        return "constant_score"


def parse_query(obj: Dict[str, Any]) -> QueryNode:
    """The parseInnerQueryBuilder analog: one top-level key names the query."""
    if not isinstance(obj, dict):
        raise ParsingException(f"query must be an object, got {type(obj).__name__}")
    if len(obj) != 1:
        raise ParsingException(
            f"query object must have exactly one key, got {sorted(obj.keys())}")
    name, body = next(iter(obj.items()))
    parser = _PARSERS.get(name)
    if parser is None:
        raise ParsingException(f"unknown query type [{name}]")
    return parser(body)


def _field_and_params(name: str, body: Dict[str, Any], value_key: str):
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(f"[{name}] expects a single field")
    field, spec = next(iter(body.items()))
    if isinstance(spec, dict):
        if value_key not in spec:
            raise ParsingException(f"[{name}] on [{field}] requires [{value_key}]")
        return field, spec
    return field, {value_key: spec}


def _parse_match(body) -> MatchQuery:
    field, spec = _field_and_params("match", body, "query")
    op = str(spec.get("operator", "or")).lower()
    if op not in ("or", "and"):
        raise ParsingException(f"[match] unknown operator [{op}]")
    msm = spec.get("minimum_should_match")
    return MatchQuery(field=field, query=str(spec["query"]), operator=op,
                      minimum_should_match=None if msm is None else int(msm),
                      boost=float(spec.get("boost", 1.0)))


def _parse_match_phrase(body) -> MatchPhraseQuery:
    field, spec = _field_and_params("match_phrase", body, "query")
    return MatchPhraseQuery(field=field, query=str(spec["query"]),
                            slop=int(spec.get("slop", 0)),
                            boost=float(spec.get("boost", 1.0)))


def _parse_term(body) -> TermQuery:
    field, spec = _field_and_params("term", body, "value")
    return TermQuery(field=field, value=spec["value"],
                     boost=float(spec.get("boost", 1.0)))


def _parse_terms(body) -> TermsQuery:
    if not isinstance(body, dict):
        raise ParsingException("[terms] expects an object")
    boost = float(body.get("boost", 1.0))
    fields = {k: v for k, v in body.items() if k != "boost"}
    if len(fields) != 1:
        raise ParsingException("[terms] expects a single field")
    field, values = next(iter(fields.items()))
    if not isinstance(values, list):
        raise ParsingException(f"[terms] on [{field}] expects an array")
    return TermsQuery(field=field, values=values, boost=boost)


def _parse_range(body) -> RangeQuery:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException("[range] expects a single field")
    field, spec = next(iter(body.items()))
    if not isinstance(spec, dict):
        raise ParsingException(f"[range] on [{field}] expects an object")
    known = {"gt", "gte", "lt", "lte", "boost", "format", "time_zone"}
    unknown = set(spec) - known
    if unknown:
        raise ParsingException(f"[range] unknown parameter {sorted(unknown)}")
    return RangeQuery(field=field, gt=spec.get("gt"), gte=spec.get("gte"),
                      lt=spec.get("lt"), lte=spec.get("lte"),
                      boost=float(spec.get("boost", 1.0)))


def _parse_bool(body) -> BoolQuery:
    if not isinstance(body, dict):
        raise ParsingException("[bool] expects an object")
    q = BoolQuery(boost=float(body.get("boost", 1.0)))
    for clause in ("must", "should", "must_not", "filter"):
        items = body.get(clause, [])
        if isinstance(items, dict):
            items = [items]
        if not isinstance(items, list):
            raise ParsingException(f"[bool] [{clause}] must be an array or object")
        setattr(q, "filter" if clause == "filter" else clause,
                [parse_query(x) for x in items])
    msm = body.get("minimum_should_match")
    if msm is not None:
        q.minimum_should_match = int(msm)
    known = {"must", "should", "must_not", "filter", "minimum_should_match", "boost"}
    unknown = set(body) - known
    if unknown:
        raise ParsingException(f"[bool] unknown parameter {sorted(unknown)}")
    return q


def _parse_match_all(body) -> MatchAllQuery:
    body = body or {}
    return MatchAllQuery(boost=float(body.get("boost", 1.0)))


def _parse_exists(body) -> ExistsQuery:
    if not isinstance(body, dict) or "field" not in body:
        raise ParsingException("[exists] requires [field]")
    return ExistsQuery(field=str(body["field"]))


def _parse_ids(body) -> IdsQuery:
    if not isinstance(body, dict) or "values" not in body:
        raise ParsingException("[ids] requires [values]")
    return IdsQuery(values=[str(v) for v in body["values"]])


def _parse_constant_score(body) -> ConstantScoreQuery:
    if not isinstance(body, dict) or "filter" not in body:
        raise ParsingException("[constant_score] requires [filter]")
    return ConstantScoreQuery(filter_query=parse_query(body["filter"]),
                              boost=float(body.get("boost", 1.0)))


_PARSERS = {
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "bool": _parse_bool,
    "match_all": _parse_match_all,
    "exists": _parse_exists,
    "ids": _parse_ids,
    "constant_score": _parse_constant_score,
}
