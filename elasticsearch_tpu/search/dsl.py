"""Query DSL — the JSON query AST.

Reference: index/query/ (SURVEY.md §2.1#29): AbstractQueryBuilder
#parseInnerQueryBuilder dispatches on the single top-level key of a query
object to a named builder; builders rewrite + lower to executable form via
the per-shard context. The JSON grammar here matches the reference's:

  {"match": {"field": "text"}} | {"match": {"field": {"query": ..., "operator": ...}}}
  {"term": {"field": "value"}} | {"term": {"field": {"value": ...}}}
  {"terms": {"field": [v1, v2]}}
  {"range": {"field": {"gt|gte|lt|lte": v}}}
  {"bool": {"must": [...], "should": [...], "must_not": [...], "filter": [...],
            "minimum_should_match": n}}
  {"match_all": {}}
  {"match_phrase": {"field": "some phrase"}}
  {"exists": {"field": "name"}}
  {"ids": {"values": [...]}}
  {"constant_score": {"filter": {...}, "boost": b}}

Lowering to kernels happens in search/planner.py against a shard reader
(the QueryShardContext#toQuery analog).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import ParsingException


@dataclasses.dataclass
class QueryNode:
    boost: float = 1.0

    def query_name(self) -> str:
        raise NotImplementedError


@dataclasses.dataclass
class MatchAllQuery(QueryNode):
    def query_name(self) -> str:
        return "match_all"


@dataclasses.dataclass
class MatchQuery(QueryNode):
    field: str = ""
    query: str = ""
    operator: str = "or"          # "or" | "and"
    minimum_should_match: Optional[int] = None

    def query_name(self) -> str:
        return "match"


@dataclasses.dataclass
class MatchPhraseQuery(QueryNode):
    field: str = ""
    query: str = ""
    slop: int = 0

    def query_name(self) -> str:
        return "match_phrase"


@dataclasses.dataclass
class TermQuery(QueryNode):
    field: str = ""
    value: Any = None

    def query_name(self) -> str:
        return "term"


@dataclasses.dataclass
class TermsQuery(QueryNode):
    field: str = ""
    values: List[Any] = dataclasses.field(default_factory=list)

    def query_name(self) -> str:
        return "terms"


@dataclasses.dataclass
class RangeQuery(QueryNode):
    field: str = ""
    gt: Any = None
    gte: Any = None
    lt: Any = None
    lte: Any = None
    # interval relation for RANGE FIELDS (reference: RangeFieldMapper);
    # ignored on plain numeric/date fields
    relation: Optional[str] = None

    def query_name(self) -> str:
        return "range"


@dataclasses.dataclass
class ExistsQuery(QueryNode):
    field: str = ""

    def query_name(self) -> str:
        return "exists"


@dataclasses.dataclass
class IdsQuery(QueryNode):
    values: List[str] = dataclasses.field(default_factory=list)

    def query_name(self) -> str:
        return "ids"


@dataclasses.dataclass
class MultiMatchQuery(QueryNode):
    """Reference: MultiMatchQueryBuilder — one text query over several
    fields with per-field boosts ("title^2")."""

    fields: List = dataclasses.field(default_factory=list)  # [(name, boost)]
    query: str = ""
    type: str = "best_fields"     # "best_fields" | "most_fields"
    operator: str = "or"
    minimum_should_match: Optional[int] = None
    tie_breaker: float = 0.0

    def query_name(self) -> str:
        return "multi_match"


@dataclasses.dataclass
class PrefixQuery(QueryNode):
    """Reference: PrefixQueryBuilder (constant-score rewrite)."""

    field: str = ""
    value: str = ""

    def query_name(self) -> str:
        return "prefix"


@dataclasses.dataclass
class WildcardQuery(QueryNode):
    """Reference: WildcardQueryBuilder — `*` any run, `?` one char
    (constant-score rewrite)."""

    field: str = ""
    value: str = ""
    case_insensitive: bool = False

    def query_name(self) -> str:
        return "wildcard"


@dataclasses.dataclass
class FuzzyQuery(QueryNode):
    """Reference: FuzzyQueryBuilder — terms within edit distance
    (Damerau-Levenshtein, transpositions count 1) of the value."""

    field: str = ""
    value: str = ""
    fuzziness: Any = "AUTO"       # "AUTO" | 0 | 1 | 2
    prefix_length: int = 0
    max_expansions: int = 50

    def query_name(self) -> str:
        return "fuzzy"


@dataclasses.dataclass
class ScoreFunction:
    """One entry of function_score.functions (reference:
    ScoreFunctionBuilder): optional filter + one scoring primitive."""

    filter_query: Optional[QueryNode] = None
    weight: Optional[float] = None
    field_value_factor: Optional[Dict[str, Any]] = None
    script_score: Optional[Any] = None  # CompiledScript


@dataclasses.dataclass
class ScriptScoreQuery(QueryNode):
    """{"script_score": {"query": ..., "script": ...}} — replace the
    base query's score with a script over doc values and `_score`
    (reference: ScriptScoreQueryBuilder; evaluated VECTORIZED here —
    one array program over all candidates, SURVEY.md §2.1#42)."""

    query: QueryNode = None  # type: ignore[assignment]
    script: Any = None       # CompiledScript
    min_score: Optional[float] = None

    def query_name(self) -> str:
        return "script_score"


@dataclasses.dataclass
class FunctionScoreQuery(QueryNode):
    """Reference: FunctionScoreQueryBuilder — combine the base query's
    score with per-doc function values."""

    query: QueryNode = None  # type: ignore[assignment]
    functions: List[ScoreFunction] = dataclasses.field(default_factory=list)
    score_mode: str = "multiply"  # multiply|sum|avg|max|min
    boost_mode: str = "multiply"  # multiply|sum|replace|avg|max|min
    max_boost: Optional[float] = None

    def query_name(self) -> str:
        return "function_score"


@dataclasses.dataclass
class RankFeatureQuery(QueryNode):
    """{"rank_feature": {"field": f, "saturation"|"log"|"sigmoid"|
    "linear": {...}}} — score docs by a stored feature value
    (reference: mapper-extras RankFeatureQueryBuilder; SURVEY.md
    §2.1#54). Default function: saturation with an index-derived
    pivot."""

    field: str = ""
    function: str = "saturation"   # saturation | log | sigmoid | linear
    pivot: Optional[float] = None  # saturation/sigmoid
    scaling_factor: Optional[float] = None  # log
    exponent: Optional[float] = None        # sigmoid

    def query_name(self) -> str:
        return "rank_feature"


@dataclasses.dataclass
class GeoDistanceQuery(QueryNode):
    """{"geo_distance": {"distance": "12km", "<field>": point}} —
    haversine radius filter on a geo_point column (reference:
    GeoDistanceQueryBuilder; SURVEY.md §2.1#55)."""

    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance_m: float = 0.0

    def query_name(self) -> str:
        return "geo_distance"


@dataclasses.dataclass
class GeoBoundingBoxQuery(QueryNode):
    """{"geo_bounding_box": {"<field>": {"top_left": ..,
    "bottom_right": ..}}} (reference: GeoBoundingBoxQueryBuilder)."""

    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0

    def query_name(self) -> str:
        return "geo_bounding_box"


@dataclasses.dataclass
class PercolateQuery(QueryNode):
    """{"percolate": {"field": f, "document": {...}}} — match the
    stored-query docs whose query matches the document(s) (reference:
    modules/percolator PercolateQueryBuilder; SURVEY.md §2.1#52)."""

    field: str = ""
    documents: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    def query_name(self) -> str:
        return "percolate"


@dataclasses.dataclass
class KnnScoreDocQuery(QueryNode):
    """The coordinator-rewritten form of a `knn` search clause
    (reference: KnnScoreDocQueryBuilder): the GLOBAL top-k winners of
    the candidate phase, pinned to exact (segment, ord, score) triples
    for ONE shard. Unioned with the text query: matching docs score
    query_score + Σ knn_score·boost (the reference's hybrid rule).
    Never parsed from JSON — built by search/knn.py."""

    query: Optional[QueryNode] = None
    # one {segment_name: (ords i64[], scores f32[])} map per knn clause
    doc_sets: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    boosts: List[float] = dataclasses.field(default_factory=list)

    def query_name(self) -> str:
        return "knn_score_doc"


@dataclasses.dataclass
class BoolQuery(QueryNode):
    must: List[QueryNode] = dataclasses.field(default_factory=list)
    should: List[QueryNode] = dataclasses.field(default_factory=list)
    must_not: List[QueryNode] = dataclasses.field(default_factory=list)
    filter: List[QueryNode] = dataclasses.field(default_factory=list)
    minimum_should_match: Optional[int] = None

    def query_name(self) -> str:
        return "bool"


@dataclasses.dataclass
class ConstantScoreQuery(QueryNode):
    filter_query: QueryNode = None  # type: ignore[assignment]

    def query_name(self) -> str:
        return "constant_score"


@dataclasses.dataclass
class NestedQuery(QueryNode):
    """{"nested": {"path": p, "query": {...}, "score_mode": m}} —
    per-OBJECT matching against a nested field's objects (reference:
    NestedQueryBuilder; SURVEY.md §2.1#29)."""

    path: str = ""
    query: QueryNode = None  # type: ignore[assignment]
    score_mode: str = "avg"  # avg | sum | min | max | none

    def query_name(self) -> str:
        return "nested"


def parse_query(obj: Dict[str, Any]) -> QueryNode:
    """The parseInnerQueryBuilder analog: one top-level key names the query."""
    if not isinstance(obj, dict):
        raise ParsingException(f"query must be an object, got {type(obj).__name__}")
    if len(obj) != 1:
        raise ParsingException(
            f"query object must have exactly one key, got {sorted(obj.keys())}")
    name, body = next(iter(obj.items()))
    parser = _PARSERS.get(name)
    if parser is None:
        raise ParsingException(f"unknown query type [{name}]")
    return parser(body)


def _field_and_params(name: str, body: Dict[str, Any], value_key: str):
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(f"[{name}] expects a single field")
    field, spec = next(iter(body.items()))
    if isinstance(spec, dict):
        if value_key not in spec:
            raise ParsingException(f"[{name}] on [{field}] requires [{value_key}]")
        return field, spec
    return field, {value_key: spec}


def _parse_match(body) -> MatchQuery:
    field, spec = _field_and_params("match", body, "query")
    op = str(spec.get("operator", "or")).lower()
    if op not in ("or", "and"):
        raise ParsingException(f"[match] unknown operator [{op}]")
    msm = spec.get("minimum_should_match")
    return MatchQuery(field=field, query=str(spec["query"]), operator=op,
                      minimum_should_match=None if msm is None else int(msm),
                      boost=float(spec.get("boost", 1.0)))


def _parse_match_phrase(body) -> MatchPhraseQuery:
    field, spec = _field_and_params("match_phrase", body, "query")
    return MatchPhraseQuery(field=field, query=str(spec["query"]),
                            slop=int(spec.get("slop", 0)),
                            boost=float(spec.get("boost", 1.0)))


def _parse_term(body) -> TermQuery:
    field, spec = _field_and_params("term", body, "value")
    return TermQuery(field=field, value=spec["value"],
                     boost=float(spec.get("boost", 1.0)))


def _parse_terms(body) -> TermsQuery:
    if not isinstance(body, dict):
        raise ParsingException("[terms] expects an object")
    boost = float(body.get("boost", 1.0))
    fields = {k: v for k, v in body.items() if k != "boost"}
    if len(fields) != 1:
        raise ParsingException("[terms] expects a single field")
    field, values = next(iter(fields.items()))
    if not isinstance(values, list):
        raise ParsingException(f"[terms] on [{field}] expects an array")
    return TermsQuery(field=field, values=values, boost=boost)


def _parse_range(body) -> RangeQuery:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException("[range] expects a single field")
    field, spec = next(iter(body.items()))
    if not isinstance(spec, dict):
        raise ParsingException(f"[range] on [{field}] expects an object")
    known = {"gt", "gte", "lt", "lte", "boost", "format", "time_zone",
             "relation"}
    unknown = set(spec) - known
    if unknown:
        raise ParsingException(f"[range] unknown parameter {sorted(unknown)}")
    relation = spec.get("relation")
    if relation is not None and str(relation).lower() not in (
            "intersects", "within", "contains"):
        raise ParsingException(f"[range] unknown relation [{relation}]")
    return RangeQuery(field=field, gt=spec.get("gt"), gte=spec.get("gte"),
                      lt=spec.get("lt"), lte=spec.get("lte"),
                      relation=None if relation is None
                      else str(relation).lower(),
                      boost=float(spec.get("boost", 1.0)))


def _parse_bool(body) -> BoolQuery:
    if not isinstance(body, dict):
        raise ParsingException("[bool] expects an object")
    q = BoolQuery(boost=float(body.get("boost", 1.0)))
    for clause in ("must", "should", "must_not", "filter"):
        items = body.get(clause, [])
        if isinstance(items, dict):
            items = [items]
        if not isinstance(items, list):
            raise ParsingException(f"[bool] [{clause}] must be an array or object")
        setattr(q, "filter" if clause == "filter" else clause,
                [parse_query(x) for x in items])
    msm = body.get("minimum_should_match")
    if msm is not None:
        q.minimum_should_match = int(msm)
    known = {"must", "should", "must_not", "filter", "minimum_should_match", "boost"}
    unknown = set(body) - known
    if unknown:
        raise ParsingException(f"[bool] unknown parameter {sorted(unknown)}")
    return q


def _parse_match_all(body) -> MatchAllQuery:
    body = body or {}
    return MatchAllQuery(boost=float(body.get("boost", 1.0)))


def _parse_exists(body) -> ExistsQuery:
    if not isinstance(body, dict) or "field" not in body:
        raise ParsingException("[exists] requires [field]")
    return ExistsQuery(field=str(body["field"]))


def _parse_ids(body) -> IdsQuery:
    if not isinstance(body, dict) or "values" not in body:
        raise ParsingException("[ids] requires [values]")
    return IdsQuery(values=[str(v) for v in body["values"]])


def _parse_constant_score(body) -> ConstantScoreQuery:
    if not isinstance(body, dict) or "filter" not in body:
        raise ParsingException("[constant_score] requires [filter]")
    return ConstantScoreQuery(filter_query=parse_query(body["filter"]),
                              boost=float(body.get("boost", 1.0)))


def _parse_nested(body) -> NestedQuery:
    if not isinstance(body, dict) or "path" not in body \
            or "query" not in body:
        raise ParsingException("[nested] requires [path] and [query]")
    mode = str(body.get("score_mode", "avg")).lower()
    if mode not in ("avg", "sum", "min", "max", "none"):
        raise ParsingException(f"[nested] unknown score_mode [{mode}]")
    return NestedQuery(path=str(body["path"]),
                       query=parse_query(body["query"]),
                       score_mode=mode,
                       boost=float(body.get("boost", 1.0)))


def _parse_multi_match(body) -> MultiMatchQuery:
    if not isinstance(body, dict) or "query" not in body:
        raise ParsingException("[multi_match] requires [query]")
    raw_fields = body.get("fields")
    if not raw_fields or not isinstance(raw_fields, list):
        raise ParsingException("[multi_match] requires [fields]")
    fields = []
    for f in raw_fields:
        name, _, boost = str(f).partition("^")
        try:
            fields.append((name, float(boost) if boost else 1.0))
        except ValueError:
            raise ParsingException(
                f"[multi_match] bad field boost in [{f}]") from None
    mm_type = str(body.get("type", "best_fields"))
    if mm_type not in ("best_fields", "most_fields"):
        raise ParsingException(
            f"[multi_match] unsupported type [{mm_type}] (best_fields and "
            f"most_fields are available)")
    op = str(body.get("operator", "or")).lower()
    if op not in ("or", "and"):
        raise ParsingException(f"[multi_match] unknown operator [{op}]")
    msm = body.get("minimum_should_match")
    known = {"query", "fields", "type", "operator", "minimum_should_match",
             "tie_breaker", "boost"}
    unknown = set(body) - known
    if unknown:
        raise ParsingException(
            f"[multi_match] unknown parameter {sorted(unknown)}")
    return MultiMatchQuery(
        fields=fields, query=str(body["query"]), type=mm_type, operator=op,
        minimum_should_match=None if msm is None else int(msm),
        tie_breaker=float(body.get("tie_breaker", 0.0)),
        boost=float(body.get("boost", 1.0)))


def _parse_prefix(body) -> PrefixQuery:
    field, spec = _field_and_params("prefix", body, "value")
    return PrefixQuery(field=field, value=str(spec["value"]),
                       boost=float(spec.get("boost", 1.0)))


def _parse_wildcard(body) -> WildcardQuery:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException("[wildcard] expects a single field")
    field, spec = next(iter(body.items()))
    if not isinstance(spec, dict):
        spec = {"value": spec}
    value = spec.get("value", spec.get("wildcard"))
    if value is None:
        raise ParsingException(f"[wildcard] on [{field}] requires [value]")
    return WildcardQuery(field=field, value=str(value),
                         case_insensitive=bool(
                             spec.get("case_insensitive", False)),
                         boost=float(spec.get("boost", 1.0)))


def _parse_fuzzy(body) -> FuzzyQuery:
    field, spec = _field_and_params("fuzzy", body, "value")
    fuzziness = spec.get("fuzziness", "AUTO")
    if isinstance(fuzziness, str) and fuzziness.upper() != "AUTO":
        try:
            fuzziness = int(fuzziness)
        except ValueError:
            raise ParsingException(
                f"[fuzzy] bad fuzziness [{fuzziness}]") from None
    if isinstance(fuzziness, int) and fuzziness not in (0, 1, 2):
        raise ParsingException("[fuzzy] fuzziness must be AUTO, 0, 1 or 2")
    return FuzzyQuery(field=field, value=str(spec["value"]),
                      fuzziness=fuzziness,
                      prefix_length=int(spec.get("prefix_length", 0)),
                      max_expansions=int(spec.get("max_expansions", 50)),
                      boost=float(spec.get("boost", 1.0)))


def _parse_function_score(body) -> FunctionScoreQuery:
    if not isinstance(body, dict):
        raise ParsingException("[function_score] expects an object")
    base = parse_query(body["query"]) if "query" in body \
        else MatchAllQuery()

    def parse_fn(obj) -> ScoreFunction:
        known = {"filter", "weight", "field_value_factor",
                 "script_score"}
        unknown = set(obj) - known
        if unknown:
            raise ParsingException(
                f"[function_score] unsupported function parameter "
                f"{sorted(unknown)} (filter/weight/field_value_factor/"
                f"script_score are available)")
        script = None
        if obj.get("script_score") is not None:
            spec = obj["script_score"]
            if not isinstance(spec, dict) or "script" not in spec:
                raise ParsingException(
                    "[script_score] requires a [script]")
            from elasticsearch_tpu.script import (ScriptException,
                                                  compile_script)
            try:
                script = compile_script(spec["script"])
            except ScriptException as e:
                raise ParsingException(str(e)) from None
        fvf = obj.get("field_value_factor")
        if fvf is not None:
            if "field" not in fvf:
                raise ParsingException(
                    "[field_value_factor] requires [field]")
            mod = str(fvf.get("modifier", "none"))
            if mod not in ("none", "log", "log1p", "log2p", "ln", "ln1p",
                           "ln2p", "square", "sqrt", "reciprocal"):
                raise ParsingException(
                    f"[field_value_factor] unknown modifier [{mod}]")
            for num_key in ("factor", "missing"):
                if fvf.get(num_key) is not None:
                    try:
                        float(fvf[num_key])
                    except (TypeError, ValueError):
                        raise ParsingException(
                            f"[field_value_factor] [{num_key}] must be "
                            f"numeric, got [{fvf[num_key]}]") from None
        if obj.get("weight") is None and fvf is None and script is None:
            raise ParsingException(
                "[function_score] function needs [weight], "
                "[field_value_factor], or [script_score]")
        return ScoreFunction(
            filter_query=(parse_query(obj["filter"])
                          if "filter" in obj else None),
            weight=(None if obj.get("weight") is None
                    else float(obj["weight"])),
            field_value_factor=fvf,
            script_score=script)

    functions: List[ScoreFunction] = []
    if "functions" in body:
        if not isinstance(body["functions"], list):
            raise ParsingException("[function_score] [functions] must be "
                                   "an array")
        functions = [parse_fn(f) for f in body["functions"]]
    else:
        shorthand = {k: body[k] for k in ("weight", "field_value_factor",
                                          "script_score")
                     if k in body}
        if shorthand:
            functions = [parse_fn(shorthand)]
    for mode_key, default in (("score_mode", "multiply"),
                              ("boost_mode", "multiply")):
        mode = str(body.get(mode_key, default))
        allowed = {"multiply", "sum", "avg", "max", "min"}
        if mode_key == "boost_mode":
            allowed = allowed | {"replace"}
        if mode not in allowed:
            raise ParsingException(
                f"[function_score] unknown {mode_key} [{mode}]")
    known = {"query", "functions", "weight", "field_value_factor",
             "script_score", "score_mode", "boost_mode", "max_boost",
             "boost"}
    unknown = set(body) - known
    if unknown:
        raise ParsingException(
            f"[function_score] unknown parameter {sorted(unknown)}")
    return FunctionScoreQuery(
        query=base, functions=functions,
        score_mode=str(body.get("score_mode", "multiply")),
        boost_mode=str(body.get("boost_mode", "multiply")),
        max_boost=(None if body.get("max_boost") is None
                   else float(body["max_boost"])),
        boost=float(body.get("boost", 1.0)))


DISTANCE_UNITS_M = {
    "mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
    "in": 0.0254, "ft": 0.3048, "yd": 0.9144,
    "mi": 1609.344, "miles": 1609.344, "nmi": 1852.0, "NM": 1852.0,
}


def parse_distance_m(spec: Any) -> float:
    """Distance grammar "12km"/"5mi"/number-of-meters (reference:
    DistanceUnit#parse)."""
    if isinstance(spec, (int, float)) and not isinstance(spec, bool):
        return float(spec)
    s = str(spec).strip()
    m = re.fullmatch(r"([\d.]+)\s*([a-zA-Z]*)", s)
    if not m:
        raise ParsingException(f"failed to parse distance [{spec}]")
    value = float(m.group(1))
    unit = m.group(2) or "m"
    factor = DISTANCE_UNITS_M.get(unit)
    if factor is None:
        raise ParsingException(f"unknown distance unit [{unit}]")
    return value * factor


def _parse_rank_feature(body) -> RankFeatureQuery:
    if not isinstance(body, dict) or "field" not in body:
        raise ParsingException("[rank_feature] requires [field]")
    fns = [k for k in ("saturation", "log", "sigmoid", "linear")
           if k in body]
    if len(fns) > 1:
        raise ParsingException(
            f"[rank_feature] can only have one function, got {fns}")
    unknown = set(body) - {"field", "boost", "saturation", "log",
                           "sigmoid", "linear"}
    if unknown:
        raise ParsingException(
            f"[rank_feature] unknown parameter {sorted(unknown)}")
    fn = fns[0] if fns else "saturation"
    spec = body.get(fn) or {}
    q = RankFeatureQuery(field=str(body["field"]), function=fn,
                         boost=float(body.get("boost", 1.0)))
    if fn == "saturation" and spec.get("pivot") is not None:
        q.pivot = float(spec["pivot"])
    if fn == "log":
        if spec.get("scaling_factor") is None:
            raise ParsingException(
                "[rank_feature] [log] requires [scaling_factor]")
        q.scaling_factor = float(spec["scaling_factor"])
    if fn == "sigmoid":
        if spec.get("pivot") is None or spec.get("exponent") is None:
            raise ParsingException(
                "[rank_feature] [sigmoid] requires [pivot] and "
                "[exponent]")
        q.pivot = float(spec["pivot"])
        q.exponent = float(spec["exponent"])
    return q


def _parse_geo_distance(body) -> GeoDistanceQuery:
    if not isinstance(body, dict) or "distance" not in body:
        raise ParsingException("[geo_distance] requires [distance]")
    dist = parse_distance_m(body["distance"])
    field = None
    point = None
    for k, v in body.items():
        if k in ("distance", "distance_type", "validation_method",
                 "boost", "_name"):
            continue
        if field is not None:
            raise ParsingException(
                f"[geo_distance] only one field allowed, got "
                f"[{field}] and [{k}]")
        field, point = k, v
    if field is None:
        raise ParsingException("[geo_distance] requires a field point")
    from elasticsearch_tpu.mapping.types import GeoPointFieldType
    try:
        lat, lon = GeoPointFieldType.parse_point(point)
    except Exception as e:  # noqa: BLE001 — mapper error → parse error
        raise ParsingException(str(e)) from None
    return GeoDistanceQuery(field=field, lat=lat, lon=lon,
                            distance_m=dist,
                            boost=float(body.get("boost", 1.0)))


def _parse_geo_bounding_box(body) -> GeoBoundingBoxQuery:
    if not isinstance(body, dict):
        raise ParsingException("[geo_bounding_box] expects an object")
    field = None
    spec = None
    for k, v in body.items():
        if k in ("validation_method", "type", "boost", "_name"):
            continue
        if field is not None:
            raise ParsingException(
                "[geo_bounding_box] only one field allowed")
        field, spec = k, v
    if field is None or not isinstance(spec, dict):
        raise ParsingException(
            "[geo_bounding_box] requires a field with corner points")
    from elasticsearch_tpu.mapping.types import GeoPointFieldType
    try:
        if "top_left" in spec and "bottom_right" in spec:
            top, left = GeoPointFieldType.parse_point(spec["top_left"])
            bottom, right = GeoPointFieldType.parse_point(
                spec["bottom_right"])
        elif all(k in spec for k in ("top", "left", "bottom", "right")):
            top, left = float(spec["top"]), float(spec["left"])
            bottom, right = float(spec["bottom"]), float(spec["right"])
        else:
            raise ParsingException(
                "[geo_bounding_box] requires [top_left]+[bottom_right] "
                "or [top]/[left]/[bottom]/[right]")
    except ParsingException:
        raise
    except Exception as e:  # noqa: BLE001
        raise ParsingException(str(e)) from None
    if bottom > top:
        raise ParsingException(
            f"[geo_bounding_box] top [{top}] must be >= bottom "
            f"[{bottom}]")
    return GeoBoundingBoxQuery(field=field, top=top, left=left,
                               bottom=bottom, right=right,
                               boost=float(body.get("boost", 1.0)))


def _parse_percolate(body) -> PercolateQuery:
    if not isinstance(body, dict) or not body.get("field"):
        raise ParsingException("[percolate] requires [field]")
    unknown = set(body) - {"field", "document", "documents", "boost",
                           "_name"}
    if unknown:
        raise ParsingException(
            f"[percolate] unknown parameter {sorted(unknown)}")
    if ("document" in body) == ("documents" in body):
        raise ParsingException(
            "[percolate] requires exactly one of [document] or "
            "[documents]")
    docs = body.get("documents", [body.get("document")])
    if not isinstance(docs, list) or not docs or not all(
            isinstance(d, dict) for d in docs):
        raise ParsingException(
            "[percolate] [documents] must be a non-empty array of "
            "objects")
    return PercolateQuery(field=str(body["field"]), documents=docs,
                          boost=float(body.get("boost", 1.0)))


def _parse_script_score(body) -> ScriptScoreQuery:
    if not isinstance(body, dict):
        raise ParsingException("[script_score] expects an object")
    if "query" not in body:
        raise ParsingException("[script_score] requires a [query]")
    if "script" not in body:
        raise ParsingException("[script_score] requires a [script]")
    unknown = set(body) - {"query", "script", "min_score", "boost"}
    if unknown:
        raise ParsingException(
            f"[script_score] unknown parameter {sorted(unknown)}")
    from elasticsearch_tpu.script import ScriptException, compile_script
    try:
        script = compile_script(body["script"])
    except ScriptException as e:
        raise ParsingException(str(e)) from None
    return ScriptScoreQuery(
        query=parse_query(body["query"]), script=script,
        min_score=(None if body.get("min_score") is None
                   else float(body["min_score"])),
        boost=float(body.get("boost", 1.0)))


_PARSERS = {
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "bool": _parse_bool,
    "match_all": _parse_match_all,
    "exists": _parse_exists,
    "ids": _parse_ids,
    "nested": _parse_nested,
    "constant_score": _parse_constant_score,
    "multi_match": _parse_multi_match,
    "prefix": _parse_prefix,
    "wildcard": _parse_wildcard,
    "fuzzy": _parse_fuzzy,
    "function_score": _parse_function_score,
    "script_score": _parse_script_score,
    "rank_feature": _parse_rank_feature,
    "geo_distance": _parse_geo_distance,
    "geo_bounding_box": _parse_geo_bounding_box,
    "percolate": _parse_percolate,
}
