"""Percolator — inverted search: index QUERIES as documents, then ask
which stored queries match a given document.

Reference: `modules/percolator` (PercolateQueryBuilder, QueryAnalyzer,
the `percolator` mapper field — SURVEY.md §2.1#52). Kept contracts:
the `percolator` mapping type validates and stores a query; the
{"percolate": {"field": f, "document": {...}}} query matches the docs
whose stored query matches the document; `documents` (plural) matches
when ANY of them does.

Divergences (documented): the reference extracts terms from stored
queries into hidden fields so a candidate pre-filter skips most
non-matching queries; this build evaluates every live stored query
against the percolated document (parsed queries are cached per
segment). Brute force is O(stored queries) per percolate call — fine
for alerting-sized query sets; the pre-filter is an optimization seam,
not a semantic one. The reference's `_percolator_document_slot`
response field (which of the plural documents matched per hit) is not
emitted: multi-document percolation matches on ANY document.
"""

from __future__ import annotations

from typing import Any, Dict, List

from elasticsearch_tpu.common.errors import IllegalArgumentException


def build_doc_reader(mapper, documents: List[Dict[str, Any]]):
    """The percolated documents as a tiny in-memory index, parsed by an
    ISOLATED CLONE of the index's mapper (same analyzers/field types as
    if indexed — the reference's MemoryIndex). A clone, because
    parse_document applies dynamic-mapping updates: a read-only search
    must never mutate the live index mapping, and the doc-values kind
    table must include any dynamically-added fields of the document."""
    from elasticsearch_tpu.index.reader import ShardReader
    from elasticsearch_tpu.index.segment import SegmentWriter
    from elasticsearch_tpu.mapping.mapper import MapperService
    clone = MapperService(mapper.index_settings, mapper.to_mapping())
    writer = SegmentWriter("_percolate_docs")
    for slot, document in enumerate(documents):
        if not isinstance(document, dict):
            raise IllegalArgumentException(
                "[percolate] [document] must be an object")
        parsed = clone.parse_document(f"_slot_{slot}", document)
        # kinds re-read per doc: dynamic mapping may have added fields
        writer.add_document(parsed, clone.dv_kinds())
    segment = writer.freeze()
    return ShardReader([(segment, None)], clone)


def segment_parsed_queries(segment, field: str):
    """Parsed query cache per (segment, field): stored queries are
    immutable once a segment freezes, so each parses once."""
    cache = getattr(segment, "_percolator_cache", None)
    if cache is None:
        cache = {}
        segment._percolator_cache = cache
    entry = cache.get(field)
    if entry is None:
        from elasticsearch_tpu.ingest import get_field
        from elasticsearch_tpu.search import dsl
        entry = {}
        for ord_ in range(segment.num_docs):
            src = segment.stored_source[ord_] or {}
            # literal dotted key first (the flat {"a.b": ...} source
            # form), then dotted traversal (object-nested form)
            spec = src.get(field)
            if spec is None:
                spec = get_field(src, field)
            if spec is None:
                continue
            try:
                entry[ord_] = dsl.parse_query(spec)
            except Exception:  # noqa: BLE001 — validated at index
                continue  # time; an unparsable survivor just no-matches
        cache[field] = entry
    return entry
