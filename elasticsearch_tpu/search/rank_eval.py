"""Ranking-quality evaluation — the rank-eval module.

Reference: `modules/rank-eval` (SURVEY.md §2.1#50): given rated
(query, document) pairs and a metric, run each query and score the
ranking. Metric definitions mirror the reference classes:

  precision@k     PrecisionAtK — |relevant ∩ top-k| / |retrieved ∩ top-k|
  recall@k        RecallAtK — |relevant ∩ top-k| / |relevant|
  mrr@k           MeanReciprocalRank — 1/rank of first relevant hit
  dcg@k / ndcg@k  DiscountedCumulativeGain — Σ (2^rel − 1)/log2(rank+1),
                  normalized by the ideal ordering when `normalize`
  err@k           ExpectedReciprocalRank — cascade model

REST: POST /{index}/_rank_eval with the reference's request shape
(`requests: [{id, request, ratings}]`, `metric: {<name>: {...}}`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException


# ---------------------------------------------------------------------------
# metric math (rating lists are in ranked order, None = unrated)
# ---------------------------------------------------------------------------

def precision_at_k(ratings: Sequence[Optional[int]], k: int,
                   relevant_rating_threshold: int = 1,
                   ignore_unlabeled: bool = False) -> float:
    top = list(ratings[:k])
    if ignore_unlabeled:
        top = [r for r in top if r is not None]
    if not top:
        return 0.0
    rel = sum(1 for r in top
              if r is not None and r >= relevant_rating_threshold)
    return rel / len(top)


def recall_at_k(ratings: Sequence[Optional[int]], k: int,
                total_relevant: int,
                relevant_rating_threshold: int = 1) -> float:
    if total_relevant <= 0:
        return 0.0
    rel = sum(1 for r in ratings[:k]
              if r is not None and r >= relevant_rating_threshold)
    return rel / total_relevant


def reciprocal_rank(ratings: Sequence[Optional[int]], k: int,
                    relevant_rating_threshold: int = 1) -> float:
    for i, r in enumerate(ratings[:k]):
        if r is not None and r >= relevant_rating_threshold:
            return 1.0 / (i + 1)
    return 0.0


def dcg_at_k(ratings: Sequence[Optional[int]], k: int) -> float:
    """Reference DiscountedCumulativeGain: (2^rel − 1) / log2(rank + 1),
    unrated docs contribute 0."""
    out = 0.0
    for i, r in enumerate(ratings[:k]):
        if r is not None and r > 0:
            out += (2.0**r - 1.0) / math.log2(i + 2)
    return out


def ndcg_at_k(ratings: Sequence[Optional[int]], k: int,
              all_ratings: Optional[Sequence[int]] = None) -> float:
    """all_ratings: every known rating for the query (for the ideal DCG);
    defaults to the observed ratings."""
    dcg = dcg_at_k(ratings, k)
    pool = [r for r in (all_ratings if all_ratings is not None else ratings)
            if r is not None and r > 0]
    ideal = dcg_at_k(sorted(pool, reverse=True), k)
    return dcg / ideal if ideal > 0 else 0.0


def err_at_k(ratings: Sequence[Optional[int]], k: int,
             max_rating: Optional[int] = None) -> float:
    """ExpectedReciprocalRank cascade model (Chapelle et al., as in the
    reference's ExpectedReciprocalRank)."""
    rated = [r or 0 for r in ratings[:k]]
    if max_rating is None:
        max_rating = max(rated, default=0)
    if max_rating <= 0:
        return 0.0
    p_continue = 1.0
    err = 0.0
    for i, r in enumerate(rated):
        useful = (2.0**r - 1.0) / (2.0**max_rating)
        err += p_continue * useful / (i + 1)
        p_continue *= 1.0 - useful
    return err


# ---------------------------------------------------------------------------
# request evaluation
# ---------------------------------------------------------------------------

_METRICS = {"precision", "recall", "mean_reciprocal_rank", "dcg",
            "expected_reciprocal_rank"}


def evaluate(search_fn, body: Dict[str, Any]) -> Dict[str, Any]:
    """search_fn(request_body) → search response dict. `body` is the
    reference-shaped rank_eval request."""
    requests = body.get("requests")
    if not requests:
        raise IllegalArgumentException("[rank_eval] requires [requests]")
    metric_spec = body.get("metric")
    if not isinstance(metric_spec, dict) or len(metric_spec) != 1:
        raise IllegalArgumentException(
            "[rank_eval] requires exactly one [metric]")
    metric_name, opts = next(iter(metric_spec.items()))
    if metric_name not in _METRICS:
        raise IllegalArgumentException(
            f"[rank_eval] unknown metric [{metric_name}]")
    opts = opts or {}
    k = int(opts.get("k", 10))
    threshold = int(opts.get("relevant_rating_threshold", 1))

    details = {}
    scores = []
    for req in requests:
        rid = req.get("id")
        if rid is None:
            raise IllegalArgumentException("[rank_eval] request needs [id]")
        ratings_by_doc: Dict[Tuple[Optional[str], str], int] = {}
        for r in req.get("ratings", []):
            ratings_by_doc[(r.get("_index"), r["_id"])] = int(r["rating"])
        search_body = dict(req.get("request") or {})
        search_body.setdefault("size", max(k, 10))
        resp = search_fn(search_body)
        hits = resp["hits"]["hits"]
        ranked: List[Optional[int]] = []
        hit_details = []
        for h in hits:
            key = (h.get("_index"), h["_id"])
            rating = ratings_by_doc.get(key,
                                        ratings_by_doc.get((None, h["_id"])))
            ranked.append(rating)
            hit_details.append({"hit": {"_index": h.get("_index"),
                                        "_id": h["_id"],
                                        "_score": h.get("_score")},
                                "rating": rating})
        all_ratings = list(ratings_by_doc.values())
        if metric_name == "precision":
            score = precision_at_k(ranked, k, threshold,
                                   bool(opts.get("ignore_unlabeled")))
        elif metric_name == "recall":
            total_rel = sum(1 for r in all_ratings if r >= threshold)
            score = recall_at_k(ranked, k, total_rel, threshold)
        elif metric_name == "mean_reciprocal_rank":
            score = reciprocal_rank(ranked, k, threshold)
        elif metric_name == "dcg":
            score = (ndcg_at_k(ranked, k, all_ratings)
                     if opts.get("normalize") else dcg_at_k(ranked, k))
        else:  # expected_reciprocal_rank
            score = err_at_k(ranked, k, opts.get("maximum_relevance"))
        unrated = sum(1 for r in ranked if r is None)
        details[rid] = {"metric_score": score, "unrated_docs": unrated,
                        "hits": hit_details}
        scores.append(score)

    return {
        "metric_score": sum(scores) / len(scores) if scores else 0.0,
        "details": details,
        "failures": {},
    }
