"""Suggesters — the term suggester.

Reference: `search/suggest/term/TermSuggester` + `DirectSpellChecker`
(SURVEY.md §2.1#50). Kept contracts: the request grammar
({"suggest": {name: {"text", "term": {"field", ...}}}}), per-token
response entries with offset/length, candidates scored by edit
distance then doc frequency, `suggest_mode` (missing | popular |
always), `max_edits`, `prefix_length`, `min_word_length`, `size`.

Candidate generation scans the shard term dictionaries with the same
banded Damerau-Levenshtein the fuzzy query uses — one vocabulary pass
per (token, shard), no per-doc work.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException

_TOKEN = re.compile(r"\w+", re.UNICODE)


def _bounded_distance(a: str, b: str, k: int):
    """Damerau-Levenshtein distance if ≤ k, else None — ONE banded DP
    pass (the candidate loop's hot function)."""
    if a == b:
        return 0
    if abs(len(a) - len(b)) > k:
        return None
    prev2 = None
    prev = list(range(len(b) + 1))
    for i in range(1, len(a) + 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j in range(1, len(b) + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (prev2 is not None and i > 1 and j > 1
                    and a[i - 1] == b[j - 2] and a[i - 2] == b[j - 1]):
                d = min(d, prev2[j - 2] + 1)
            cur[j] = d
            row_min = min(row_min, d)
        if row_min > k:
            return None
        prev2, prev = prev, cur
    return prev[len(b)] if prev[len(b)] <= k else None


class TermSuggestSpec:
    kind = "term"

    def __init__(self, name: str, body: Dict[str, Any]):
        self.name = name
        self.text = body.get("text")
        term = body.get("term")
        if self.text is None or not isinstance(term, dict):
            raise IllegalArgumentException(
                f"suggester [{name}] requires [text] and [term]")
        self.field = term.get("field")
        if not self.field:
            raise IllegalArgumentException(
                f"suggester [{name}] requires [term.field]")
        self.size = int(term.get("size", 5))
        self.max_edits = int(term.get("max_edits", 2))
        if self.max_edits not in (1, 2):
            raise IllegalArgumentException(
                "[term] max_edits must be 1 or 2")
        self.prefix_length = int(term.get("prefix_length", 1))
        self.min_word_length = int(term.get("min_word_length", 4))
        self.suggest_mode = str(term.get("suggest_mode", "missing"))
        if self.suggest_mode not in ("missing", "popular", "always"):
            raise IllegalArgumentException(
                f"[term] unknown suggest_mode [{self.suggest_mode}]")


class PhraseSuggestSpec:
    """Reference: PhraseSuggester — whole-phrase corrections built from
    per-token candidates, scored by candidate confidence × doc
    frequency; `max_errors` bounds how many tokens may change;
    `highlight` wraps changed tokens."""

    kind = "phrase"

    def __init__(self, name: str, body: Dict[str, Any]):
        self.name = name
        self.text = body.get("text")
        spec = body.get("phrase")
        if self.text is None or not isinstance(spec, dict):
            raise IllegalArgumentException(
                f"suggester [{name}] requires [text] and [phrase]")
        self.field = spec.get("field")
        if not self.field:
            raise IllegalArgumentException(
                f"phrase suggester [{name}] requires [field]")
        self.size = int(spec.get("size", 5))
        self.max_errors = float(spec.get("max_errors", 1.0))
        self.max_edits = 2
        hl = spec.get("highlight") or {}
        self.pre_tag = hl.get("pre_tag", "")
        self.post_tag = hl.get("post_tag", "")


class CompletionSuggestSpec:
    """Reference: CompletionSuggester over a `completion` field —
    prefix lookup of stored inputs, weight-ranked."""

    kind = "completion"

    def __init__(self, name: str, body: Dict[str, Any]):
        self.name = name
        self.prefix = body.get("prefix", body.get("text"))
        spec = body.get("completion")
        if self.prefix is None or not isinstance(spec, dict):
            raise IllegalArgumentException(
                f"suggester [{name}] requires [prefix] and [completion]")
        self.field = spec.get("field")
        if not self.field:
            raise IllegalArgumentException(
                f"completion suggester [{name}] requires [field]")
        self.size = int(spec.get("size", 5))
        self.skip_duplicates = bool(spec.get("skip_duplicates", False))


def parse_suggest(body: Dict[str, Any]) -> List[Any]:
    if not isinstance(body, dict):
        raise IllegalArgumentException("[suggest] must be an object")
    specs: List[Any] = []
    global_text = body.get("text")
    for name, spec in body.items():
        if name == "text":
            continue
        if not isinstance(spec, dict):
            raise IllegalArgumentException(
                f"suggester [{name}] must be an object")
        if "text" not in spec and "prefix" not in spec \
                and global_text is not None:
            spec = dict(spec, text=global_text)
        if "term" in spec:
            specs.append(TermSuggestSpec(name, spec))
        elif "phrase" in spec:
            specs.append(PhraseSuggestSpec(name, spec))
        elif "completion" in spec:
            specs.append(CompletionSuggestSpec(name, spec))
        else:
            raise IllegalArgumentException(
                f"suggester [{name}]: one of [term], [phrase], "
                f"[completion] is required")
    return specs


def _field_frequencies(indices, names: List[str], field: str,
                       shard_filter=None) -> Dict[str, int]:
    """term → doc frequency across the TARGET shards' term dicts.
    shard_filter: {index: iterable of shard nums} — required in cluster
    groups so unassigned local copies aren't double-counted in the
    cross-node merge."""
    freqs: Dict[str, int] = {}
    for name in names:
        svc = indices.index(name)
        wanted = (None if shard_filter is None
                  else set(shard_filter.get(name, ())))
        for num, shard in sorted(svc.shards.items()):
            if wanted is not None and num not in wanted:
                continue
            reader = shard.acquire_searcher()
            for view in reader.views:
                fp = view.pack.fields.get(field)
                if fp is None:
                    continue
                for term, row in fp.vocab.items():
                    freqs[term] = freqs.get(term, 0) + int(
                        fp.doc_freq[row])
    return freqs


def run_suggest(indices, names: List[str],
                body: Dict[str, Any],
                shard_filter=None) -> Dict[str, Any]:
    specs = parse_suggest(body)
    out: Dict[str, Any] = {}
    freq_cache: Dict[str, Dict[str, int]] = {}

    def freqs_for(field: str) -> Dict[str, int]:
        f = freq_cache.get(field)
        if f is None:
            f = _field_frequencies(indices, names, field, shard_filter)
            freq_cache[field] = f
        return f

    for spec in specs:
        if spec.kind == "completion":
            out[spec.name] = _run_completion(indices, names, spec,
                                             shard_filter)
            continue
        if spec.kind == "phrase":
            out[spec.name] = _run_phrase(freqs_for(spec.field), spec)
            continue
        freqs = freqs_for(spec.field)
        entries = []
        for m in _TOKEN.finditer(str(spec.text)):
            token = m.group(0).lower()
            entry = {"text": token, "offset": m.start(),
                     "length": m.end() - m.start(), "options": []}
            exists = freqs.get(token, 0) > 0
            skip = (
                len(token) < spec.min_word_length
                or (spec.suggest_mode == "missing" and exists))
            if not skip:
                options = _candidates(token, freqs, spec)
                entry["options"] = options
            entries.append(entry)
        out[spec.name] = entries
    return out


def _run_phrase(freqs: Dict[str, int],
                spec: PhraseSuggestSpec) -> List[Dict[str, Any]]:
    """Beam over per-token candidates (the token itself + close terms),
    scored by Π token confidence·log-df; at most `max_errors` tokens
    change (fraction when < 1, absolute otherwise — reference rule)."""
    import math
    text = str(spec.text)
    matches = list(_TOKEN.finditer(text))
    tokens = [m.group(0).lower() for m in matches]
    if not tokens:
        return [{"text": text, "offset": 0, "length": len(text),
                 "options": []}]
    max_changes = (max(1, int(round(spec.max_errors * len(tokens))))
                   if spec.max_errors < 1.0 else int(spec.max_errors))

    shim = TermSuggestSpec("_", {"text": "", "term": {"field": spec.field,
                                                      "size": 3}})
    per_token: List[List[Tuple[str, float, bool]]] = []
    for tok in tokens:
        df = freqs.get(tok, 0)
        own_conf = 1.0 if df > 0 else 0.05
        opts = [(tok, own_conf * math.log1p(df + 1), False)]
        for cand in _candidates(tok, freqs, shim):
            opts.append((cand["text"],
                         cand["score"] * math.log1p(cand["freq"] + 1),
                         True))
        per_token.append(opts)

    beams: List[Tuple[List[str], int, float]] = [([], 0, 0.0)]
    for opts in per_token:
        nxt = []
        for terms, changes, score in beams:
            for term, s, changed in opts:
                c = changes + (1 if changed else 0)
                if c > max_changes:
                    continue
                nxt.append((terms + [term], c, score + s))
        nxt.sort(key=lambda b: -b[2])
        beams = nxt[:20]

    options = []
    seen = set()
    for terms, changes, score in beams:
        if changes == 0:
            continue  # the input itself is not a suggestion
        phrase = " ".join(terms)
        if phrase in seen:
            continue
        seen.add(phrase)
        opt = {"text": phrase,
               "score": round(score / max(1, len(terms)), 6)}
        if spec.pre_tag or spec.post_tag:
            opt["highlighted"] = " ".join(
                f"{spec.pre_tag}{t}{spec.post_tag}" if t != tokens[i]
                else t for i, t in enumerate(terms))
        options.append(opt)
    options.sort(key=lambda o: (-o["score"], o["text"]))
    return [{"text": text, "offset": 0, "length": len(text),
             "options": options[: spec.size]}]


def _run_completion(indices, names: List[str],
                    spec: CompletionSuggestSpec,
                    shard_filter=None) -> List[Dict[str, Any]]:
    """Prefix lookup over the completion field's ordinal tables (sorted
    unique inputs per segment → binary search), weight-ranked."""
    import bisect

    import numpy as np

    from elasticsearch_tpu.mapping.types import CompletionFieldType
    prefix = str(spec.prefix)
    best: Dict[str, float] = {}
    for name in names:
        svc = indices.index(name)
        wanted = (None if shard_filter is None
                  else set(shard_filter.get(name, ())))
        for num, shard in sorted(svc.shards.items()):
            if wanted is not None and num not in wanted:
                continue
            reader = shard.acquire_searcher()
            for view in reader.views:
                pack = view.pack
                terms = pack.dv_ord_terms.get(spec.field)
                col = pack.dv_ord.get(spec.field)
                if not terms or col is None:
                    continue
                # ordinal range of prefix matches: scan from the left
                # bound while startswith (no string sentinel — a non-BMP
                # next char would sort past any BMP sentinel)
                lo = bisect.bisect_left(terms, prefix)
                hi = lo
                while hi < len(terms) and terms[hi].startswith(prefix):
                    hi += 1
                if lo >= hi:
                    continue
                wcol = pack.dv_i64.get(
                    spec.field + CompletionFieldType.WEIGHT_SUFFIX)
                live = view.live_mask
                seg_col = np.asarray(col)
                n = len(seg_col)
                warr = None if wcol is None else np.asarray(wcol)

                def record(ord_idx: int, doc: int) -> None:
                    w = 1.0 if warr is None else float(warr[doc])
                    t = terms[ord_idx]
                    if t not in best or w > best[t]:
                        best[t] = w

                # one pass over the column for all matching ordinals
                in_range = ((seg_col >= lo) & (seg_col < hi)
                            & live[:n])
                for doc in np.nonzero(in_range)[0].tolist():
                    record(int(seg_col[doc]), doc)
                # multi-input docs keep extras in the segment column
                dv = view.segment.doc_values.get(spec.field)
                if dv is not None and dv.extra:
                    for d, extra in dv.extra.items():
                        if d < len(live) and live[d]:
                            for eo in extra:
                                if lo <= eo < hi:
                                    record(int(eo), d)
    options = [{"text": t, "score": s} for t, s in best.items()]
    options.sort(key=lambda o: (-o["score"], o["text"]))
    return [{"text": prefix, "offset": 0, "length": len(prefix),
             "options": options[: spec.size]}]


def merge_suggest(specs: List[TermSuggestSpec],
                  partials: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-node reduce: per token, merge candidate options by text
    (summing doc freqs, keeping the best score), re-sort, cut to size
    (reference: the suggest phase's reduce)."""
    out: Dict[str, Any] = {}
    by_name = {s.name: s for s in specs}
    for name in by_name:
        merged_entries: Dict[Tuple[str, int], Dict[str, Any]] = {}
        order: List[Tuple[str, int]] = []
        for part in partials:
            for entry in part.get(name, []):
                key = (entry["text"], entry["offset"])
                cur = merged_entries.get(key)
                if cur is None:
                    cur = {"text": entry["text"],
                           "offset": entry["offset"],
                           "length": entry["length"], "options": {}}
                    merged_entries[key] = cur
                    order.append(key)
                for opt in entry["options"]:
                    existing = cur["options"].get(opt["text"])
                    if existing is None:
                        cur["options"][opt["text"]] = dict(opt)
                    else:
                        if "freq" in opt:
                            existing["freq"] = existing.get("freq", 0) \
                                + opt["freq"]
                        existing["score"] = max(existing["score"],
                                                opt["score"])
        size = by_name[name].size
        out[name] = []
        for key in order:
            entry = merged_entries[key]
            options = sorted(entry["options"].values(),
                             key=lambda o: (-o["score"],
                                            -o.get("freq", 0),
                                            o["text"]))[: size]
            out[name].append({"text": entry["text"],
                              "offset": entry["offset"],
                              "length": entry["length"],
                              "options": options})
    return out


def _candidates(token: str, freqs: Dict[str, int],
                spec: TermSuggestSpec) -> List[Dict[str, Any]]:
    prefix = token[: spec.prefix_length]
    token_freq = freqs.get(token, 0)
    scored: List[Tuple[float, int, str]] = []
    for term, df in freqs.items():
        if term == token or df <= 0:
            continue
        if spec.prefix_length and not term.startswith(prefix):
            continue
        if abs(len(term) - len(token)) > spec.max_edits:
            continue
        if spec.suggest_mode == "popular" and df <= token_freq:
            continue
        dist = _bounded_distance(token, term, spec.max_edits)
        if dist is not None:
            # reference scoring shape: closer edits first, then
            # higher doc frequency
            scored.append((1.0 - dist / max(len(token), 1), df, term))
    scored.sort(key=lambda t: (-t[0], -t[1], t[2]))
    return [{"text": term, "score": round(score, 6), "freq": df}
            for score, df, term in scored[: spec.size]]
