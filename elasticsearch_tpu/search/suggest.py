"""Suggesters — the term suggester.

Reference: `search/suggest/term/TermSuggester` + `DirectSpellChecker`
(SURVEY.md §2.1#50). Kept contracts: the request grammar
({"suggest": {name: {"text", "term": {"field", ...}}}}), per-token
response entries with offset/length, candidates scored by edit
distance then doc frequency, `suggest_mode` (missing | popular |
always), `max_edits`, `prefix_length`, `min_word_length`, `size`.

Candidate generation scans the shard term dictionaries with the same
banded Damerau-Levenshtein the fuzzy query uses — one vocabulary pass
per (token, shard), no per-doc work.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException

_TOKEN = re.compile(r"\w+", re.UNICODE)


def _bounded_distance(a: str, b: str, k: int):
    """Damerau-Levenshtein distance if ≤ k, else None — ONE banded DP
    pass (the candidate loop's hot function)."""
    if a == b:
        return 0
    if abs(len(a) - len(b)) > k:
        return None
    prev2 = None
    prev = list(range(len(b) + 1))
    for i in range(1, len(a) + 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j in range(1, len(b) + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (prev2 is not None and i > 1 and j > 1
                    and a[i - 1] == b[j - 2] and a[i - 2] == b[j - 1]):
                d = min(d, prev2[j - 2] + 1)
            cur[j] = d
            row_min = min(row_min, d)
        if row_min > k:
            return None
        prev2, prev = prev, cur
    return prev[len(b)] if prev[len(b)] <= k else None


class TermSuggestSpec:
    def __init__(self, name: str, body: Dict[str, Any]):
        self.name = name
        self.text = body.get("text")
        term = body.get("term")
        if self.text is None or not isinstance(term, dict):
            raise IllegalArgumentException(
                f"suggester [{name}] requires [text] and [term]")
        self.field = term.get("field")
        if not self.field:
            raise IllegalArgumentException(
                f"suggester [{name}] requires [term.field]")
        self.size = int(term.get("size", 5))
        self.max_edits = int(term.get("max_edits", 2))
        if self.max_edits not in (1, 2):
            raise IllegalArgumentException(
                "[term] max_edits must be 1 or 2")
        self.prefix_length = int(term.get("prefix_length", 1))
        self.min_word_length = int(term.get("min_word_length", 4))
        self.suggest_mode = str(term.get("suggest_mode", "missing"))
        if self.suggest_mode not in ("missing", "popular", "always"):
            raise IllegalArgumentException(
                f"[term] unknown suggest_mode [{self.suggest_mode}]")


def parse_suggest(body: Dict[str, Any]) -> List[TermSuggestSpec]:
    if not isinstance(body, dict):
        raise IllegalArgumentException("[suggest] must be an object")
    specs = []
    global_text = body.get("text")
    for name, spec in body.items():
        if name == "text":
            continue
        if not isinstance(spec, dict):
            raise IllegalArgumentException(
                f"suggester [{name}] must be an object")
        if "term" not in spec:
            raise IllegalArgumentException(
                f"suggester [{name}]: only the [term] suggester is "
                f"supported")
        if "text" not in spec and global_text is not None:
            spec = dict(spec, text=global_text)
        specs.append(TermSuggestSpec(name, spec))
    return specs


def _field_frequencies(indices, names: List[str], field: str,
                       shard_filter=None) -> Dict[str, int]:
    """term → doc frequency across the TARGET shards' term dicts.
    shard_filter: {index: iterable of shard nums} — required in cluster
    groups so unassigned local copies aren't double-counted in the
    cross-node merge."""
    freqs: Dict[str, int] = {}
    for name in names:
        svc = indices.index(name)
        wanted = (None if shard_filter is None
                  else set(shard_filter.get(name, ())))
        for num, shard in sorted(svc.shards.items()):
            if wanted is not None and num not in wanted:
                continue
            reader = shard.acquire_searcher()
            for view in reader.views:
                fp = view.pack.fields.get(field)
                if fp is None:
                    continue
                for term, row in fp.vocab.items():
                    freqs[term] = freqs.get(term, 0) + int(
                        fp.doc_freq[row])
    return freqs


def run_suggest(indices, names: List[str],
                body: Dict[str, Any],
                shard_filter=None) -> Dict[str, Any]:
    specs = parse_suggest(body)
    out: Dict[str, Any] = {}
    freq_cache: Dict[str, Dict[str, int]] = {}
    for spec in specs:
        freqs = freq_cache.get(spec.field)
        if freqs is None:
            freqs = _field_frequencies(indices, names, spec.field,
                                       shard_filter)
            freq_cache[spec.field] = freqs
        entries = []
        for m in _TOKEN.finditer(str(spec.text)):
            token = m.group(0).lower()
            entry = {"text": token, "offset": m.start(),
                     "length": m.end() - m.start(), "options": []}
            exists = freqs.get(token, 0) > 0
            skip = (
                len(token) < spec.min_word_length
                or (spec.suggest_mode == "missing" and exists))
            if not skip:
                options = _candidates(token, freqs, spec)
                entry["options"] = options
            entries.append(entry)
        out[spec.name] = entries
    return out


def merge_suggest(specs: List[TermSuggestSpec],
                  partials: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-node reduce: per token, merge candidate options by text
    (summing doc freqs, keeping the best score), re-sort, cut to size
    (reference: the suggest phase's reduce)."""
    out: Dict[str, Any] = {}
    by_name = {s.name: s for s in specs}
    for name in by_name:
        merged_entries: Dict[Tuple[str, int], Dict[str, Any]] = {}
        order: List[Tuple[str, int]] = []
        for part in partials:
            for entry in part.get(name, []):
                key = (entry["text"], entry["offset"])
                cur = merged_entries.get(key)
                if cur is None:
                    cur = {"text": entry["text"],
                           "offset": entry["offset"],
                           "length": entry["length"], "options": {}}
                    merged_entries[key] = cur
                    order.append(key)
                for opt in entry["options"]:
                    existing = cur["options"].get(opt["text"])
                    if existing is None:
                        cur["options"][opt["text"]] = dict(opt)
                    else:
                        existing["freq"] += opt["freq"]
                        existing["score"] = max(existing["score"],
                                                opt["score"])
        size = by_name[name].size
        out[name] = []
        for key in order:
            entry = merged_entries[key]
            options = sorted(entry["options"].values(),
                             key=lambda o: (-o["score"], -o["freq"],
                                            o["text"]))[: size]
            out[name].append({"text": entry["text"],
                              "offset": entry["offset"],
                              "length": entry["length"],
                              "options": options})
    return out


def _candidates(token: str, freqs: Dict[str, int],
                spec: TermSuggestSpec) -> List[Dict[str, Any]]:
    prefix = token[: spec.prefix_length]
    token_freq = freqs.get(token, 0)
    scored: List[Tuple[float, int, str]] = []
    for term, df in freqs.items():
        if term == token or df <= 0:
            continue
        if spec.prefix_length and not term.startswith(prefix):
            continue
        if abs(len(term) - len(token)) > spec.max_edits:
            continue
        if spec.suggest_mode == "popular" and df <= token_freq:
            continue
        dist = _bounded_distance(token, term, spec.max_edits)
        if dist is not None:
            # reference scoring shape: closer edits first, then
            # higher doc frequency
            scored.append((1.0 - dist / max(len(token), 1), df, term))
    scored.sort(key=lambda t: (-t[0], -t[1], t[2]))
    return [{"text": term, "score": round(score, 6), "freq": df}
            for score, df, term in scored[: spec.size]]
