"""can_match prefilter — skip shards that cannot possibly match.

Reference: `CanMatchPreFilterSearchPhase` + `MinAndMax` field stats
(SURVEY.md §2.1#35): before the query phase fans out, shards whose
numeric/date field ranges are disjoint with the query's range clauses are
skipped entirely and reported in `_shards.skipped`. Here the per-shard
stats are min/max over each segment's doc-values column (computed lazily,
cached on the segment — the pack-manifest analog of Lucene's
PointValues#getMinPackedValue)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from elasticsearch_tpu.index.segment import MISSING_I64
from elasticsearch_tpu.search import dsl


def _segment_minmax(seg, field: str) -> Optional[Tuple[float, float]]:
    """(min, max) of a numeric dv column over ALL docs in the segment
    (tombstones included — that only widens the range, never causing a
    wrong skip). None ⇒ no values at all."""
    cache = getattr(seg, "_minmax_cache", None)
    if cache is None:
        cache = {}
        seg._minmax_cache = cache
    if field in cache:
        return cache[field]
    col = seg.doc_values.get(field)
    out: Optional[Tuple[float, float]] = None
    if col is not None and col.kind in ("i64", "f64"):
        vals = col.values
        mask = (vals != MISSING_I64) if col.kind == "i64" \
            else ~np.isnan(vals)
        lo = hi = None
        if mask.any():
            lo = float(vals[mask].min())
            hi = float(vals[mask].max())
        for extras in col.extra.values():
            for v in extras:
                f = float(v)
                lo = f if lo is None else min(lo, f)
                hi = f if hi is None else max(hi, f)
        if lo is not None:
            out = (lo, hi)
    cache[field] = out
    return out


def _shard_minmax(reader, field: str) -> Optional[Tuple[float, float]]:
    lo = hi = None
    for view in reader.views:
        mm = _segment_minmax(view.segment, field)
        if mm is None:
            continue
        lo = mm[0] if lo is None else min(lo, mm[0])
        hi = mm[1] if hi is None else max(hi, mm[1])
    return None if lo is None else (lo, hi)


def _numeric_ft(mapper, field: str):
    ft = mapper.field_type(field)
    if ft is None or getattr(ft, "dv_kind", "none") not in ("i64", "f64"):
        return None
    if not getattr(ft, "has_doc_values", False):
        return None  # doc_values:false → no column stats; postings may
        # still match, so never skip on their absence
    return ft


def can_match(reader, query: dsl.QueryNode, mapper) -> bool:
    """False ⇒ the shard DEFINITELY has no matching doc (safe to skip);
    True ⇒ unknown, run the query phase. Conservative on everything the
    walker doesn't model."""
    return _walk(reader, query, mapper)


def _walk(reader, node: dsl.QueryNode, mapper) -> bool:
    if isinstance(node, dsl.RangeQuery):
        ft = _numeric_ft(mapper, node.field)
        if ft is None:
            return True  # keyword/text ranges: no stats modeled
        mm = _shard_minmax(reader, node.field)
        if mm is None:
            return False  # no doc on this shard has the field
        lo, hi = mm
        try:
            if node.gt is not None and \
                    float(ft.normalize_range_bound(node.gt)) >= hi:
                return False
            if node.gte is not None and \
                    float(ft.normalize_range_bound(node.gte)) > hi:
                return False
            if node.lt is not None and \
                    float(ft.normalize_range_bound(node.lt)) <= lo:
                return False
            if node.lte is not None and \
                    float(ft.normalize_range_bound(node.lte)) < lo:
                return False
        except Exception:  # unparseable bound: the query phase will 400
            return True
        return True
    if isinstance(node, dsl.TermQuery):
        ft = _numeric_ft(mapper, node.field)
        if ft is None:
            return True
        mm = _shard_minmax(reader, node.field)
        if mm is None:
            return False
        try:
            v = float(ft.normalize_range_bound(node.value))
        except Exception:
            return True
        return mm[0] <= v <= mm[1]
    if isinstance(node, dsl.ConstantScoreQuery):
        return _walk(reader, node.filter_query, mapper)
    if isinstance(node, dsl.BoolQuery):
        for q in list(node.must) + list(node.filter):
            if not _walk(reader, q, mapper):
                return False
        if node.should and not node.must and not node.filter:
            # pure should (msm ≥ 1): all clauses impossible ⇒ no match
            if not any(_walk(reader, q, mapper) for q in node.should):
                return False
        return True
    return True
