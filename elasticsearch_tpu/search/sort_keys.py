"""Pure-python sort-spec grammar + comparable-key construction, split
out of `search.sort` so coordinator *merge* code can run in processes
that must never import the device stack (`search.sort` pulls
`index.segment` → ops → jax at import time; serving fronts and merge
workers route through this module instead).

Everything here is stdlib-only and byte-for-byte the same semantics the
in-process coordinator merge has always used: `search.sort` re-exports
these names, so existing imports keep working.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Sequence, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException


@dataclasses.dataclass
class SortSpec:
    field: str                      # field name | "_score" | "_doc"
    order: str = "asc"              # "asc" | "desc"
    missing: Any = "_last"          # "_last" | "_first" | literal value


def parse_sort(spec: Any) -> List[SortSpec]:
    """Reference grammar (FieldSortBuilder#fromXContent)."""
    if spec is None:
        return []
    if not isinstance(spec, list):
        spec = [spec]
    out: List[SortSpec] = []
    for entry in spec:
        if isinstance(entry, str):
            default = "desc" if entry == "_score" else "asc"
            out.append(SortSpec(entry, default))
        elif isinstance(entry, dict):
            if len(entry) != 1:
                raise IllegalArgumentException(
                    "[sort] entry must name exactly one field")
            field, opts = next(iter(entry.items()))
            if isinstance(opts, str):
                opts = {"order": opts}
            if not isinstance(opts, dict):
                raise IllegalArgumentException(
                    f"[sort] malformed options for [{field}]")
            order = opts.get("order", "desc" if field == "_score" else "asc")
            if order not in ("asc", "desc"):
                raise IllegalArgumentException(
                    f"[sort] unknown order [{order}]")
            out.append(SortSpec(field, order, opts.get("missing", "_last")))
        else:
            raise IllegalArgumentException("[sort] malformed sort entry")
    return out


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and math.isnan(v):
        return True
    return False


def _element_key(spec: SortSpec, v: Any) -> Tuple:
    """Ascending-comparable key for one sort element honoring order +
    missing placement. Shape: (missing_rank, direction-adjusted value)."""
    if _is_missing(v):
        if spec.missing == "_first":
            return (0, 0)
        if spec.missing == "_last":
            return (2, 0)
        v = spec.missing  # literal replacement value
    if isinstance(v, str):
        # strings can't negate: desc uses an inverted-codepoint key
        key: Any = v if spec.order == "asc" else _invert_str(v)
    else:
        key = v if spec.order == "asc" else -float(v)
    return (1, key)


def _invert_str(s: str) -> Tuple:
    return tuple(-ord(c) for c in s) + (float("inf"),)


def sort_key(specs: Sequence[SortSpec], values: Sequence[Any]) -> Tuple:
    return tuple(_element_key(s, v) for s, v in zip(specs, values))
