"""Query rescorer — second-pass re-ranking of the top window.

Reference: `search/rescore/QueryRescorer` + `RescorerBuilder`
(SURVEY.md §2.1#50): each rescore entry re-scores the shard's top
`window_size` hits with a (usually more expensive) query; matched hits
combine `query_weight·original ⊕ rescore_query_weight·secondary` by
`score_mode` (total/multiply/avg/max/min), unmatched hits keep
`query_weight·original`. Entries chain in order; only the window
re-sorts — ranks below it are untouched."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.search import dsl

SCORE_MODES = ("total", "multiply", "avg", "max", "min")


@dataclasses.dataclass
class RescoreSpec:
    window_size: int
    query: dsl.QueryNode
    query_weight: float = 1.0
    rescore_query_weight: float = 1.0
    score_mode: str = "total"

    def combine(self, orig: float, matched: bool, secondary: float) -> float:
        q = self.query_weight * orig
        if not matched:
            return q
        r = self.rescore_query_weight * secondary
        if self.score_mode == "total":
            return q + r
        if self.score_mode == "multiply":
            return q * r
        if self.score_mode == "avg":
            return (q + r) / 2.0
        if self.score_mode == "max":
            return max(q, r)
        return min(q, r)


def parse_rescore(spec: Any) -> List[RescoreSpec]:
    entries = spec if isinstance(spec, list) else [spec]
    out: List[RescoreSpec] = []
    for entry in entries:
        if not isinstance(entry, dict) or "query" not in entry:
            raise IllegalArgumentException("[rescore] requires [query]")
        q = entry["query"]
        if not isinstance(q, dict) or "rescore_query" not in q:
            raise IllegalArgumentException(
                "[rescore] requires [query.rescore_query]")
        mode = str(q.get("score_mode", "total")).lower()
        if mode not in SCORE_MODES:
            raise IllegalArgumentException(
                f"[rescore] unknown score_mode [{mode}]")
        out.append(RescoreSpec(
            window_size=int(entry.get("window_size", 10)),
            query=dsl.parse_query(q["rescore_query"]),
            query_weight=float(q.get("query_weight", 1.0)),
            rescore_query_weight=float(q.get("rescore_query_weight", 1.0)),
            score_mode=mode))
    return out


def rescore_shard_hits(reader, hits: List, specs: List[RescoreSpec]
                       ) -> List:
    """Apply the rescore chain to one shard's query-phase hits (best
    first). Each spec evaluates its query ONCE per touched segment —
    dense mask algebra, same as the query planner — then combines and
    re-sorts the window."""
    from elasticsearch_tpu.search.planner import SegmentQueryExecutor
    if not hits:
        return hits
    seg_index = {v.segment.name: i for i, v in enumerate(reader.views)}
    for spec in specs:
        window = hits[: spec.window_size]
        needed = sorted({h.ref.segment for h in window
                         if h.ref.segment in seg_index})
        masks: Dict[str, np.ndarray] = {}
        scores: Dict[str, np.ndarray] = {}
        for seg_name in needed:
            executor = SegmentQueryExecutor(reader, seg_index[seg_name])
            m, s = executor.execute(spec.query)
            masks[seg_name] = np.asarray(m)
            scores[seg_name] = np.asarray(s)
        for h in window:
            m = masks.get(h.ref.segment)
            matched = bool(m[h.ref.ord]) if m is not None else False
            secondary = float(scores[h.ref.segment][h.ref.ord]) \
                if matched else 0.0
            h.score = spec.combine(h.score, matched, secondary)
        window.sort(key=lambda h: (-h.score, h.doc_id))
        hits = window + hits[spec.window_size:]
    return hits
