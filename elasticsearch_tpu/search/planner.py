"""Query planner/executor: DSL AST → kernel programs per segment.

Reference analog: index/query/QueryShardContext#toQuery + the per-segment
execution in search/query/QueryPhase#executeInternal (SURVEY.md §3.3). The
reference walks postings doc-at-a-time through BooleanScorer/ConjunctionDISI;
here every node of the query tree evaluates densely over the segment's
padded doc axis:

  node → (match_mask bool[d_pad], score f32[d_pad])

with the invariant that `score` is already zeroed outside `match_mask`.
Parent nodes combine children by mask algebra + score addition, which
reproduces Lucene's boolean scoring semantics (sum of matched scoring
clauses) without per-doc control flow — and makes nested conjunctive
subtrees in should-context safe by construction (SURVEY.md §7.3#7).

Scoring leaves launch one score_and_mask kernel per leaf (terms padded to
power-of-two buckets to bound the jit cache, §7.3#1). Phrase verification
is host-side over the candidate docs (postings positions live on host).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import QueryShardException
from elasticsearch_tpu.index.reader import SegmentView, ShardReader
from elasticsearch_tpu.index.segment import MISSING_I64
from elasticsearch_tpu.mapping.types import (
    FieldType,
    IpFieldType,
    KeywordFieldType,
    NumberFieldType,
    RangeFieldType,
    TextFieldType,
)
from elasticsearch_tpu.ops import bm25, sparse
from elasticsearch_tpu.ops.smallfloat import bm25_norm_cache
from elasticsearch_tpu.search import dsl
# re-exported for batcher-side callers; the implementation lives in the
# import-light plan_sig module because the serving-front processes (which
# must never pull in JAX) sign request bodies with the same function
from elasticsearch_tpu.search.plan_sig import (  # noqa: F401
    canonical_body, wire_plan_signature)

MAX_SLOTS_PER_PASS = 32


def choose_kernel_variant(d_pad: int,
                          weights: Optional[np.ndarray] = None,
                          enabled: bool = True,
                          compressed: bool = False,
                          pallas: bool = False) -> str:
    """Pick the device-kernel variant for one lowered pack/batch.

    Lowering-time decision (PERF.md round 8): "packed" — the single
    uint32-key sort + hierarchical top-k + exact-f32 rescore — whenever
    the pack's doc axis and the batch's slot weights fit the 16-bit
    packed layout (sparse.packable); otherwise the exact-f32 reference
    kernel. The fallback conditions are the documented overflow cases:
    d_pad ≥ 2^16 chunk-local doc ids, non-finite/negative weights, or
    weight magnitudes outside [1e-12, 1e30] (where the monotone 16-bit
    impact code could turn a positive contribution into code 0 and
    perturb TotalHits).

    compressed=True (the resident pack holds only the 16-bit streams,
    PERF.md round 11): the same packable() predicate decides between
    "compressed" (quantized sort keys + block-max pruning, needs the
    monotone lower-bound guarantee on weights) and "compressed_exact"
    (per-lane residual-table decode then the exact-f32 pipeline — the
    automatic fallback for weights that would violate the bound). A
    compressed pack has no f32 posting copy, so "ref"/"packed" are not
    reachable from it.

    pallas=True (PR 15): prefer the fused Pallas spelling of the
    compressed pipeline — one kernel for gather, merge, in-kernel
    block-max skip and top-k, bit-identical to "compressed". It has the
    same packable() requirement, so the fallback chain stays typed:
    pallas unavailable (jaxlib without the pallas extra) or weights not
    packable → the same "compressed"/"compressed_exact" choice as
    pallas=False. Never errors."""
    if compressed:
        if sparse.packable(d_pad, weights):
            if pallas:
                from elasticsearch_tpu.ops import pallas_merge
                if pallas_merge.available():
                    return "pallas"
            return "compressed"
        return "compressed_exact"
    if enabled and sparse.packable(d_pad, weights):
        return "packed"
    return "ref"


def _edit_distance_lte(a: str, b: str, k: int) -> bool:
    """Damerau-Levenshtein (adjacent transposition = 1) ≤ k, banded with
    early exit (reference: Lucene's LevenshteinAutomata accept set for
    fuzziness ≤ 2)."""
    if k == 0:
        return a == b
    if abs(len(a) - len(b)) > k:
        return False
    prev2: Optional[List[int]] = None
    prev = list(range(len(b) + 1))
    for i in range(1, len(a) + 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j in range(1, len(b) + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (prev2 is not None and i > 1 and j > 1
                    and a[i - 1] == b[j - 2] and a[i - 2] == b[j - 1]):
                d = min(d, prev2[j - 2] + 1)
            cur[j] = d
            row_min = min(row_min, d)
        if row_min > k:
            return False
        prev2, prev = prev, cur
    return prev[len(b)] <= k


def _bucket(n: int, minimum: int = 1) -> int:
    """Round up to a power of two (jit-cache bounding, SURVEY.md §7.3#1)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def _analyzed_terms(ft, text) -> list:
    """Analyze `text` through the field's search analyzer, memoized on
    the FieldType instance. One query over an index re-analyzes the same
    string once per segment view (and repeated query shapes re-analyze
    it once per request); the memo collapses that to one analyzer run.
    It lives on the FieldType, so a mapping update (which swaps the
    FieldType) naturally drops it. Returns a fresh list — callers may
    mutate their copy."""
    text = str(text)
    memo = getattr(ft, "_terms_memo", None)
    if memo is None:
        memo = {}
        try:
            ft._terms_memo = memo
        except AttributeError:  # slotted/frozen field type: no memo
            return ft.search_terms(text)
    hit = memo.get(text)
    if hit is None:
        hit = ft.search_terms(text)
        if len(memo) < 4096:  # bound pathological query cardinality
            memo[text] = hit
    return list(hit)


class SegmentQueryExecutor:
    """Evaluates one parsed query against one segment view."""

    def __init__(self, reader: ShardReader, view_idx: int):
        self.reader = reader
        self.view_idx = view_idx
        self.view: SegmentView = reader.views[view_idx]
        self.d_pad = self.view.pack.d_pad

    # -------------- public --------------

    def execute(self, node: dsl.QueryNode) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """→ (mask bool[d_pad], score f32[d_pad]); score zero off-mask."""
        return self._eval(node, scoring=True)

    # -------------- recursive eval --------------

    def _eval(self, node: dsl.QueryNode, scoring: bool):
        if isinstance(node, dsl.MatchAllQuery):
            mask = jnp.ones(self.d_pad, dtype=bool)
            score = jnp.full(self.d_pad, node.boost if scoring else 0.0,
                             dtype=jnp.float32)
            return mask, score
        if isinstance(node, dsl.MatchQuery):
            return self._eval_match(node, scoring)
        if isinstance(node, dsl.TermQuery):
            try:
                ft = self._field_type(node.field)
            except _UnmappedField:
                ft = None
            if isinstance(ft, IpFieldType) and "/" in str(node.value):
                # CIDR term → address range (reference: IpFieldMapper
                # term queries accept networks)
                lo, hi = IpFieldType.cidr_bounds(node.value)
                return self._eval_ip_range(node.field, lo, hi, node.boost)
            if isinstance(ft, RangeFieldType):
                v = ft.parse_bound(node.value)
                return self._eval_range_field(
                    dsl.RangeQuery(field=node.field, gte=v, lte=v,
                                   boost=node.boost), ft)
            return self._eval_terms(node.field, [node.value], node.boost,
                                    scoring, "or", 1)
        if isinstance(node, dsl.TermsQuery):
            return self._eval_terms(node.field, node.values, node.boost,
                                    scoring, "or", 1)
        if isinstance(node, dsl.RangeQuery):
            return self._eval_range(node)
        if isinstance(node, dsl.ExistsQuery):
            mask = jnp.asarray(self.reader.has_field_mask(self.view_idx, node.field))
            return mask, jnp.where(mask, node.boost if scoring else 0.0, 0.0).astype(jnp.float32)
        if isinstance(node, dsl.IdsQuery):
            mask = jnp.asarray(self.reader.resolve_ids(self.view_idx, node.values))
            return mask, jnp.where(mask, node.boost if scoring else 0.0, 0.0).astype(jnp.float32)
        if isinstance(node, dsl.MatchPhraseQuery):
            return self._eval_phrase(node, scoring)
        if isinstance(node, dsl.ConstantScoreQuery):
            mask, _ = self._eval(node.filter_query, scoring=False)
            return mask, jnp.where(mask, node.boost if scoring else 0.0, 0.0).astype(jnp.float32)
        if isinstance(node, dsl.BoolQuery):
            return self._eval_bool(node, scoring)
        if isinstance(node, dsl.MultiMatchQuery):
            return self._eval_multi_match(node, scoring)
        if isinstance(node, dsl.PrefixQuery):
            return self._eval_expanded_terms(
                node.field, self._expand_prefix(node.field, node.value),
                node.boost, scoring, constant=True)
        if isinstance(node, dsl.WildcardQuery):
            return self._eval_expanded_terms(
                node.field, self._expand_wildcard(node), node.boost,
                scoring, constant=True)
        if isinstance(node, dsl.FuzzyQuery):
            return self._eval_expanded_terms(
                node.field, self._expand_fuzzy(node), node.boost,
                scoring, constant=False)
        if isinstance(node, dsl.FunctionScoreQuery):
            return self._eval_function_score(node, scoring)
        if isinstance(node, dsl.ScriptScoreQuery):
            return self._eval_script_score(node, scoring)
        if isinstance(node, dsl.KnnScoreDocQuery):
            return self._eval_knn_score_doc(node, scoring)
        if isinstance(node, dsl.RankFeatureQuery):
            return self._eval_rank_feature(node, scoring)
        if isinstance(node, dsl.GeoDistanceQuery):
            return self._eval_geo_distance(node)
        if isinstance(node, dsl.GeoBoundingBoxQuery):
            return self._eval_geo_bbox(node)
        if isinstance(node, dsl.NestedQuery):
            return self._eval_nested(node, scoring)
        if isinstance(node, dsl.PercolateQuery):
            return self._eval_percolate(node, scoring)
        if hasattr(node, "evaluate"):
            # plugin-registered query types evaluate themselves against
            # the executor (SearchPlugin#getQueries seam)
            return node.evaluate(self, scoring)
        raise QueryShardException(f"unsupported query [{node.query_name()}]")

    def _eval_multi_match(self, node: dsl.MultiMatchQuery, scoring: bool):
        """best_fields: per doc, the best field's score (+ tie_breaker ×
        the rest); most_fields: sum. Mask is the OR of the field masks
        (reference: DisjunctionMaxQuery vs a should-bool)."""
        per_field = []
        for field, fboost in node.fields:
            sub = dsl.MatchQuery(
                field=field, query=node.query, operator=node.operator,
                minimum_should_match=node.minimum_should_match,
                boost=fboost)
            per_field.append(self._eval_match(sub, scoring))
        if not per_field:
            return self._none()
        mask = per_field[0][0]
        for m, _ in per_field[1:]:
            mask = mask | m
        scores = jnp.stack([s for _, s in per_field])
        if node.type == "most_fields":
            score = jnp.sum(scores, axis=0)
        else:  # best_fields
            best = jnp.max(scores, axis=0)
            score = best + node.tie_breaker * (jnp.sum(scores, axis=0)
                                               - best)
        score = jnp.where(mask, score * node.boost, 0.0)
        return mask, score

    # ---- multi-term expansion (reference: MultiTermQuery rewrites) ----

    _MAX_EXPANSIONS = 1024  # reference: indices.query.bool.max_clause_count

    def _field_vocab(self, field: str):
        fp = self.view.pack.fields.get(field)
        return fp.vocab if fp is not None else {}

    def _expand_prefix(self, field: str, prefix: str) -> List[str]:
        terms = [t for t in self._field_vocab(field)
                 if t.startswith(prefix)]
        self._check_expansion(terms, "prefix")
        return terms

    def _expand_wildcard(self, node: dsl.WildcardQuery) -> List[str]:
        import fnmatch
        pattern = node.value.lower() if node.case_insensitive \
            else node.value
        # fnmatchcase: only * and ? are wildcards in the reference
        # grammar; [] must match literally
        pattern = pattern.replace("[", "[[]")
        out = []
        for t in self._field_vocab(node.field):
            probe = t.lower() if node.case_insensitive else t
            if fnmatch.fnmatchcase(probe, pattern):
                out.append(t)
        self._check_expansion(out, "wildcard")
        return out

    def _expand_fuzzy(self, node: dsl.FuzzyQuery) -> List[str]:
        value = node.value
        if node.fuzziness == "AUTO" or (
                isinstance(node.fuzziness, str)):
            n = len(value)
            max_d = 0 if n < 3 else (1 if n < 6 else 2)
        else:
            max_d = int(node.fuzziness)
        pl = node.prefix_length
        prefix = value[:pl]
        out = []
        for t in self._field_vocab(node.field):
            if abs(len(t) - len(value)) > max_d:
                continue
            if pl and not t.startswith(prefix):
                continue
            if _edit_distance_lte(value, t, max_d):
                out.append(t)
            if len(out) >= node.max_expansions:
                break
        return out

    def _check_expansion(self, terms: List[str], kind: str) -> None:
        if len(terms) > self._MAX_EXPANSIONS:
            raise QueryShardException(
                f"[{kind}] query expands to {len(terms)} terms, more "
                f"than the {self._MAX_EXPANSIONS} clause limit")

    def _eval_expanded_terms(self, field: str, terms: List[str],
                             boost: float, scoring: bool, *,
                             constant: bool):
        """OR over an expanded term set. constant=True → the reference's
        constant-score rewrite (prefix/wildcard score = boost); else
        BM25-scored like a terms disjunction (fuzzy)."""
        if not terms:
            return self._none()
        mask, score = self._eval_terms(field, terms, boost,
                                       scoring and not constant, "or", 1,
                                       pre_analyzed=True)
        if constant and scoring:
            score = jnp.where(mask, boost, 0.0).astype(jnp.float32)
        return mask, score

    def _eval_function_score(self, node: dsl.FunctionScoreQuery,
                             scoring: bool):
        mask, score = self._eval(node.query, scoring)
        if not scoring:
            return mask, score
        if not node.functions:
            # max_boost only caps function output; with no functions the
            # query-level boost still applies
            return mask, jnp.where(mask, score * node.boost, 0.0)
        factors = []
        applies = []   # per function: which docs its filter matches
        for fn in node.functions:
            factor = jnp.ones(self.d_pad, dtype=jnp.float32)
            if fn.field_value_factor is not None:
                factor = factor * self._field_value_factor(
                    fn.field_value_factor)
            if fn.script_score is not None:
                factor = factor * self._run_score_script(
                    fn.script_score, score)
            if fn.weight is not None:
                factor = factor * fn.weight
            if fn.filter_query is not None:
                fmask, _ = self._eval(fn.filter_query, scoring=False)
            else:
                fmask = jnp.ones(self.d_pad, dtype=bool)
            factors.append(factor)
            applies.append(fmask)
        stacked = jnp.stack(factors)
        applied = jnp.stack(applies)
        n_applied = jnp.sum(applied, axis=0)
        # only MATCHING functions combine (reference:
        # FunctionScoreQuery#score — non-matching functions are absent
        # from the combination, and a doc matching none scores neutral 1)
        if node.score_mode == "multiply":
            combined = jnp.prod(jnp.where(applied, stacked, 1.0), axis=0)
        elif node.score_mode == "sum":
            combined = jnp.sum(jnp.where(applied, stacked, 0.0), axis=0)
        elif node.score_mode == "avg":
            combined = (jnp.sum(jnp.where(applied, stacked, 0.0), axis=0)
                        / jnp.maximum(n_applied, 1))
        elif node.score_mode == "max":
            combined = jnp.max(
                jnp.where(applied, stacked, -jnp.inf), axis=0)
        else:  # min
            combined = jnp.min(
                jnp.where(applied, stacked, jnp.inf), axis=0)
        combined = jnp.where(n_applied > 0, combined, 1.0)
        if node.max_boost is not None:
            combined = jnp.minimum(combined, node.max_boost)
        if node.boost_mode == "multiply":
            final = score * combined
        elif node.boost_mode == "sum":
            final = score + combined
        elif node.boost_mode == "replace":
            final = combined
        elif node.boost_mode == "avg":
            final = (score + combined) / 2.0
        elif node.boost_mode == "max":
            final = jnp.maximum(score, combined)
        else:  # min
            final = jnp.minimum(score, combined)
        return mask, jnp.where(mask, final * node.boost, 0.0)

    def _eval_knn_score_doc(self, node: dsl.KnnScoreDocQuery,
                            scoring: bool):
        """Union of the base query with pinned knn winners: a doc
        matches if the query matches OR it is a knn winner; its score
        is query_score + Σ knn_score·boost (reference hybrid rule)."""
        seg_name = self.view.segment.name
        knn_mask = np.zeros(self.d_pad, dtype=bool)
        knn_score = np.zeros(self.d_pad, dtype=np.float32)
        for doc_set, boost in zip(node.doc_sets, node.boosts):
            entry = doc_set.get(seg_name)
            if entry is None:
                continue
            ords, scores = entry
            knn_mask[ords] = True
            knn_score[ords] += scores * boost
        kmask = jnp.asarray(knn_mask)
        kscore = jnp.asarray(knn_score)
        if node.query is None:
            return kmask, (kscore if scoring
                           else jnp.zeros_like(kscore))
        bmask, bscore = self._eval(node.query, scoring)
        mask = bmask | kmask
        if not scoring:
            return mask, jnp.zeros_like(kscore)
        return mask, jnp.where(bmask, bscore, 0.0) + kscore

    def _eval_rank_feature(self, node: dsl.RankFeatureQuery,
                           scoring: bool):
        """Feature-value scoring on the f64 column (reference:
        RankFeatureQuery; the impact-postings trick becomes plain
        column math on device). Missing docs don't match."""
        vals, present = self._dv_column(node.field)
        mask = present
        if not scoring:
            return mask, jnp.zeros(self.d_pad, dtype=jnp.float32)
        from elasticsearch_tpu.mapping.types import RankFeatureFieldType
        ft = self.reader.mapper.field_type(node.field)
        if ft is not None and isinstance(ft, RankFeatureFieldType) \
                and not ft.positive_score_impact:
            # negative impact: smaller values score higher — the
            # reference inverts inside the same saturation shape
            vals = jnp.where(present, 1.0 / jnp.maximum(vals, 1e-9),
                             0.0)
        x = jnp.where(present, vals, 0.0).astype(jnp.float32)
        if node.function == "linear":
            score = x
        elif node.function == "log":
            score = jnp.log(jnp.maximum(
                node.scaling_factor + x, 1e-9))
        elif node.function == "sigmoid":
            xp = jnp.power(x, node.exponent)
            score = xp / (xp + jnp.power(node.pivot, node.exponent))
        else:  # saturation
            pivot = node.pivot
            if pivot is None:
                # index-derived default pivot: geometric mean of the
                # shard's feature values (reference computes an
                # approximate geometric mean from the impacts)
                pivot = self._rank_feature_default_pivot(node.field)
            score = x / (x + pivot)
        return mask, jnp.where(mask, score * node.boost,
                               0.0).astype(jnp.float32)

    def _rank_feature_default_pivot(self, field: str) -> float:
        cache = getattr(self.reader, "_rf_pivot_cache", None)
        if cache is None:
            cache = {}
            self.reader._rf_pivot_cache = cache
        if field in cache:
            return cache[field]
        logs, count = 0.0, 0
        for v in self.reader.views:
            col = v.segment.doc_values.get(field)
            if col is None or col.kind != "f64":
                continue
            vals = col.values
            ok = ~np.isnan(vals) & (vals > 0)
            if ok.any():
                logs += float(np.log(vals[ok]).sum())
                count += int(ok.sum())
        pivot = float(np.exp(logs / count)) if count else 1.0
        cache[field] = pivot
        return pivot

    _EARTH_R_M = 6371008.7714  # mean earth radius, as Lucene uses

    def _geo_columns(self, field: str):
        from elasticsearch_tpu.mapping.types import GeoPointFieldType
        pack = self.view.pack
        lat = pack.dv_f64.get(field + GeoPointFieldType.LAT_SUFFIX)
        lon = pack.dv_f64.get(field + GeoPointFieldType.LON_SUFFIX)
        if lat is None or lon is None:
            return None, None, jnp.zeros(self.d_pad, dtype=bool)
        lat = jnp.asarray(lat)
        lon = jnp.asarray(lon)
        present = ~jnp.isnan(lat)
        return lat, lon, present

    def _eval_geo_distance(self, node: dsl.GeoDistanceQuery):
        """Vectorized haversine over the segment's lat/lon columns —
        one fused elementwise pass (no BKD tree)."""
        lat, lon, present = self._geo_columns(node.field)
        if lat is None:
            return self._none()
        rad = jnp.pi / 180.0
        dlat = (lat - node.lat) * rad
        dlon = (lon - node.lon) * rad
        a = jnp.sin(dlat / 2) ** 2 + jnp.cos(lat * rad) * \
            jnp.cos(node.lat * rad) * jnp.sin(dlon / 2) ** 2
        dist = 2 * self._EARTH_R_M * jnp.arcsin(
            jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
        mask = present & (dist <= node.distance_m)
        return mask, jnp.where(mask, node.boost, 0.0).astype(jnp.float32)

    def _eval_geo_bbox(self, node: dsl.GeoBoundingBoxQuery):
        lat, lon, present = self._geo_columns(node.field)
        if lat is None:
            return self._none()
        lat_ok = (lat <= node.top) & (lat >= node.bottom)
        if node.left <= node.right:
            lon_ok = (lon >= node.left) & (lon <= node.right)
        else:
            # box crossing the antimeridian (reference behavior)
            lon_ok = (lon >= node.left) | (lon <= node.right)
        mask = present & lat_ok & lon_ok
        return mask, jnp.where(mask, node.boost, 0.0).astype(jnp.float32)

    def _eval_percolate(self, node: dsl.PercolateQuery, scoring: bool):
        """Evaluate every live stored query of this segment against the
        percolated document(s) (search/percolator.py; reference:
        PercolateQuery with MemoryIndex verification — here without
        the term-extraction pre-filter, see module docstring). Score =
        boost for matching stored queries (the reference scores 1.0
        filter-style unless the inner query scores)."""
        from elasticsearch_tpu.search import percolator as perc
        ft = self.reader.mapper.field_type(node.field)
        from elasticsearch_tpu.mapping.types import PercolatorFieldType
        if ft is None or not isinstance(ft, PercolatorFieldType):
            raise QueryShardException(
                f"[percolate] field [{node.field}] is not a "
                f"[percolator] field")
        # one tiny in-memory index of the documents per REQUEST, keyed
        # by the index's mapper (a multi-index search re-parses the
        # documents per index — each index's own analyzers/types apply)
        readers = getattr(node, "_doc_readers", None)
        if readers is None:
            readers = {}
            node._doc_readers = readers
        cached = readers.get(id(self.reader.mapper))
        if cached is None:
            cached = perc.build_doc_reader(self.reader.mapper,
                                           node.documents)
            readers[id(self.reader.mapper)] = cached
        queries = perc.segment_parsed_queries(self.view.segment,
                                              node.field)
        doc_exec = SegmentQueryExecutor(cached, 0)
        doc_live = cached.views[0].live_mask
        live = self.view.live_mask  # skip tombstoned stored queries
        mask = np.zeros(self.d_pad, dtype=bool)
        for ord_, q in queries.items():
            if not live[ord_]:
                continue
            try:
                qmask, _ = doc_exec._eval(q, scoring=False)
            except Exception:  # noqa: BLE001 — one poisonous stored
                continue  # query (e.g. type mismatch vs the document's
                #           dynamic fields) must not break the search
            if bool((np.asarray(qmask)[: len(doc_live)]
                     & doc_live).any()):
                mask[ord_] = True
        m = jnp.asarray(mask)
        score = jnp.where(m, node.boost if scoring else 0.0,
                          0.0).astype(jnp.float32)
        return m, score

    def _dv_column(self, field: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Numeric doc-values column → (values_f32, present_mask); the
        one extraction both score scripts and field_value_factor use."""
        pack = self.view.pack
        if field in pack.dv_f64:
            vals = jnp.asarray(pack.dv_f64[field], dtype=jnp.float32)
            present = ~jnp.isnan(vals)
        elif field in pack.dv_i64:
            raw = pack.dv_i64[field]
            present = jnp.asarray(raw != MISSING_I64)
            vals = jnp.asarray(raw, dtype=jnp.float32)
        else:
            present = jnp.zeros(self.d_pad, dtype=bool)
            vals = jnp.zeros(self.d_pad, dtype=jnp.float32)
        return vals, present

    def _script_resolver(self, field: str):
        """doc['field'] in a score script → FieldColumn over this
        view's doc-values (numeric; missing = 0 with .empty mask —
        lang-expression semantics, see script module docstring)."""
        from elasticsearch_tpu.script import FieldColumn
        vals, present = self._dv_column(field)
        return FieldColumn(jnp.where(present, vals, 0.0), present)

    def _vec_column(self, field: str) -> jnp.ndarray:
        """dense_vector matrix f32[d_pad, dims] for score scripts
        (cosineSimilarity et al.); unknown field → 400."""
        mat = self.view.pack.dv_vec.get(field)
        if mat is None:
            from elasticsearch_tpu.script import ScriptException
            raise ScriptException(
                f"[{field}] is not a dense_vector field")
        return jnp.asarray(mat)

    def _run_score_script(self, script, base_score) -> jnp.ndarray:
        from elasticsearch_tpu.script import ScriptException
        try:
            return script.score_vector(self._script_resolver, base_score,
                                       vec_resolver=self._vec_column)
        except ScriptException:
            raise
        except Exception as e:  # noqa: BLE001 — surface as a 400
            from elasticsearch_tpu.script import ScriptException as SE
            raise SE(f"runtime error in score script "
                     f"[{script.source[:80]}]: {e}") from None

    def _eval_script_score(self, node: dsl.ScriptScoreQuery,
                           scoring: bool):
        # min_score prunes MATCHES, so it must run even in filter
        # context (a filter-placed script_score matches the same docs
        # as a query-placed one)
        needs_script = scoring or node.min_score is not None
        mask, score = self._eval(node.query, scoring or needs_script)
        if not needs_script:
            return mask, score
        scripted = self._run_score_script(node.script, score)
        # the reference rejects negative script scores (since 7.x)
        scripted = jnp.maximum(scripted, 0.0)
        if node.min_score is not None:
            mask = mask & (scripted >= node.min_score)
        if not scoring:
            return mask, jnp.zeros_like(scripted)
        return mask, jnp.where(mask, scripted * node.boost,
                               0.0).astype(jnp.float32)

    def _field_value_factor(self, fvf: dict) -> jnp.ndarray:
        """Per-doc factor from a doc-values column (reference:
        FieldValueFactorFunction)."""
        field = fvf["field"]
        factor = float(fvf.get("factor", 1.0))
        missing = fvf.get("missing")
        vals, present = self._dv_column(field)
        if missing is None:
            # the reference errors on missing values without [missing];
            # a dense kernel can't throw per-doc, so treat as 0
            fill = 0.0
        else:
            fill = float(missing)
        vals = jnp.where(present, vals, fill) * factor
        mod = fvf.get("modifier", "none")
        if mod == "log":
            vals = jnp.where(vals > 0, jnp.log10(jnp.maximum(vals, 1e-9)),
                             0.0)
        elif mod == "log1p":
            vals = jnp.log10(jnp.maximum(vals, 0.0) + 1.0)
        elif mod == "log2p":
            vals = jnp.log10(jnp.maximum(vals, 0.0) + 2.0)
        elif mod == "ln":
            vals = jnp.where(vals > 0, jnp.log(jnp.maximum(vals, 1e-9)),
                             0.0)
        elif mod == "ln1p":
            vals = jnp.log(jnp.maximum(vals, 0.0) + 1.0)
        elif mod == "ln2p":
            vals = jnp.log(jnp.maximum(vals, 0.0) + 2.0)
        elif mod == "square":
            vals = vals * vals
        elif mod == "sqrt":
            vals = jnp.sqrt(jnp.maximum(vals, 0.0))
        elif mod == "reciprocal":
            vals = jnp.where(vals != 0, 1.0 / vals, 0.0)
        return vals.astype(jnp.float32)

    def _eval_bool(self, node: dsl.BoolQuery, scoring: bool):
        mask = jnp.ones(self.d_pad, dtype=bool)
        score = jnp.zeros(self.d_pad, dtype=jnp.float32)
        for child in node.must:
            cmask, cscore = self._eval(child, scoring)
            mask = mask & cmask
            score = score + cscore
        for child in node.filter:
            cmask, _ = self._eval(child, scoring=False)
            mask = mask & cmask
        for child in node.must_not:
            cmask, _ = self._eval(child, scoring=False)
            mask = mask & ~cmask
        if node.should:
            msm = node.minimum_should_match
            if msm is None:
                # the reference default: 1 when there is nothing mandatory,
                # else 0 (should becomes purely score-boosting)
                msm = 0 if (node.must or node.filter) else 1
            count = jnp.zeros(self.d_pad, dtype=jnp.int32)
            for child in node.should:
                cmask, cscore = self._eval(child, scoring)
                count = count + cmask.astype(jnp.int32)
                score = score + cscore
            if msm > 0:
                mask = mask & (count >= msm)
        score = jnp.where(mask, score * node.boost, 0.0)
        return mask, score

    # -------------- leaves --------------

    def _field_type(self, field: str) -> FieldType:
        ft = self.reader.mapper.field_type(field)
        if ft is None:
            # unmapped fields match nothing (reference: unmapped term queries
            # return MatchNoDocsQuery under lenient resolution)
            raise _UnmappedField(field)
        return ft

    def _eval_match(self, node: dsl.MatchQuery, scoring: bool):
        try:
            ft = self._field_type(node.field)
        except _UnmappedField:
            return self._none()
        if isinstance(ft, TextFieldType):
            terms = _analyzed_terms(ft, node.query)
        else:
            # match on keyword/numeric behaves like a term query
            terms = [ft.normalize_term(node.query)]
        if not terms:
            return self._none()
        msm = 1 if node.operator == "or" else len(terms)
        if node.minimum_should_match is not None and node.operator == "or":
            msm = node.minimum_should_match
        return self._eval_terms(node.field, terms, node.boost, scoring,
                                node.operator, msm, pre_analyzed=True)

    def _eval_terms(self, field: str, values: Sequence, boost: float,
                    scoring: bool, operator: str, msm: int,
                    pre_analyzed: bool = False):
        try:
            ft = self._field_type(field)
        except _UnmappedField:
            return self._none()
        if pre_analyzed:
            terms = [str(v) for v in values]
        elif isinstance(ft, TextFieldType):
            # term/terms queries are NOT analyzed (reference: TermQueryBuilder
            # compares raw bytes even on text fields)
            terms = [str(v) for v in values]
        else:
            terms = [ft.normalize_term(v) for v in values]
        fp = self.view.pack.fields.get(field)
        if fp is None:
            return self._none()
        k1, b = self.reader.k1, self.reader.b
        doc_count, avgdl = self.reader.field_stats(field)
        cache = bm25_norm_cache(k1, b, avgdl)

        total_mask = None
        total_count = jnp.zeros(self.d_pad, dtype=jnp.int32)
        total_score = jnp.zeros(self.d_pad, dtype=jnp.float32)
        # chunk terms into ≤32-slot kernel passes
        for chunk_start in range(0, len(terms), MAX_SLOTS_PER_PASS):
            chunk = terms[chunk_start: chunk_start + MAX_SLOTS_PER_PASS]
            t_pad = _bucket(len(chunk))
            starts = np.zeros((1, t_pad), dtype=np.int32)
            lengths = np.zeros((1, t_pad), dtype=np.int32)
            idf_boost = np.zeros((1, t_pad), dtype=np.float32)
            max_len = 1
            for t, term in enumerate(chunk):
                row = fp.term_row(term)
                s, ln = fp.row_slice(row)
                df = self.reader.doc_freq(field, term)
                starts[0, t], lengths[0, t] = s, ln
                if scoring and df > 0:
                    idf = math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))
                    idf_boost[0, t] = boost * idf * (k1 + 1.0)
                max_len = max(max_len, ln)
            max_len = _bucket(max_len, 128)
            scores, termmask = bm25.score_and_mask(
                jnp.asarray(fp.flat_docs), jnp.asarray(fp.flat_tfs),
                jnp.asarray(fp.norms_u8), jnp.asarray(cache),
                jnp.asarray(starts), jnp.asarray(lengths), jnp.asarray(idf_boost),
                max_len=max_len, d_pad=self.d_pad)
            tm = termmask[0, : self.d_pad]
            total_score = total_score + scores[0, : self.d_pad]
            # per-slot presence → per-chunk match count
            bits = jnp.asarray([1 << t for t in range(len(chunk))], dtype=jnp.int32)
            present = (tm[None, :] & bits[:, None]) != 0
            total_count = total_count + jnp.sum(present, axis=0).astype(jnp.int32)
        if operator == "and":
            mask = total_count >= len(terms)
        else:
            mask = total_count >= max(1, msm)
        score = jnp.where(mask, total_score, 0.0)
        return mask, score

    def _eval_nested(self, node: dsl.NestedQuery, scoring: bool):
        """Per-OBJECT matching over the segment's nested store
        (reference: NestedQueryBuilder joins hidden sub-documents via
        BitSetProducer; here each object is evaluated directly). Child
        scores are constant (1·boost per matching object, filter-like);
        score_mode combines them: sum → count, avg/min/max → 1, none → 0."""
        store = self.view.segment.nested_store.get(node.path)
        if not store:
            return self._none()
        mapper = self.reader.mapper
        if hasattr(mapper, "mapper"):  # MapperService → DocumentMapper
            mapper = mapper.mapper
        mask = np.zeros(self.d_pad, dtype=bool)
        score = np.zeros(self.d_pad, dtype=np.float32)
        for ord_, objs in store.items():
            n_matched = 0
            for obj in objs:
                if _nested_object_matches(node.query, obj, mapper,
                                          node.path):
                    n_matched += 1
            if n_matched:
                mask[ord_] = True
                if scoring and node.score_mode != "none":
                    child = float(node.boost)
                    score[ord_] = (child * n_matched
                                   if node.score_mode == "sum" else child)
        return jnp.asarray(mask), jnp.asarray(score)

    def _eval_ip_range(self, field: str, lo128: int, hi128: int,
                       boost: float):
        """[lo128, hi128] inclusive over the ip field's split (hi, lo)
        signed-offset i64 columns — a 128-bit compare as two 64-bit
        lexicographic compares (IpFieldType docstring)."""
        pack = self.view.pack
        h = pack.dv_i64.get(field + IpFieldType.HI_SUFFIX)
        l = pack.dv_i64.get(field + IpFieldType.LO_SUFFIX)
        if h is None or l is None or lo128 > hi128:
            return self._none()
        lo_h, lo_l = IpFieldType.split128(lo128)
        hi_h, hi_l = IpFieldType.split128(hi128)
        # presence via the exists mask, NOT the i64 sentinel: an
        # IPv4-mapped address has hi == 0, which collides with MISSING_I64
        # after the signed offset
        present = self.reader.has_field_mask(self.view_idx, field)
        ge = (h > lo_h) | ((h == lo_h) & (l >= lo_l))
        le = (h < hi_h) | ((h == hi_h) & (l <= hi_l))
        mask = jnp.asarray(present & ge & le)
        score = jnp.where(mask, jnp.float32(boost), 0.0).astype(jnp.float32)
        return mask, score

    def _eval_range_field(self, node: dsl.RangeQuery, ft: RangeFieldType):
        """Interval-vs-interval matching on a range FIELD (reference:
        RangeFieldMapper; relation intersects|within|contains, default
        intersects)."""
        pack = self.view.pack
        cols = pack.dv_i64 if ft.bound_kind == "i64" else pack.dv_f64
        g = cols.get(node.field + RangeFieldType.GTE_SUFFIX)
        l = cols.get(node.field + RangeFieldType.LTE_SUFFIX)
        if g is None or l is None:
            return self._none()
        q_lo, q_hi = ft.parse_range({k: v for k, v in
                                     (("gt", node.gt), ("gte", node.gte),
                                      ("lt", node.lt), ("lte", node.lte))
                                     if v is not None})
        if ft.bound_kind == "i64":
            present = g != MISSING_I64
        else:
            present = ~np.isnan(g)
        relation = (node.relation or "intersects").lower()
        if relation == "within":
            hit = (g >= q_lo) & (l <= q_hi)
        elif relation == "contains":
            hit = (g <= q_lo) & (l >= q_hi)
        elif relation == "intersects":
            hit = (g <= q_hi) & (l >= q_lo)
        else:
            raise QueryShardException(
                f"[range] unknown relation [{relation}]")
        mask = jnp.asarray(present & hit)
        score = jnp.where(mask, jnp.float32(node.boost),
                          0.0).astype(jnp.float32)
        return mask, score

    def _eval_range(self, node: dsl.RangeQuery):
        try:
            ft = self._field_type(node.field)
        except _UnmappedField:
            return self._none()
        if isinstance(ft, IpFieldType):
            lo = 0
            hi = (1 << 128) - 1
            if node.gte is not None:
                lo = ft.parse_ip(node.gte)
            elif node.gt is not None:
                lo = ft.parse_ip(node.gt) + 1
            if node.lte is not None:
                hi = ft.parse_ip(node.lte)
            elif node.lt is not None:
                hi = ft.parse_ip(node.lt) - 1
            return self._eval_ip_range(node.field, lo, hi, node.boost)
        if isinstance(ft, RangeFieldType):
            return self._eval_range_field(node, ft)
        if isinstance(ft, (TextFieldType, KeywordFieldType)):
            raise QueryShardException(
                f"range query on [{ft.type_name}] field [{node.field}] is not supported")
        lo_raw = node.gte if node.gte is not None else node.gt
        hi_raw = node.lte if node.lte is not None else node.lt
        pack = self.view.pack
        if node.field in pack.dv_i64:
            col = pack.dv_i64[node.field]
            lo = -(2**62) if lo_raw is None else int(ft.normalize_range_bound(lo_raw))
            hi = 2**62 if hi_raw is None else int(ft.normalize_range_bound(hi_raw))
            if node.gt is not None and node.gte is None:
                lo += 1
            if node.lt is not None and node.lte is None:
                hi -= 1
            mask = bm25.range_mask_i64(
                jnp.asarray(col), jnp.asarray([lo], dtype=jnp.int64),
                jnp.asarray([hi], dtype=jnp.int64))[0]
        elif node.field in pack.dv_f64:
            col = pack.dv_f64[node.field]
            lo = -np.inf if lo_raw is None else float(ft.normalize_range_bound(lo_raw))
            hi = np.inf if hi_raw is None else float(ft.normalize_range_bound(hi_raw))
            mask = bm25.range_mask_f64(
                jnp.asarray(col), jnp.asarray([lo], dtype=jnp.float64),
                jnp.asarray([hi], dtype=jnp.float64))[0]
            if node.gt is not None and node.gte is None:
                mask = mask & (jnp.asarray(col) != lo)
            if node.lt is not None and node.lte is None:
                mask = mask & (jnp.asarray(col) != hi)
        else:
            return self._none()
        # constant_score semantics: ranges don't score (reference wraps range
        # in filter context scoring = 1*boost when in scoring context)
        score = jnp.where(mask, jnp.float32(node.boost), 0.0).astype(jnp.float32)
        return mask, score

    def _eval_phrase(self, node: dsl.MatchPhraseQuery, scoring: bool):
        try:
            ft = self._field_type(node.field)
        except _UnmappedField:
            return self._none()
        if not isinstance(ft, TextFieldType):
            return self._eval_terms(node.field, [node.query], node.boost,
                                    scoring, "and", 1)
        terms = _analyzed_terms(ft, node.query)
        if not terms:
            return self._none()
        seg = self.view.segment
        positions = seg.positions.get(node.field, {})
        # candidates: docs containing all terms (host intersection over the
        # postings — phrase verification is host-side round 1)
        doc_sets = []
        for t in terms:
            entry = seg.postings.get(node.field, {}).get(t)
            if entry is None:
                return self._none()
            doc_sets.append(set(int(d) for d in entry[0]))
        candidates = sorted(set.intersection(*doc_sets))
        if not candidates:
            return self._none()
        k1, b = self.reader.k1, self.reader.b
        doc_count, avgdl = self.reader.field_stats(node.field)
        dfs = [self.reader.doc_freq(node.field, t) for t in terms]
        idf_sum = sum(math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))
                      for df in dfs if df > 0)
        from elasticsearch_tpu.ops.smallfloat import LENGTH_TABLE
        mask = np.zeros(self.d_pad, dtype=bool)
        score = np.zeros(self.d_pad, dtype=np.float32)
        for d in candidates:
            plists = [positions.get(t, {}).get(d) for t in terms]
            if any(p is None for p in plists):
                continue
            freq = _phrase_freq(plists, node.slop)
            if freq <= 0:
                continue
            mask[d] = True
            if scoring:
                dl = float(LENGTH_TABLE[seg.norms[node.field][d]])
                denom = freq + k1 * (1 - b + b * dl / (avgdl or 1.0))
                score[d] = node.boost * idf_sum * (k1 + 1.0) * freq / denom
        return jnp.asarray(mask), jnp.asarray(score)

    def _none(self):
        return (jnp.zeros(self.d_pad, dtype=bool),
                jnp.zeros(self.d_pad, dtype=jnp.float32))


class _UnmappedField(Exception):
    def __init__(self, field: str):
        self.field = field


def _phrase_freq(plists: List[np.ndarray], slop: int) -> int:
    """Exact phrase count (slop=0): positions p_i with p_i = p_0 + i.
    For slop>0 uses a simple window check (approximation of sloppy freq)."""
    first = plists[0]
    count = 0
    for p0 in first:
        ok = True
        for i, pl in enumerate(plists[1:], start=1):
            target = p0 + i
            if slop == 0:
                if target not in pl:
                    ok = False
                    break
            else:
                if not ((np.abs(pl - target) <= slop).any()):
                    ok = False
                    break
        if ok:
            count += 1
    return count


def _nested_object_matches(q: dsl.QueryNode, obj: Dict[str, list],
                           doc_mapper, path: str) -> bool:
    """Evaluate an inner nested query against ONE object's flat
    {absolute subfield path: [raw values]} map — the per-sub-document
    match the reference gets from indexing each nested object as its own
    Lucene doc. Field types normalize both sides."""
    if isinstance(q, dsl.MatchAllQuery):
        return True
    if isinstance(q, dsl.BoolQuery):
        for c in list(q.must) + list(q.filter):
            if not _nested_object_matches(c, obj, doc_mapper, path):
                return False
        for c in q.must_not:
            if _nested_object_matches(c, obj, doc_mapper, path):
                return False
        if q.should:
            msm = q.minimum_should_match
            if msm is None:
                msm = 0 if (q.must or q.filter) else 1
            if msm > 0:
                n = sum(1 for c in q.should
                        if _nested_object_matches(c, obj, doc_mapper, path))
                if n < msm:
                    return False
        return True
    if isinstance(q, dsl.ConstantScoreQuery):
        return _nested_object_matches(q.filter_query, obj, doc_mapper, path)
    if isinstance(q, dsl.NestedQuery):
        raise QueryShardException(
            "[nested] within [nested] is not supported yet")
    if isinstance(q, dsl.ExistsQuery):
        return bool(obj.get(q.field))
    if isinstance(q, (dsl.TermQuery, dsl.TermsQuery)):
        ft = doc_mapper.fields.get(q.field)
        vals = obj.get(q.field)
        if ft is None or not vals:
            return False
        wants = ([q.value] if isinstance(q, dsl.TermQuery)
                 else list(q.values))
        try:
            want_norm = {ft.normalize_term(w) for w in wants}
            return any(ft.normalize_term(v) in want_norm for v in vals)
        except Exception:
            return False
    if isinstance(q, dsl.MatchQuery):
        ft = doc_mapper.fields.get(q.field)
        vals = obj.get(q.field)
        if ft is None or not vals:
            return False
        if isinstance(ft, TextFieldType):
            q_terms = _analyzed_terms(ft, q.query)
            if not q_terms:
                return False
            doc_terms = set()
            for v in vals:
                doc_terms.update(ft.analyzer.terms(str(v)))
            hits = sum(1 for t in q_terms if t in doc_terms)
            if q.operator == "and":
                return hits == len(q_terms)
            need = q.minimum_should_match or 1
            return hits >= need
        try:
            want = ft.normalize_term(q.query)
            return any(ft.normalize_term(v) == want for v in vals)
        except Exception:
            return False
    if isinstance(q, dsl.RangeQuery):
        ft = doc_mapper.fields.get(q.field)
        vals = obj.get(q.field)
        if ft is None or not vals:
            return False
        try:
            for v in vals:
                dv = ft.doc_value(v) if ft.has_doc_values \
                    else ft.normalize_range_bound(v)
                if q.gt is not None and \
                        not dv > ft.normalize_range_bound(q.gt):
                    continue
                if q.gte is not None and \
                        not dv >= ft.normalize_range_bound(q.gte):
                    continue
                if q.lt is not None and \
                        not dv < ft.normalize_range_bound(q.lt):
                    continue
                if q.lte is not None and \
                        not dv <= ft.normalize_range_bound(q.lte):
                    continue
                return True
        except Exception:
            return False
        return False
    raise QueryShardException(
        f"[nested] unsupported inner query [{q.query_name()}]")
