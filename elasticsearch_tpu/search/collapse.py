"""Field collapsing — exact per-shard grouped top-N.

Reference: `CollapseBuilder` + the collapsing top-docs collector
(SURVEY.md §2.1#50): each shard returns its best hit PER KEY for the top
`n_groups` keys (ranked by their best score); the coordinator keeps the
best per key across shards. Here the per-shard pass is vectorized: the
planner's dense (mask, score) arrays group by the doc-value column with
one maximum.at scatter per segment — no candidate-depth cap, so a key
dominating the ranking can never starve later groups (exact, unlike a
windowed post-dedupe)."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.index.segment import MISSING_I64
from elasticsearch_tpu.ops import bm25
from elasticsearch_tpu.search import dsl
from elasticsearch_tpu.search.planner import SegmentQueryExecutor
from elasticsearch_tpu.search.query_phase import ShardDocRef, ShardHit


def collapse_top_groups(reader, query: dsl.QueryNode, field: str,
                        n_groups: int
                        ) -> Tuple[List[Tuple[ShardHit, Any]], int]:
    """→ ([(best hit, collapse key)] for the shard's top n_groups keys,
    total matching docs). Missing-key docs each form their own group
    (reference: they are not collapsed together)."""
    best: Dict[Any, Tuple[float, int, int]] = {}  # key → (score, seg, ord)
    loose: List[Tuple[float, int, int]] = []      # missing-key docs
    total = 0
    for idx, view in enumerate(reader.views):
        executor = SegmentQueryExecutor(reader, idx)
        mask, score = executor.execute(query)
        import jax.numpy as jnp
        live = jnp.asarray(view.live_mask)
        final = np.asarray(bm25.mask_scores(score[None, :], mask[None, :],
                                            live)[0])
        m = np.asarray(mask & live)
        n = view.segment.num_docs
        m = m[:n]
        total += int(m.sum())
        if not m.any():
            continue
        col = view.segment.doc_values.get(field)
        ords = np.nonzero(m)[0]
        scores = final[:n][ords]
        if col is None:
            keys = None
        elif col.kind == "ord":
            raw = col.values[ords]
            keys = [None if r < 0 else col.ord_terms[int(r)]
                    for r in raw.tolist()]
        elif col.kind == "i64":
            raw = col.values[ords]
            keys = [None if r == MISSING_I64 else int(r)
                    for r in raw.tolist()]
        else:
            raw = col.values[ords]
            keys = [None if math.isnan(r) else float(r)
                    for r in raw.tolist()]
        for i, o in enumerate(ords.tolist()):
            s = float(scores[i])
            key = keys[i] if keys is not None else None
            if key is None:
                loose.append((s, idx, o))
                continue
            cur = best.get(key)
            # tie-break toward earlier segment/doc, the merge order rule
            if cur is None or s > cur[0]:
                best[key] = (s, idx, o)
    ranked: List[Tuple[float, int, int, Any]] = [
        (s, seg, o, key) for key, (s, seg, o) in best.items()]
    ranked.extend((s, seg, o, None) for s, seg, o in loose)
    ranked.sort(key=lambda t: (-t[0], t[1], t[2]))
    out = []
    for s, seg, o, key in ranked[: n_groups]:
        segment = reader.views[seg].segment
        out.append((ShardHit(segment.doc_ids[o], s,
                             ShardDocRef(segment.name, o)), key))
    return out, total
