"""Off-interpreter coordinator merge: a columnar, heap-based k-way merge
of `search_shard_group` partials that reproduces
`coordinator.merge_group_responses` byte-identically — without importing
the device stack, so it can run on serving-front processes or a small
node-local worker pool instead of the batcher's interpreter.

Mechanics:

  * `route_search` finishes its fan-out/failover with the per-group
    partials in hand. When deferral is active (the serving front's
    dispatch context, or a node-local merge pool) and the body is
    defer-eligible, it returns a `DeferredMerge` carrying a JSON-safe
    descriptor instead of merging inline — the batcher's per-request
    steady-state work stays doorbell → plan memo → device launch →
    columns handoff.
  * `merge_descriptor` is the reduce: per-group runs arrive pre-sorted
    by `(sort_key, _index, __shard, rank)` (the shard-group local
    pre-merge ordering), so a `heapq.merge` with the group position as
    final tie-break replays exactly the stable global sort the
    in-process path gets from `merged.sort(key=t[:4])`, with early exit
    once the `from+size` window is full.
  * Aggregation-bearing bodies never defer: partial aggregates travel
    as pickled reducer state whose classes import the device stack —
    those merges stay on the batcher, which is the pre-existing path.

Deferral is opt-in per dispatch via a contextvar (`deferring(True)`),
so transport handlers, CCS federation, msearch item assembly and scroll
continuations — all of which post-process the merged dict — keep the
inline path untouched.
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.metrics import CounterMetric, SampleRing
from elasticsearch_tpu.search import sort_keys

DESCRIPTOR_VERSION = 1

_DEFER: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "es_tpu_merge_defer", default=False)


def defer_active() -> bool:
    return _DEFER.get()


@contextlib.contextmanager
def deferring(enabled: bool):
    """Scope deferral to one dispatch: handlers run on the calling
    thread (thread pools here are admission gates, not executors), so a
    contextvar set around `controller.dispatch` reaches `route_search`."""
    token = _DEFER.set(bool(enabled))
    try:
        yield
    finally:
        _DEFER.reset(token)


def can_defer(body: Optional[Dict[str, Any]]) -> bool:
    """Aggregations reduce through pickled aggregator state whose
    classes live behind the device stack — they merge on the batcher."""
    body = body or {}
    return not (body.get("aggs") or body.get("aggregations"))


class DeferredMerge:
    """A merge the coordinator handed off instead of performing: the
    JSON-safe descriptor plus nothing else. Boundaries resolve it — the
    serving supervisor ships it to the front that owns the reply, and
    `node.handle` routes it through the node's merge pool."""

    __slots__ = ("descriptor",)

    def __init__(self, descriptor: Dict[str, Any]):
        self.descriptor = descriptor

    def resolve(self) -> Dict[str, Any]:
        return merge_descriptor(self.descriptor)


def build_descriptor(groups: List[Dict[str, Any]],
                     body: Optional[Dict[str, Any]],
                     params: Optional[Dict[str, str]],
                     t0: float,
                     failed_shards: int = 0,
                     failures: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
    """Everything `merge_group_responses` reads, as one JSON-safe dict.
    `t0` is a perf_counter stamp — CLOCK_MONOTONIC on this platform, so
    `took` computed in another process on the same host stays honest."""
    return {"v": DESCRIPTOR_VERSION,
            "groups": groups,
            "body": body or {},
            "params": params or {},
            "t0": float(t0),
            "failed_shards": int(failed_shards),
            "failures": list(failures or [])}


# ---------------------------------------------------------------------------
# the reduce — byte-identical port of coordinator.merge_group_responses
# ---------------------------------------------------------------------------

def _group_run(gi: int, g: Dict[str, Any], sort_specs) -> List[tuple]:
    """One group's merge entries `(key, _index, __shard, rank, gi, doc)`
    — `rank` resets per group, exactly the in-process enumerate."""
    run = []
    for rank, doc in enumerate(g["hits"]):
        if sort_specs:
            key = sort_keys.sort_key(sort_specs, doc.get("sort") or [])
        else:
            key = -(doc.get("_score") or 0.0)
        run.append((key, doc.get("_index", ""), doc.pop("__shard", 0),
                    rank, gi, doc))
    return run


def _entry_key(t: tuple) -> tuple:
    return t[:4]


def merge_descriptor(desc: Dict[str, Any]) -> Dict[str, Any]:
    """K-way columnar merge of shard-group partials → one reference-
    shaped _search response, byte-identical to
    `coordinator.merge_group_responses` over the same inputs."""
    groups: List[Dict[str, Any]] = desc["groups"]
    body: Dict[str, Any] = desc.get("body") or {}
    params: Dict[str, Any] = desc.get("params") or {}
    t0 = desc.get("t0")
    failures = list(desc.get("failures") or [])
    n_failed = int(desc.get("failed_shards", 0)) + len(failures)
    size = int(params.get("size", body.get("size", 10)))
    from_ = int(params.get("from", body.get("from", 0)))
    sort_specs = sort_keys.parse_sort(body.get("sort"))

    total = 0
    relation = "eq"
    n_shards = n_failed
    n_skipped = 0
    timed_out = False
    runs: List[List[tuple]] = []
    for gi, g in enumerate(groups):
        total += g["total"]
        n_shards += g.get("shards", 0)
        n_skipped += g.get("skipped", 0)
        if g.get("timed_out"):
            timed_out = True
        if g.get("relation") == "gte":
            relation = "gte"
        run = _group_run(gi, g, sort_specs)
        # shard groups pre-sort their hits by (key, index, shard, rank);
        # heapq.merge requires it, so verify — an unsorted run (foreign
        # group producer) falls back to an explicit per-run sort, which
        # is still exactly the in-process stable order since `rank` is
        # unique within a group
        for i in range(1, len(run)):
            if _entry_key(run[i - 1]) > _entry_key(run[i]):
                run.sort(key=_entry_key)
                break
        runs.append(run)

    # stable across runs: heapq.merge resolves key ties by iterable
    # position = group order, same as the in-process stable global sort
    merged_iter = heapq.merge(*runs, key=_entry_key)

    collapse_field = (body.get("collapse") or {}).get("field") \
        if body.get("collapse") else None
    window: List[Dict[str, Any]] = []
    want = from_ + size
    if collapse_field:
        seen_keys = set()
        picked: List[Dict[str, Any]] = []
        if want > 0:
            for entry in merged_iter:
                doc = entry[5]
                key_vals = (doc.get("fields") or {}).get(collapse_field)
                if key_vals:
                    if key_vals[0] in seen_keys:
                        continue
                    seen_keys.add(key_vals[0])
                picked.append(doc)
                if len(picked) >= want:
                    break
        window = picked[from_: want]
    else:
        if want > 0:
            for pos, entry in enumerate(merged_iter):
                if pos >= from_:
                    window.append(entry[5])
                if pos + 1 >= want:
                    break

    any_hits = any(g["hits"] for g in groups)
    if sort_specs:
        only_score = all(s.field == "_score" for s in sort_specs)
        max_score = None
        if only_score and any_hits:
            max_score = max((d.get("_score") or float("-inf")
                             for g in groups for d in g["hits"]),
                            default=None)
        if not only_score:
            for doc in window:
                doc["_score"] = None
    else:
        max_score = max((g.get("max_score") for g in groups
                         if g.get("max_score") is not None),
                        default=None)

    shards_json: Dict[str, Any] = {"total": n_shards,
                                   "successful": n_shards - n_failed,
                                   "skipped": n_skipped,
                                   "failed": n_failed}
    if failures:
        shards_json["failures"] = failures
    out: Dict[str, Any] = {
        "took": int((time.perf_counter() - t0) * 1000),
        "timed_out": timed_out,
        "_shards": shards_json,
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": max_score,
                 "hits": window},
    }

    if body.get("suggest") is not None:
        from elasticsearch_tpu.search.suggest import (merge_suggest,
                                                      parse_suggest)
        specs = parse_suggest(body["suggest"])
        out["suggest"] = merge_suggest(
            specs, [g.get("suggest") for g in groups
                    if g.get("suggest") is not None])

    if body.get("profile"):
        shards = [s for g in groups for s in g.get("profile_shards", [])]
        out["profile"] = {"shards": shards}
        tpu = [s["tpu"] for s in shards if "tpu" in s]
        if tpu:
            out["profile"]["tpu"] = tpu
    return out


# ---------------------------------------------------------------------------
# node-local merge pool
# ---------------------------------------------------------------------------

class MergeStats:
    """The merge families, registered on the node whether or not a pool
    exists — inline resolutions and pool resolutions both record here,
    so `es_tpu_merge_*` never disappears from a scrape."""

    def __init__(self):
        self.merges = CounterMetric()          # merges completed (any path)
        self.inline = CounterMetric()          # … of which ran inline
        self.fallbacks = CounterMetric()       # pool gave up → inline
        self.worker_restarts = CounterMetric()
        self.latency = SampleRing(512)         # merge execution seconds

    def record(self, seconds: float, inline: bool = False) -> None:
        self.merges.inc()
        if inline:
            self.inline.inc()
        self.latency.add(seconds)

    def to_dict(self) -> Dict[str, Any]:
        pcts = self.latency.percentiles()
        return {"merges": self.merges.count,
                "inline": self.inline.count,
                "fallbacks": self.fallbacks.count,
                "worker_restarts": self.worker_restarts.count,
                "latency_ms": {f"p{int(k)}": round(v * 1000.0, 3)
                               for k, v in pcts.items()}}


def merge_inline(descriptor: Dict[str, Any],
                 stats: Optional[MergeStats] = None) -> Dict[str, Any]:
    t = time.perf_counter()
    out = merge_descriptor(descriptor)
    if stats is not None:
        stats.record(time.perf_counter() - t, inline=True)
    return out


def _pool_worker_main(conn) -> None:
    """Merge-pool worker loop: recv pickled descriptor → merge → send
    (response, merge_seconds). EOF ⇒ parent closed us; exit quietly."""
    import pickle
    while True:
        try:
            job = conn.recv_bytes()
        except (EOFError, OSError):
            return
        try:
            desc = pickle.loads(job)
            t = time.perf_counter()
            out = merge_descriptor(desc)
            conn.send(("ok", out, time.perf_counter() - t))
        except Exception as exc:  # noqa: BLE001 — report, don't die
            try:
                conn.send(("err", f"{type(exc).__name__}: {exc}", 0.0))
            except (OSError, ValueError):
                return


class _Job:
    __slots__ = ("data", "event", "result", "attempts")

    def __init__(self, data: bytes):
        self.data = data
        self.event = threading.Event()
        self.result: Any = None
        self.attempts = 0


class MergePool:
    """A small pool of spawn-context worker processes performing the
    k-way merge off the batcher's interpreter when no serving fronts
    exist to absorb it (`front_processes == 0`). Failure policy: a dead
    worker is respawned and the job retried once; a second failure (or
    timeout) falls back to an inline merge so a broken pool degrades to
    exactly the pre-pool behavior."""

    HIGH_WATER = int(os.environ.get("ES_TPU_MERGE_BACKLOG_HIGH_WATER", "32"))
    BACKLOG_DEBOUNCE_S = 5.0
    JOB_TIMEOUT_S = float(os.environ.get("ES_TPU_MERGE_JOB_TIMEOUT_S", "30"))

    def __init__(self, size: int, stats: Optional[MergeStats] = None):
        import multiprocessing
        self.size = max(1, int(size))
        self.stats = stats if stats is not None else MergeStats()
        self._ctx = multiprocessing.get_context("spawn")
        self._queue: "queue.Queue[Optional[_Job]]" = queue.Queue()
        self._lock = threading.Lock()
        self._closed = False
        self._last_backlog_emit = 0.0
        self._workers: List[Any] = []
        self._threads: List[threading.Thread] = []
        for i in range(self.size):
            self._workers.append(self._spawn(i))
            t = threading.Thread(target=self._drive, args=(i,),
                                 name=f"es-tpu-merge-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    # -- workers ----------------------------------------------------------

    def _spawn(self, i: int):
        parent, child = self._ctx.Pipe()
        proc = self._ctx.Process(target=_pool_worker_main, args=(child,),
                                 name=f"es-tpu-merge-worker-{i}",
                                 daemon=True)
        proc.start()
        child.close()
        return {"proc": proc, "conn": parent}

    def _respawn(self, i: int, reason: str) -> None:
        from elasticsearch_tpu.common import events
        old = self._workers[i]
        pid = getattr(old["proc"], "pid", None)
        try:
            old["conn"].close()
        except OSError:
            pass
        if old["proc"].is_alive():
            old["proc"].terminate()
        old["proc"].join(timeout=5.0)
        events.emit("merge.worker_exit", severity="warning",
                    worker=i, pid=pid, reason=reason)
        self.stats.worker_restarts.inc()
        self._workers[i] = self._spawn(i)
        events.emit("merge.worker_respawn", severity="info", worker=i,
                    pid=self._workers[i]["proc"].pid)

    def _drive(self, i: int) -> None:
        """One manager thread per worker: pull a job, round-trip it over
        the worker's pipe, respawn + retry-once on worker death."""
        while True:
            job = self._queue.get()
            if job is None:
                return
            worker = self._workers[i]
            try:
                worker["conn"].send_bytes(job.data)
                if not worker["conn"].poll(self.JOB_TIMEOUT_S):
                    raise TimeoutError("merge worker timed out")
                status, payload, seconds = worker["conn"].recv()
            except Exception as exc:  # noqa: BLE001 — supervise
                if self._closed:
                    job.result = ("dead", None, 0.0)
                    job.event.set()
                    continue
                self._respawn(i, f"{type(exc).__name__}: {exc}")
                job.attempts += 1
                if job.attempts < 2:
                    self._queue.put(job)
                else:
                    job.result = ("dead", None, 0.0)
                    job.event.set()
                continue
            job.result = (status, payload, seconds)
            job.event.set()

    # -- submission -------------------------------------------------------

    def merge(self, descriptor: Dict[str, Any]) -> Dict[str, Any]:
        import pickle
        if self._closed:
            return merge_inline(descriptor, self.stats)
        depth = self._queue.qsize()
        if depth >= self.HIGH_WATER:
            now = time.monotonic()
            if now - self._last_backlog_emit >= self.BACKLOG_DEBOUNCE_S:
                self._last_backlog_emit = now
                from elasticsearch_tpu.common import events
                events.emit("merge.backlog", severity="warning",
                            depth=depth, high_water=self.HIGH_WATER,
                            pool_size=self.size)
        job = _Job(pickle.dumps(descriptor, protocol=4))
        self._queue.put(job)
        if not job.event.wait(self.JOB_TIMEOUT_S * 2):
            self.stats.fallbacks.inc()
            return merge_inline(descriptor, self.stats)
        status, payload, seconds = job.result
        if status != "ok":
            self.stats.fallbacks.inc()
            return merge_inline(descriptor, self.stats)
        self.stats.record(seconds)
        return payload

    # -- introspection ----------------------------------------------------

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def status(self) -> Dict[str, Any]:
        return {"pool_size": self.size,
                "queue_depth": self.queue_depth(),
                "workers": [{"worker": i,
                             "pid": w["proc"].pid,
                             "alive": w["proc"].is_alive()}
                            for i, w in enumerate(self._workers)],
                **self.stats.to_dict()}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for _ in self._threads:
            self._queue.put(None)
        for w in self._workers:
            try:
                w["conn"].close()
            except OSError:
                pass
        for w in self._workers:
            w["proc"].join(timeout=5.0)
            if w["proc"].is_alive():
                w["proc"].terminate()
                w["proc"].join(timeout=5.0)
