"""TPU serving path for `_search` — resident packs + micro-batched kernel.

This wires the batched kernel pipeline (parallel/distributed.py) into the
live search path, replacing the per-query/per-segment host loop for the
queries that dominate serving traffic. Reference seam being replaced:
`search/query/QueryPhase#executeInternal`'s per-segment BulkScorer loop
(SURVEY.md §3.3 ⚙⚙) — here a whole micro-batch of queries crosses all
shards in ONE kernel launch (SURVEY.md §2.3 P4: TPUs want batches, not
threads).

Three pieces:

  IndexPackCache — per (index, field) StackedShardPack built from the
    union of every shard's current reader (one pack row per segment, one
    statistics GROUP per shard so idf/avgdl match the per-shard planner
    path exactly — the reference's query_then_fetch statistics scope).
    Packs are derived caches (SURVEY.md §5.4): rebuilt when any shard's
    reader changes, HBM-accounted via the `hbm` circuit breaker.

  lowering — QueryNode → FlatQuery(terms, boost, min_count) for the query
    shapes the kernel serves: match (or/and/msm), term/terms on one text
    field, and single-field bool should-of-term/match. Everything else
    (phrase, ranges, aggs, multi-field bools...) returns None and falls
    back to the planner path — same contract split as the reference's
    `EnginePlugin#getEngineFactory` seam: the fast engine serves what it
    can, behavior elsewhere is unchanged.

  MicroBatcher — coalesces concurrent queries for ~2ms (or until the
    batch cap) and executes them as one kernel call; callers block on
    futures. Batch sizes pad to power-of-two buckets so the jit cache is
    hit, not re-traced.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.common import events, profiler, tenancy, tracing
from elasticsearch_tpu.common.metrics import CounterMetric, LabeledCounters
from elasticsearch_tpu.mapping.types import TextFieldType
from elasticsearch_tpu.ops import sparse
from elasticsearch_tpu.parallel import distributed as dist
from elasticsearch_tpu.parallel.mesh import SHARD_AXIS, make_mesh
from elasticsearch_tpu.search import dsl

logger = logging.getLogger("elasticsearch_tpu.tpu_service")


class StageTimes:
    """Accumulated per-stage wall time on the serving path (VERDICT r3
    #1a: measure where the time goes before optimizing it). Reported via
    TpuSearchService.stats()["stages"] and the profile/_nodes/stats trees.

    Besides the running (seconds, count) totals, each stage keeps a
    bounded ring of recent per-call samples and reports p50/p95/p99
    latency. The totals alone mislead for queue-style stages: batch_wait
    sums each query's wait even though a whole train waits CONCURRENTLY,
    so "5087 s total" can describe a 20 s run. The percentiles are the
    per-query truth."""

    RING_SIZE = 512

    def __init__(self):
        from elasticsearch_tpu.common.metrics import SampleRing
        self._ring_cls = SampleRing
        self._lock = threading.Lock()
        self.seconds: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self._rings: Dict[str, Any] = {}

    def add(self, stage: str, dt: float, n: int = 1) -> None:
        with self._lock:
            self.seconds[stage] = self.seconds.get(stage, 0.0) + dt
            self.counts[stage] = self.counts.get(stage, 0) + n
            ring = self._rings.get(stage)
            if ring is None:
                ring = self._rings[stage] = self._ring_cls(self.RING_SIZE)
        # stage exemplar: the ring remembers the trace_id of its slowest
        # recent traced sample (the metrics→trace pivot in /_tpu/stats)
        span = tracing.current_span()
        ring.add(dt / n if n > 1 else dt,
                 exemplar=span.trace_id if span is not None else None)
        # the same dt the stats ring keeps also lands on the active trace
        # (no-op — one thread-local read — when the request isn't traced)
        tracing.record_stage("tpu." + stage, dt, n=n)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            stages = sorted(self.seconds)
            out = {s: {"seconds": round(self.seconds[s], 4),
                       "count": self.counts[s]}
                   for s in stages}
            rings = {s: self._rings.get(s) for s in stages}
        for s, ring in rings.items():
            if ring is None:
                continue
            pcts = ring.percentiles((50.0, 95.0, 99.0))
            if pcts:
                out[s]["p50_ms"] = round(pcts[50.0] * 1000.0, 3)
                out[s]["p95_ms"] = round(pcts[95.0] * 1000.0, 3)
                out[s]["p99_ms"] = round(pcts[99.0] * 1000.0, 3)
            # metrics→trace pivot: the slowest recent traced sample's
            # trace_id (key absent when nothing traced is in-window)
            exemplar = ring.exemplar_trace_id
            if exemplar is not None:
                out[s]["exemplar_trace_id"] = exemplar
        return out

    def metrics_view(self) -> List[Tuple[str, float, int, Any]]:
        """(stage, total_seconds, count, ring) rows for the metrics
        registry — the live ring OBJECTS, so the Prometheus summary
        exports current quantiles and the completeness check can see
        every ring is registered."""
        with self._lock:
            return [(s, self.seconds[s], self.counts.get(s, 0),
                     self._rings.get(s))
                    for s in sorted(self.seconds)]


# ---------------------------------------------------------------------------
# DSL lowering
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FlatQuery:
    """A query the kernel can serve directly: weighted-OR over one text
    field's terms with a minimum-match count (1 = OR, len(terms) = AND)."""

    field: str
    terms: List[str]
    boost: float
    min_count: int


def lower_query(query: dsl.QueryNode, mapper) -> Optional[FlatQuery]:
    """QueryNode → FlatQuery, or None when this query needs the planner.
    `mapper`: the index's MapperService (analysis for match queries)."""
    if isinstance(query, dsl.MatchQuery):
        ft = mapper.field_type(query.field)
        if not isinstance(ft, TextFieldType):
            return None
        terms = ft.search_terms(query.query)
        if not terms:
            return None
        msm = len(terms) if query.operator == "and" else 1
        if query.minimum_should_match is not None and query.operator == "or":
            # unclamped: msm > len(terms) matches nothing, like the planner
            msm = query.minimum_should_match
        return FlatQuery(query.field, terms, query.boost, msm)
    if isinstance(query, dsl.TermQuery):
        ft = mapper.field_type(query.field)
        if not isinstance(ft, TextFieldType):
            return None  # keyword/numeric terms: norms differ — planner
        return FlatQuery(query.field, [str(query.value)], query.boost, 1)
    if isinstance(query, dsl.TermsQuery):
        ft = mapper.field_type(query.field)
        if not isinstance(ft, TextFieldType):
            return None
        terms = [str(v) for v in query.values]
        if not terms:
            return None
        return FlatQuery(query.field, terms, query.boost, 1)
    if isinstance(query, dsl.BoolQuery):
        # single-field should-only bool of term/match clauses = weighted OR
        if query.must or query.must_not or query.filter:
            return None
        subs = [lower_query(q, mapper) for q in query.should]
        if not subs or any(s is None for s in subs):
            return None
        fields = {s.field for s in subs}
        if len(fields) != 1:
            return None
        if any(s.min_count != 1 for s in subs):
            return None  # nested AND semantics ≠ flat msm
        boosts = {s.boost for s in subs}
        if len(boosts) != 1:
            return None  # per-clause boosts need per-slot weights; planner
        msm = query.minimum_should_match or 1
        if msm > 1 and any(len(s.terms) != 1 for s in subs):
            # msm counts CLAUSES; flat min_count counts TERMS — only
            # identical when every clause is a single term
            return None
        terms: List[str] = []
        for s in subs:
            terms.extend(s.terms)
        return FlatQuery(fields.pop(), terms, query.boost * subs[0].boost,
                         msm)
    return None


# ---------------------------------------------------------------------------
# lowered-plan cache
# ---------------------------------------------------------------------------

def plan_key(query: dsl.QueryNode) -> Optional[Tuple]:
    """Canonical hashable key for a parsed query tree, or None when the
    tree holds something unhashable (scripts, callables) — those queries
    are simply not plan-cached. Two requests with the same query body
    parse to equal dataclass trees, so the key captures "same shape +
    same values" exactly; Zipf-distributed real traffic repeats shapes
    constantly, which is what makes memoizing lower_query worth it."""
    try:
        key = _plan_key_node(query)
        hash(key)
        return key
    except TypeError:
        return None


def _plan_key_node(value: Any) -> Any:
    if isinstance(value, dsl.QueryNode):
        parts = [type(value).__name__]
        for f in dataclasses.fields(value):
            parts.append(_plan_key_node(getattr(value, f.name)))
        return tuple(parts)
    if isinstance(value, (list, tuple)):
        return tuple(_plan_key_node(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((k, _plan_key_node(v))
                            for k, v in value.items()))
    return value


#: cached marker for "this query lowers to None" — caching the negative
#: is as valuable as the positive (the planner-path traffic re-probes
#: lowering on every request otherwise)
NOT_LOWERABLE = object()


class PlanCache:
    """LRU memo of lower_query results keyed on (index, mapping
    generation, canonical query body). Entries remember the reader_key
    of the resident pack they were validated against so a pack rebuild
    (refresh/merge mid-traffic) re-lowers instead of trusting stale
    routing; a mapping update changes the generation component, making
    every old entry unreachable (and explicitly purged via the
    invalidation seams)."""

    def __init__(self, max_entries: int = 2048):
        from collections import OrderedDict
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def get(self, key: Tuple) -> Any:
        """→ FlatQuery | NOT_LOWERABLE | None (miss)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def put(self, key: Tuple, value: Any) -> None:
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def invalidate_index(self, index_name: str) -> None:
        with self._lock:
            stale = [k for k in self._entries if k[0] == index_name]
            for k in stale:
                del self._entries[k]
            self.invalidations += len(stale)
        if stale:
            events.emit("plan_cache.invalidate", index=index_name,
                        entries=len(stale))

    def clear(self) -> None:
        with self._lock:
            dropped = len(self._entries)
            self.invalidations += dropped
            self._entries.clear()
        if dropped:
            events.emit("plan_cache.invalidate", entries=dropped,
                        reason="clear")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"size": len(self._entries),
                    "max_entries": self.max_entries,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "invalidations": self.invalidations}


# ---------------------------------------------------------------------------
# pack residency
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ResidentPack:
    """One (index, field) pack + its device arrays + provenance."""

    pack: dist.StackedShardPack
    device_arrays: Tuple
    # row → (shard_num, segment_name): resolves kernel hits back to the
    # owning IndexShard for the fetch phase
    row_origin: List[Tuple[int, str]]
    reader_key: Tuple  # identity of the readers this pack was built from
    hbm_bytes: int
    # pinned point-in-time readers per shard (the ReaderContext analog:
    # the fetch phase resolves _source against the same snapshot the
    # query phase scored, SURVEY.md §3.3)
    readers: Dict[int, Any] = dataclasses.field(default_factory=dict)
    # block-max layout (SURVEY.md §5.7): impact-descending copies of the
    # postings, host + device — pruned mode scores only each term's top
    # PREFIX_CAP entries and bounds what it skipped
    imp_host: Optional[Tuple[np.ndarray, np.ndarray]] = None
    imp_device_arrays: Optional[Tuple] = None
    # vectorized hit resolution (VERDICT r3 #1): one fancy-index resolves
    # a whole [B, k] kernel result to external ids/shards — no per-hit
    # Python on the serving path
    row_shard: Optional[np.ndarray] = None    # int32[S_pad], -1 = padding
    row_offset: Optional[np.ndarray] = None   # int64[S_pad] into id_cat
    id_cat: Optional[np.ndarray] = None       # object[total_docs] ext ids
    row_segments: Optional[List[Any]] = None  # row → Segment (pinned)
    # terms-tuple → _slots_needed result. The slot count depends only on
    # this pack's postings lengths, so the memo lives (and dies) with the
    # pack — a rebuild starts fresh, no invalidation protocol needed.
    slots_memo: Dict[Tuple[str, ...], int] = dataclasses.field(
        default_factory=dict)
    # compressed resident format (PERF.md round 11): host-side 16-bit
    # streams + residual tables. When set, device_arrays is the 5-tuple
    # from device_put_compressed (6-tuple with the delta doc stream's
    # base column, PR 15), there is no f32 posting copy on device and no
    # impact-sorted copy at all (imp_host/imp_device_arrays stay None →
    # every query routes to the exact kernel in a compressed variant)
    comp_streams: Optional[dist.CompressedStreams] = None
    # per-pack HBM accounting detail for /_tpu/stats and the Prometheus
    # pack families: raw vs resident bytes, ratio, block metadata, docs
    hbm_detail: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # placement (fault-domain) residency: when this pack is one replica
    # of an R-way placement, the group's sub-mesh its arrays live on —
    # launches MUST use it (a strict subset of the full mesh). None =
    # single-group serving, launches use the batcher's mesh unchanged.
    group_mesh: Optional[Any] = None
    group_id: Optional[int] = None

    @property
    def compressed(self) -> bool:
        return self.comp_streams is not None

    def resolve_ids(self, rows: np.ndarray, ords: np.ndarray) -> np.ndarray:
        """(pack row, local ordinal) → external _id, vectorized."""
        if len(rows) == 0:
            return np.empty(0, dtype=object)
        return self.id_cat[self.row_offset[rows] + ords]


# -- streaming delta chain (LSM resident path) ------------------------------
#
# Append-only refreshes build a SMALL delta pack from only the new
# segments instead of re-placing the whole (index, field) image; searches
# run the kernel on base + each delta and union the per-pack top-ks
# host-side (ops/sparse.union_topk). A background compactor folds the
# chain back into one full (compressed) base pack. A doc lives in exactly
# one pack: an update/delete of a committed doc mutates a live mask,
# which bumps the engine's live_version and forces a full rebuild — the
# delta path is append-only by construction.

#: chaos seam (tests): each hook is called with the (index, field) key at
#: the top of every compaction and may block or raise — "kill lands
#: mid-compaction" is a hook that parks until the batcher dies.
COMPACTION_FAULT_HOOKS: List[Any] = []


@dataclasses.dataclass
class DeltaStats:
    """Node-wide delta lifecycle counters (rendered by node.py as the
    ``es_tpu_delta_*`` Prometheus families)."""

    appends: int = 0              # delta packs built
    seals: int = 0                # delta packs made immutable on device
    compactions: int = 0
    compaction_failures: int = 0
    replayed_ops: int = 0         # via supervisor recovery replay
    compact_seconds: float = 0.0  # cumulative wall time folding chains


@dataclasses.dataclass
class _ChainMeta:
    """What the delta chain currently covers, per shard: the chain serves
    exactly `reader_key`; a new reader is delta-eligible iff every
    shard's covered segments are a PREFIX of its segments and its
    live_version is unchanged."""

    reader_key: Tuple
    covered: Dict[int, Tuple[str, ...]]
    live_versions: Dict[int, int]
    union: Optional["_UnionView"] = None


@dataclasses.dataclass
class PackChain:
    """Resolved residency for one (index, field): the base pack, the
    delta packs chained on it, and the row-space view results resolve
    against (`base` itself when the chain is empty)."""

    base: ResidentPack
    deltas: Tuple[ResidentPack, ...]
    view: Any
    reader_key: Tuple


class _UnionView:
    """Read-only facade over base + delta packs presenting ONE
    concatenated row/id space to the fetch phase. Pack i's kernel rows
    re-base by ``offsets[i]`` (running sum of padded row counts); id
    ordinals re-base via concatenated row_offset/id_cat tables. Exposes
    exactly the members the serializer and columnar fetch consume
    (resolve_ids / row_origin / row_segments / row_shard / readers)."""

    def __init__(self, packs: List[ResidentPack]):
        self.packs = tuple(packs)
        offsets: List[int] = []
        off = 0
        id_off = 0
        row_origin: List[Tuple[int, str]] = []
        row_segments: List[Any] = []
        shard_parts, off_parts, id_parts = [], [], []
        for p in self.packs:
            offsets.append(off)
            s_pad = p.pack.num_shards
            ro = list(p.row_origin)
            ro += [(-1, "")] * (s_pad - len(ro))
            row_origin.extend(ro)
            rs = list(p.row_segments or ())
            rs += [None] * (s_pad - len(rs))
            row_segments.extend(rs)
            shard_parts.append(p.row_shard)
            off_parts.append(p.row_offset + id_off)
            id_parts.append(p.id_cat)
            id_off += len(p.id_cat)
            off += s_pad
        self.offsets = tuple(offsets)
        self.row_origin = row_origin
        self.row_segments = row_segments
        self.row_shard = np.concatenate(shard_parts)
        self.row_offset = np.concatenate(off_parts)
        self.id_cat = np.concatenate(id_parts)
        base = self.packs[0]
        self.pack = base.pack          # stats consumers see the base
        self.readers = base.readers
        self.reader_key = base.reader_key  # kept current by the chain
        self.hbm_bytes = sum(int(p.hbm_bytes) for p in self.packs)
        self.hbm_detail = dict(base.hbm_detail)
        self.comp_streams = None
        self.group_mesh = base.group_mesh
        self.group_id = base.group_id

    @property
    def compressed(self) -> bool:
        return False

    def resolve_ids(self, rows: np.ndarray, ords: np.ndarray) -> np.ndarray:
        if len(rows) == 0:
            return np.empty(0, dtype=object)
        return self.id_cat[self.row_offset[rows] + ords]


class IndexPackCache:
    """Builds and caches the StackedShardPack for an (index, field).

    The cache key is the tuple of per-shard reader identities: engine
    refresh/merge swaps the reader object, so identity equality is exactly
    "segments or live-docs changed". HBM bytes are charged to the `hbm`
    breaker before device placement and released on eviction."""

    def __init__(self, mesh=None, breaker=None, group_id=None):
        self._mesh = mesh
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[str, str], ResidentPack] = {}
        self._breaker = breaker
        # fault-domain placement: a group-scoped cache stamps its id and
        # sub-mesh onto every pack it builds so launches route to the
        # group's devices (None = the classic whole-mesh cache)
        self.group_id = group_id
        # per-key build serialization: a refresh-triggered rebuild of one
        # (index, field) pack must not block fast-path lookups of every
        # other key on the node (ADVICE r2 low #4)
        self._build_locks: Dict[Tuple[str, str], threading.Lock] = {}
        # on_evict(old_resident): set by TpuSearchService so eviction
        # also retires the pack's micro-batch queue (its strong ref
        # would otherwise pin the freed device arrays)
        self.on_evict = None
        self.hits = 0          # lookups served by the current pack
        self.misses = 0        # lookups that (re)built a pack
        self.stale_served = 0  # lookups served stale during a rebuild
        # warmth (last-access stamp) and last-known HBM cost per key.
        # Both SURVIVE invalidate_all: partial-mesh recovery orders
        # re-residency warmest-first and projects bytes against the
        # shrunken headroom before rebuilding anything.
        self._heat: Dict[Tuple[str, str], float] = {}
        self._last_bytes: Dict[Tuple[str, str], int] = {}
        # -- streaming delta chain state -------------------------------
        self.delta_enabled = False
        self.delta_max_packs = 4       # chain length that requests a fold
        self.delta_max_docs = 50_000   # total delta docs that request one
        self.delta_stats: Optional[DeltaStats] = None
        self.on_compact_needed = None  # callable(key), set by the service
        self._deltas: Dict[Tuple[str, str], List[ResidentPack]] = {}
        self._chain_meta: Dict[Tuple[str, str], _ChainMeta] = {}
        self._services: Dict[Tuple[str, str], Any] = {}  # compactor's map

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            # per-(index,field) HBM breakdown: raw vs resident bytes,
            # ratio, block metadata — the /_tpu/stats + Prometheus view
            # of the compressed-pack capacity win
            packs = {f"{idx}/{field}": dict(entry.hbm_detail)
                     for (idx, field), entry in self._cache.items()}
            deltas = {
                f"{idx}/{field}": {
                    "packs": len(lst),
                    "bytes": sum(int(p.hbm_bytes) for p in lst),
                    "docs": sum(int(p.hbm_detail.get("docs", 0))
                                for p in lst)}
                for (idx, field), lst in self._deltas.items() if lst}
            return {"resident": len(self._cache), "hits": self.hits,
                    "misses": self.misses,
                    "stale_served": self.stale_served,
                    "packs": packs, "deltas": deltas}

    def delta_totals(self) -> Tuple[int, int]:
        """(resident delta packs, resident delta bytes) on this cache."""
        with self._lock:
            n = sum(len(lst) for lst in self._deltas.values())
            b = sum(int(p.hbm_bytes) for lst in self._deltas.values()
                    for p in lst)
            return n, b

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh(shape=(1, _n_local_devices()))
        return self._mesh

    def set_mesh(self, mesh) -> None:
        """Re-target future builds at a different mesh (partial-mesh
        recovery). Only sound on an EMPTY cache — existing packs were
        placed with the old sharding — so callers invalidate first."""
        with self._lock:
            if self._cache:
                raise RuntimeError("set_mesh on a non-empty pack cache; "
                                   "invalidate_all first")
            self._mesh = mesh

    def heat_of(self, key: Tuple[str, str]) -> float:
        with self._lock:
            return self._heat.get(key, 0.0)

    def peek(self, key: Tuple[str, str]) -> Optional[ResidentPack]:
        """Current resident for `key` without building (placement's
        live-replica check)."""
        with self._lock:
            return self._cache.get(tuple(key))

    def resident_keys(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._cache)

    def residents(self) -> List[ResidentPack]:
        with self._lock:
            return list(self._cache.values())

    def bytes_of(self, key: Tuple[str, str]) -> int:
        with self._lock:
            return self._last_bytes.get(key, 0)

    def get(self, index_service, field: str) -> Optional[ResidentPack]:
        readers = []
        for shard_num, shard in sorted(index_service.shards.items()):
            readers.append((shard_num, shard.acquire_searcher()))
        reader_key = tuple(id(r) for _, r in readers)
        key = (index_service.name, field)
        with self._lock:
            self._heat[key] = time.monotonic()
            entry = self._cache.get(key)
            if entry is not None and entry.reader_key == reader_key:
                self.hits += 1
                return entry
            build_lock = self._build_locks.setdefault(key,
                                                      threading.Lock())
        # STALE-WHILE-REBUILD (the reference serves the old reader while
        # a refresh opens the new one): if another thread is already
        # rebuilding this key, serve the previous pack instead of
        # queueing behind a minutes-long build — a background merge
        # completing mid-traffic must not stall every search into the
        # batch timeout (observed at 2.6M docs: ~150s pack build →
        # timeout storm → kernel breaker trip). Staleness is bounded by
        # one refresh lag, the same window the reference exposes.
        if not build_lock.acquire(blocking=False):
            with self._lock:
                entry = self._cache.get(key)
                if entry is not None:
                    self.stale_served += 1
            if entry is not None:
                return entry
            build_lock.acquire()  # no old pack — must wait for a build
        try:
            with self._lock:
                entry = self._cache.get(key)
                if entry is not None and entry.reader_key == reader_key:
                    self.hits += 1
                    return entry
                self.misses += 1
            entry = self._build(readers, field, reader_key)
            old = None
            dropped: List[ResidentPack] = []
            with self._lock:
                if entry is not None:
                    old = self._cache.get(key)
                    if old is not None and self._breaker is not None:
                        self._breaker.release(old.hbm_bytes)
                    self._cache[key] = entry
                    self._last_bytes[key] = int(entry.hbm_bytes)
                    # a full rebuild covers everything the chain did —
                    # the folded deltas drain to exactly zero
                    dropped = self._drop_deltas_locked(key)
                    self._set_chain_meta_locked(key, readers, reader_key)
            if entry is not None:
                events.emit("pack.build", index=key[0], field=key[1],
                            hbm_bytes=int(entry.hbm_bytes),
                            compressed=entry.compressed,
                            rebuild=old is not None,
                            group=self.group_id)
            if self.on_evict is not None:
                for stale in ([old] if old is not None else []) + dropped:
                    self.on_evict(stale)
            return entry
        finally:
            build_lock.release()

    # -- streaming delta chain -----------------------------------------

    def _drop_deltas_locked(self, key) -> List[ResidentPack]:
        """Release every delta chained on `key` (caller holds _lock and
        runs on_evict after dropping it)."""
        dropped = self._deltas.pop(key, [])
        for p in dropped:
            if self._breaker is not None:
                self._breaker.release(p.hbm_bytes)
        meta = self._chain_meta.get(key)
        if meta is not None:
            meta.union = None
        return dropped

    def _set_chain_meta_locked(self, key, readers, reader_key) -> None:
        if not self.delta_enabled:
            return
        self._chain_meta[key] = _ChainMeta(
            reader_key=reader_key,
            covered={num: tuple(v.segment.name for v in r.views)
                     for num, r in readers},
            live_versions={num: getattr(r, "live_version", 0)
                           for num, r in readers})

    def _chain_locked(self, key) -> Optional[PackChain]:
        base = self._cache.get(key)
        meta = self._chain_meta.get(key)
        if base is None or meta is None:
            return None
        deltas = tuple(self._deltas.get(key, ()))
        if not deltas:
            return PackChain(base, (), base, meta.reader_key)
        return PackChain(base, deltas, meta.union, meta.reader_key)

    def _delta_eligible(self, meta: _ChainMeta, readers):
        """Append-only check, per shard: the chain's covered segments
        must be a PREFIX of the new reader's and its live_version
        unchanged (an update/delete of a committed doc bumps it).
        Returns {shard_num: [uncovered SegmentViews]} or None → full
        rebuild."""
        new = dict(readers)
        if set(new) != set(meta.covered):
            return None
        fresh: Dict[int, List[Any]] = {}
        for num, r in new.items():
            names = tuple(v.segment.name for v in r.views)
            old = meta.covered[num]
            if names[:len(old)] != old:
                return None
            if getattr(r, "live_version", 0) != meta.live_versions.get(
                    num, 0):
                return None
            fresh[num] = list(r.views[len(old):])
        return fresh

    def get_chain(self, index_service, field: str) -> Optional[PackChain]:
        """Chain-aware residency: like get(), but an append-only refresh
        builds a small delta pack over only the NEW segments instead of
        re-placing the whole image."""
        if not self.delta_enabled:
            entry = self.get(index_service, field)
            return None if entry is None else PackChain(
                entry, (), entry, entry.reader_key)
        readers = []
        for shard_num, shard in sorted(index_service.shards.items()):
            readers.append((shard_num, shard.acquire_searcher()))
        reader_key = tuple(id(r) for _, r in readers)
        key = (index_service.name, field)
        with self._lock:
            self._heat[key] = time.monotonic()
            self._services[key] = index_service
            chain = self._chain_locked(key)
            if chain is None:
                # base resident but never chained (built via get())
                entry = self._cache.get(key)
                if entry is not None and entry.reader_key == reader_key:
                    self._set_chain_meta_locked(key, readers, reader_key)
                    chain = self._chain_locked(key)
            if chain is not None and chain.reader_key == reader_key:
                self.hits += 1
                return chain
            build_lock = self._build_locks.setdefault(key,
                                                      threading.Lock())
        # stale-while-rebuild applies to the chain exactly as to get()
        if not build_lock.acquire(blocking=False):
            with self._lock:
                chain = self._chain_locked(key)
                if chain is not None:
                    self.stale_served += 1
            if chain is not None:
                return chain
            build_lock.acquire()
        try:
            with self._lock:
                chain = self._chain_locked(key)
                if chain is not None and chain.reader_key == reader_key:
                    self.hits += 1
                    return chain
                base = self._cache.get(key)
                meta = self._chain_meta.get(key)
            fresh = None
            if base is not None and meta is not None:
                fresh = self._delta_eligible(meta, readers)
            if fresh is None:
                entry = self._build_and_swap(key, readers, field,
                                             reader_key)
                return None if entry is None else PackChain(
                    entry, (), entry, reader_key)
            return self._append_delta(key, base, fresh, readers, field,
                                      reader_key)
        finally:
            build_lock.release()

    def _build_and_swap(self, key, readers, field,
                        reader_key) -> Optional[ResidentPack]:
        """Full build + swap, chain reset. Caller holds the build lock."""
        with self._lock:
            self.misses += 1
        entry = self._build(readers, field, reader_key)
        old = None
        dropped: List[ResidentPack] = []
        with self._lock:
            if entry is not None:
                old = self._cache.get(key)
                if old is not None and self._breaker is not None:
                    self._breaker.release(old.hbm_bytes)
                self._cache[key] = entry
                self._last_bytes[key] = int(entry.hbm_bytes)
                dropped = self._drop_deltas_locked(key)
                self._set_chain_meta_locked(key, readers, reader_key)
        if entry is not None:
            events.emit("pack.build", index=key[0], field=key[1],
                        hbm_bytes=int(entry.hbm_bytes),
                        compressed=entry.compressed,
                        rebuild=old is not None, group=self.group_id)
        if self.on_evict is not None:
            for stale in ([old] if old is not None else []) + dropped:
                self.on_evict(stale)
        return entry

    def _append_delta(self, key, base: ResidentPack, fresh, readers,
                      field: str, reader_key) -> PackChain:
        """Build one immutable delta pack from the uncovered segments
        and chain it on the base. Caller holds the build lock."""
        docs = sum(v.segment.num_docs for views in fresh.values()
                   for v in views
                   if field in v.segment.postings)
        events.emit("delta.append", index=key[0], field=field,
                    docs=int(docs),
                    segments=sum(len(v) for v in fresh.values()))
        delta = self._build_delta(readers, fresh, field, reader_key)
        want_compact = False
        with self._lock:
            if delta is not None:
                self._deltas.setdefault(key, []).append(delta)
            # even a field-less delta advances coverage: the chain now
            # answers for this reader set
            self._set_chain_meta_locked(key, readers, reader_key)
            meta = self._chain_meta[key]
            deltas = list(self._deltas.get(key, ()))
            if deltas:
                base_ = self._cache[key]
                meta.union = _UnionView([base_] + deltas)
                meta.union.reader_key = reader_key
                total_docs = sum(
                    int(p.hbm_detail.get("docs", 0)) for p in deltas)
                want_compact = (len(deltas) > self.delta_max_packs
                                or total_docs > self.delta_max_docs)
            chain = self._chain_locked(key)
        if delta is not None:
            if self.delta_stats is not None:
                self.delta_stats.appends += 1
                self.delta_stats.seals += 1
            events.emit("delta.seal", index=key[0], field=field,
                        hbm_bytes=int(delta.hbm_bytes),
                        chain_len=len(chain.deltas))
        if want_compact and self.on_compact_needed is not None:
            self.on_compact_needed(key)
        return chain

    def _build_delta(self, readers, fresh, field: str,
                     reader_key) -> Optional[ResidentPack]:
        segments, live, groups = [], [], []
        row_origin: List[Tuple[int, str]] = []
        row_segments: List[Any] = []
        for group_idx, (shard_num, _reader) in enumerate(readers):
            for view in fresh.get(shard_num, ()):
                if field not in view.segment.postings:
                    continue
                segments.append(view.segment)
                n = view.segment.num_docs
                live.append(view.live_mask[:n].copy())
                groups.append(group_idx)
                row_origin.append((shard_num, view.segment.name))
                row_segments.append(view.segment)
        if not segments:
            return None
        k1 = readers[0][1].k1
        b = readers[0][1].b
        n_sh = self.mesh.shape[SHARD_AXIS]
        s_pad = ((len(segments) + n_sh - 1) // n_sh) * n_sh
        pack = dist.build_delta_pack(segments, field, live_docs=live,
                                     k1=k1, b=b, pad_shards_to=s_pad,
                                     row_groups=groups)
        return self._place_pack(pack, field, readers, reader_key,
                                row_origin, row_segments,
                                label=f"delta[{field}]",
                                compressible=False)

    def compact(self, key) -> bool:
        """Fold the delta chain into a fresh full (compressed) base pack.
        Releases the old base + every delta exactly (the drain-to-zero
        invariant covers compaction too); on failure the chain keeps
        serving and a `compaction_failure` incident is opened."""
        index_service = self._services.get(key)
        if index_service is None:
            return False
        field = key[1]
        with self._lock:
            build_lock = self._build_locks.setdefault(key,
                                                      threading.Lock())
        with build_lock:
            with self._lock:
                deltas = list(self._deltas.get(key, ()))
            if not deltas:
                return False
            delta_bytes = sum(int(p.hbm_bytes) for p in deltas)
            t0 = time.monotonic()
            events.emit("compaction.begin", index=key[0], field=field,
                        delta_packs=len(deltas),
                        delta_bytes=delta_bytes)
            try:
                for hook in list(COMPACTION_FAULT_HOOKS):
                    hook(key)  # chaos seam: may park or raise
                readers = []
                for shard_num, shard in sorted(
                        index_service.shards.items()):
                    readers.append((shard_num, shard.acquire_searcher()))
                reader_key = tuple(id(r) for _, r in readers)
                entry = self._build(readers, field, reader_key)
            except Exception as exc:  # noqa: BLE001 — chain keeps serving
                if self.delta_stats is not None:
                    self.delta_stats.compaction_failures += 1
                events.emit("compaction.end", severity="error",
                            index=key[0], field=field, error=str(exc),
                            duration_s=round(time.monotonic() - t0, 6))
                events.incident("compaction_failure", index=key[0],
                                field=field, error=str(exc))
                return False
            evicted: List[ResidentPack] = []
            with self._lock:
                if entry is not None:
                    old = self._cache.get(key)
                    if old is not None and self._breaker is not None:
                        self._breaker.release(old.hbm_bytes)
                        evicted.append(old)
                    self._cache[key] = entry
                    self._last_bytes[key] = int(entry.hbm_bytes)
                    evicted += self._drop_deltas_locked(key)
                    self._set_chain_meta_locked(key, readers, reader_key)
            if self.on_evict is not None:
                for stale in evicted:
                    self.on_evict(stale)
            dur = time.monotonic() - t0
            if self.delta_stats is not None:
                self.delta_stats.compactions += 1
                self.delta_stats.compact_seconds += dur
            events.emit("compaction.end", index=key[0], field=field,
                        duration_s=round(dur, 6),
                        reclaimed_bytes=delta_bytes,
                        hbm_bytes=(int(entry.hbm_bytes)
                                   if entry is not None else 0))
            return entry is not None

    def _build(self, readers, field: str,
               reader_key: Tuple) -> Optional[ResidentPack]:
        segments = []
        live = []
        groups = []
        row_origin: List[Tuple[int, str]] = []
        row_segments: List[Any] = []
        for group_idx, (shard_num, reader) in enumerate(readers):
            for view in reader.views:
                if field not in view.segment.postings:
                    continue
                segments.append(view.segment)
                n = view.segment.num_docs
                live.append(view.live_mask[:n].copy())
                groups.append(group_idx)
                row_origin.append((shard_num, view.segment.name))
                row_segments.append(view.segment)
        if not segments:
            return None
        k1 = readers[0][1].k1
        b = readers[0][1].b
        # pad rows to a multiple of the mesh's shards axis
        n_sh = self.mesh.shape[SHARD_AXIS]
        s_pad = ((len(segments) + n_sh - 1) // n_sh) * n_sh
        pack = dist.build_stacked_pack(segments, field, live_docs=live,
                                       k1=k1, b=b, pad_shards_to=s_pad,
                                       row_groups=groups)
        return self._place_pack(pack, field, readers, reader_key,
                                row_origin, row_segments,
                                label=f"pack[{field}]", compressible=True)

    def _place_pack(self, pack, field: str, readers, reader_key: Tuple,
                    row_origin, row_segments, *, label: str,
                    compressible: bool) -> ResidentPack:
        """Charge the breaker, place `pack` on device, build resolution
        tables. `compressible=False` (delta packs) forces the raw format:
        deltas are small and short-lived — compaction folds them into
        the compressed base, so per-delta stream compression would buy
        bytes at the cost of append latency."""
        # what the uncompressed resident image costs: doc-sorted pack +
        # the impact-sorted copy (same two arrays re-ordered) — the
        # baseline both /_tpu/stats' compression_ratio and the bench's
        # hbm_bytes_per_doc compare against
        raw_bytes = (pack.nbytes_device() + pack.flat_docs.nbytes
                     + pack.flat_impact.nbytes)
        n_docs = int(sum(len(ids) for ids in pack.shard_doc_ids))
        streams = None
        comp_reason = None
        if compressible and KERNEL_CONFIG["compressed_pack"]:
            comp_reason = dist.compress_pack_reason(pack)
            if comp_reason is None:
                streams = dist.build_compressed_streams(pack)
            else:
                logger.info("pack[%s] not compressible (%s); resident "
                            "in raw format", field, comp_reason)
        if streams is not None:
            # compressed residency: the 16-bit streams + block metadata +
            # residual tables are the WHOLE device image — no f32 copy,
            # no impact-sorted copy, no pruned path
            hbm = streams.nbytes_device()
            if self._breaker is not None:
                self._breaker.add_estimate_bytes_and_maybe_break(
                    hbm, label=label)
            try:
                arrays = dist.device_put_compressed(streams, self.mesh)
            except Exception:
                if self._breaker is not None:
                    self._breaker.release(hbm)
                raise
            imp_docs = imp_impacts = None
            imp_arrays = None
        else:
            imp_docs, imp_impacts = dist.build_impact_sorted(pack)
            hbm = (pack.nbytes_device() + imp_docs.nbytes
                   + imp_impacts.nbytes)
            if self._breaker is not None:
                self._breaker.add_estimate_bytes_and_maybe_break(
                    hbm, label=label)
            try:
                arrays = dist.device_put_pack(pack, self.mesh)
                imp_arrays = dist.device_put_pack(
                    dataclasses.replace(pack, flat_docs=imp_docs,
                                        flat_impact=imp_impacts), self.mesh)
            except Exception:
                if self._breaker is not None:  # undo the charge on failure
                    self._breaker.release(hbm)
                raise
        n_postings = int(sum(int(rs[-1]) for rs in pack.row_starts))
        hbm_detail = {
            "compressed": streams is not None,
            "hbm_bytes": int(hbm),
            "raw_bytes": int(raw_bytes),
            "compression_ratio": (float(hbm) / raw_bytes if raw_bytes
                                  else 1.0),
            "block_meta_bytes": (int(streams.block_max.nbytes)
                                 if streams is not None else 0),
            "residual_bytes": (int(streams.res_vals.nbytes)
                               if streams is not None else 0),
            # delta doc stream (PR 15): u8 block-relative deltas + u16
            # per-block bases instead of the u16 doc stream — the bytes
            # the "≤ 6 B/posting" acceptance is accounted against
            "doc_delta": streams is not None and streams.delta,
            "doc_base_bytes": (int(streams.doc_bases.nbytes)
                               if streams is not None and streams.delta
                               else 0),
            "docs": n_docs,
            "hbm_bytes_per_doc": (float(hbm) / n_docs if n_docs else 0.0),
            "postings": n_postings,
            "hbm_bytes_per_posting": (float(hbm) / n_postings
                                      if n_postings else 0.0),
        }
        if comp_reason is not None:
            hbm_detail["compress_reason"] = comp_reason
        # vectorized-resolution tables: row → owning shard, row → offset
        # into one concatenated external-id array (object dtype: fancy
        # indexing is C-speed, the per-hit Python lookup is gone)
        s_pad = pack.num_shards
        row_shard = np.full(s_pad, -1, dtype=np.int32)
        row_shard[: len(row_origin)] = [sn for sn, _ in row_origin]
        sizes = [len(ids) for ids in pack.shard_doc_ids]
        row_offset = np.zeros(s_pad, dtype=np.int64)
        np.cumsum(sizes[:-1], out=row_offset[1:len(sizes)])
        id_cat = np.empty(int(sum(sizes)), dtype=object)
        off = 0
        for ids in pack.shard_doc_ids:
            id_cat[off: off + len(ids)] = ids
            off += len(ids)
        return ResidentPack(pack, arrays, row_origin, reader_key, hbm,
                            readers={num: r for num, r in readers},
                            imp_host=(None if imp_docs is None
                                      else (imp_docs, imp_impacts)),
                            imp_device_arrays=imp_arrays,
                            row_shard=row_shard, row_offset=row_offset,
                            id_cat=id_cat, row_segments=row_segments,
                            comp_streams=streams, hbm_detail=hbm_detail,
                            group_mesh=(self.mesh if self.group_id
                                        is not None else None),
                            group_id=self.group_id)

    def invalidate(self, index_name: str) -> None:
        evicted = []
        with self._lock:
            for key in [k for k in self._cache if k[0] == index_name]:
                entry = self._cache.pop(key)
                if self._breaker is not None:
                    self._breaker.release(entry.hbm_bytes)
                evicted.append(entry)
            for key in [k for k in self._deltas if k[0] == index_name]:
                evicted.extend(self._drop_deltas_locked(key))
            # deliberate eviction forgets the key entirely (unlike
            # invalidate_all, whose keys recovery re-attains)
            for key in [k for k in self._heat if k[0] == index_name]:
                self._heat.pop(key, None)
                self._last_bytes.pop(key, None)
                self._chain_meta.pop(key, None)
                self._services.pop(key, None)
        if evicted:
            events.emit("pack.evict", index=index_name,
                        packs=len(evicted),
                        hbm_bytes=sum(int(e.hbm_bytes) for e in evicted),
                        group=self.group_id)
        if self.on_evict is not None:
            for entry in evicted:
                self.on_evict(entry)

    def invalidate_all(self) -> List[Tuple[str, str]]:
        """Crash-recovery drop of EVERY resident pack (the batcher
        supervisor's respawn path): each pack's full charge is released,
        so afterwards the `hbm` breaker reads EXACTLY zero — the same
        drain-to-zero invariant the per-index lifecycle tests assert.
        Returns the dropped (index, field) keys so recovery can
        re-attain residency eagerly."""
        dropped: List[ResidentPack] = []
        with self._lock:
            entries = list(self._cache.items())
            self._cache.clear()
            for _key, entry in entries:
                if self._breaker is not None:
                    self._breaker.release(entry.hbm_bytes)
            for key in list(self._deltas):
                dropped.extend(self._drop_deltas_locked(key))
            # chain coverage died with the packs; recovery re-attains
            # residency through a full rebuild which re-stamps it
            self._chain_meta.clear()
        if self.on_evict is not None:
            for _key, entry in entries:
                self.on_evict(entry)
            for entry in dropped:
                self.on_evict(entry)
        return [key for key, _entry in entries]


# ---------------------------------------------------------------------------
# micro-batching
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Pending:
    flat: FlatQuery
    k: int
    future: Future
    # the submitting request's span (None when untraced): batch workers
    # parent their launch/device spans under the FIRST traced query of
    # the train so a trace shows which batch served it
    trace_span: Any = None
    # batch_wait decomposition marks (perf_counter). Workers stamp
    # cycle/take/launched; the REQUEST thread reads them back after
    # `future.result()` so the four sub-stages sum exactly to the
    # legacy `batch_wait` measured on the same thread.
    t_submit: float = 0.0
    t_cycle: float = 0.0
    t_take: float = 0.0
    t_launched: float = 0.0
    # owning tenant (stamped on the request thread): batch composition
    # takes weighted round-robin across tenant lanes so one tenant's
    # burst can't monopolize batch slots ahead of tenants already
    # waiting — the starved lane's cost would show up as batch_wait.queue
    tenant: str = tenancy.DEFAULT_TENANT


def _batch_bucket(n: int, cap: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def _take_fair(pendings: List[_Pending], cap: int,
               weight_of) -> Tuple[List[_Pending], List[_Pending]]:
    """Compose one batch train of up to `cap` queries from `pendings`
    by weighted round-robin across tenant lanes → (taken, remaining).

    Each waiting tenant gets a quota proportional to its weight (never
    below 1 slot, so no lane starves); lanes are drained one query at a
    time in rotation, FIFO within a lane. Leftover capacity after every
    quota is met is filled ignoring quotas — a full train always beats
    strict proportionality (padding is already paid). The overwhelmingly
    common single-tenant case returns a plain slice."""
    if len(pendings) <= cap:
        return pendings, []
    first = pendings[0].tenant
    if all(p.tenant == first for p in pendings):
        return pendings[:cap], pendings[cap:]
    lanes: Dict[str, List[_Pending]] = {}
    order: List[str] = []
    for p in pendings:
        lane = lanes.get(p.tenant)
        if lane is None:
            lanes[p.tenant] = lane = []
            order.append(p.tenant)
        lane.append(p)
    weights = {t: max(1e-6, float(weight_of(t))) for t in order}
    total = sum(weights.values())
    quota = {t: max(1, int(cap * weights[t] / total)) for t in order}
    taken: List[_Pending] = []
    cursor = {t: 0 for t in order}
    enforce_quota = True
    while len(taken) < cap:
        progressed = False
        for t in order:
            if len(taken) >= cap:
                break
            i = cursor[t]
            if i >= len(lanes[t]) or (enforce_quota and i >= quota[t]):
                continue
            taken.append(lanes[t][i])
            cursor[t] = i + 1
            progressed = True
        if not progressed:
            if enforce_quota:
                enforce_quota = False
                continue
            break
    taken_ids = {id(p) for p in taken}
    # remainder keeps the original arrival order (lane concatenation
    # would distort the next train's rotation and the queue-wait marks)
    remaining = [p for p in pendings if id(p) not in taken_ids]
    return taken, remaining


class _PackQueue:
    """One pack's pending queries + a launch worker + a completion
    thread. Packs batch independently, so pack A's kernel launch
    (including a first-compile stall) never delays pack B's queries
    (VERDICT r2 weak #10). Launch and completion are SPLIT so batch N+1
    is prepped and dispatched while batch N still executes on device —
    JAX async dispatch double-buffers the kernel (VERDICT r3 #1d); the
    bounded in-flight queue is the backpressure."""

    IDLE_EXIT_S = 60.0
    PIPELINE_DEPTH = 3

    def __init__(self, batcher: "MicroBatcher", resident: ResidentPack):
        import queue as _queue
        self.batcher = batcher
        self.resident = resident
        self.cv = threading.Condition()
        self.pendings: List[_Pending] = []
        self.closed = False
        # launched-but-not-finished batches; inflight.qsize() is NOT a
        # busy signal (the completer dequeues before materializing)
        self.n_inflight = 0
        self.inflight: Any = _queue.Queue(maxsize=self.PIPELINE_DEPTH)
        self.completer = threading.Thread(target=self._complete,
                                          daemon=True,
                                          name="micro-batcher-complete")
        self.completer.start()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="micro-batcher-pack")
        self.thread.start()

    def submit(self, pending: _Pending) -> bool:
        with self.cv:
            if self.closed:
                return False
            self.pendings.append(pending)
            self.cv.notify_all()
            return True

    def launch_mesh(self):
        """The mesh this queue's launches run on: the resident's
        placement-group sub-mesh when the pack is group-placed, else
        the batcher-wide mesh (single-group serving, unchanged)."""
        return getattr(self.resident, "group_mesh", None) \
            or self.batcher.mesh

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()

    def _run(self) -> None:
        batcher = self.batcher
        try:
            while True:
                retire = False
                taken: List[_Pending] = []
                with self.cv:
                    idle_deadline = time.monotonic() + self.IDLE_EXIT_S
                    while not self.pendings and not self.closed:
                        remaining = idle_deadline - time.monotonic()
                        if remaining <= 0:
                            # idle: retire this queue (a fresh one spawns
                            # on the next query; stale queues don't leak)
                            self.closed = True
                            retire = True
                            break
                        self.cv.wait(timeout=remaining)
                    if not retire:
                        if self.closed and not self.pendings:
                            return
                        # adaptive window: launch a FULL batch any time,
                        # but while the device is busy with an in-flight
                        # batch keep accumulating — per-launch cost is
                        # ~fixed, so more/smaller launches lose (the
                        # completer notifies when a batch finishes).
                        # After having waited on a busy device, hold one
                        # REFILL window so the just-released cohort
                        # (still assembling its responses under the GIL)
                        # makes this train instead of fragmenting into
                        # the next one. An idle device pays only
                        # window_s — no refill, no latency floor.
                        deadline = time.monotonic() + batcher.window_s
                        waited_busy = False
                        # a HALF-full train launches even while the
                        # device is busy (pipeline depth > 1 must not
                        # require a completely full queue — with C
                        # concurrent clients the queue can never exceed
                        # C minus in-flight, so gating on max_batch
                        # serializes trains when C ≈ max_batch)
                        pipeline_min = max(8, batcher.max_batch // 2)
                        t_cycle = time.perf_counter()
                        while (len(self.pendings) < batcher.max_batch
                               and not self.closed):
                            now = time.monotonic()
                            if now >= deadline:
                                if self.n_inflight > 0 and \
                                        len(self.pendings) < pipeline_min:
                                    waited_busy = True
                                    self.cv.wait(timeout=0.25)
                                    continue
                                if not waited_busy:
                                    break
                                # one refill window after a busy wait:
                                # the just-released cohort joins THIS
                                # train — fuller trains beat an instant
                                # launch (measured: trains shrink to
                                # ~bucket-half and padding wins without
                                # this)
                                waited_busy = False
                                deadline = now + max(
                                    0.05, batcher.window_s)
                                continue
                            self.cv.wait(timeout=deadline - now)
                        taken, self.pendings = _take_fair(
                            self.pendings, batcher.max_batch,
                            batcher.tenant_weight)
                        t_take = time.perf_counter()
                        for p in taken:
                            p.t_cycle = t_cycle
                            p.t_take = t_take
                if retire:
                    # NEVER hold cv while taking the batcher lock
                    # (submit's get/create path holds it before us)
                    batcher._retire(self)
                    return
                if not taken:
                    continue
                trace_parent = next(
                    (p.trace_span for p in taken if p.trace_span), None)
                try:
                    profiler.tag_stage("batch_launch")
                    # deadline-stamped dispatch: if this launch wedges,
                    # the watchdog fails `taken` typed and trips the
                    # supervisor instead of hanging the micro-batcher
                    wd = batcher.watchdog
                    mesh = self.launch_mesh()
                    token = (wd.begin("launch", taken,
                                      devices=_mesh_device_ids(mesh))
                             if wd is not None else None)
                    try:
                        with tracing.span_under(trace_parent,
                                                "tpu.batch_launch",
                                                queries=len(taken)):
                            st = launch_flat_batch(
                                self.resident, [p.flat for p in taken],
                                k=max(p.k for p in taken),
                                mesh=mesh,
                                stages=batcher.stages)
                    finally:
                        if wd is not None:
                            wd.end(token)
                except Exception as exc:  # noqa: BLE001 — per query
                    for p in taken:
                        if not p.future.done():
                            p.future.set_exception(exc)
                else:
                    t_launched = time.perf_counter()
                    for p in taken:
                        p.t_launched = t_launched
                    with self.cv:
                        self.n_inflight += 1
                    # blocks when PIPELINE_DEPTH batches are in flight
                    self.inflight.put((st, taken))
                finally:
                    profiler.tag_stage(None)
        finally:
            self.inflight.put(None)  # stop the completer

    def _complete(self) -> None:
        batcher = self.batcher
        while True:
            item = self.inflight.get()
            if item is None:
                return
            st, taken = item
            trace_parent = next(
                (p.trace_span for p in taken if p.trace_span), None)
            try:
                profiler.tag_stage("batch_finish")
                wd = batcher.watchdog
                token = (wd.begin("finish", taken,
                                  devices=_mesh_device_ids(
                                      self.launch_mesh()))
                         if wd is not None else None)
                try:
                    with tracing.span_under(trace_parent,
                                            "tpu.batch_finish",
                                            queries=len(taken)):
                        results = finish_flat_batch(st)
                finally:
                    if wd is not None:
                        wd.end(token)
            except Exception as exc:  # noqa: BLE001 — per query
                for p in taken:
                    if not p.future.done():
                        p.future.set_exception(exc)
                with self.cv:
                    self.n_inflight -= 1
                    self.cv.notify_all()
                profiler.tag_stage(None)
                continue
            with batcher._lock:
                batcher.batches_executed += 1
                batcher.queries_executed += len(taken)
            for p, res in zip(taken, results):
                # the watchdog may have failed this future already (an
                # overdue launch that eventually returned)
                if not p.future.done():
                    p.future.set_result(res)
            with self.cv:  # batch finished — the worker may launch now
                self.n_inflight -= 1
                self.cv.notify_all()
            profiler.tag_stage(None)


class MicroBatcher:
    """Coalesces concurrent queries per resident pack into single kernel
    launches (SURVEY.md §2.3 P4). Queries arriving within `window_s` (or
    until `max_batch`) share a launch; k pads to the max requested.
    Each pack has its own queue + worker, so launches for different
    packs overlap."""

    def __init__(self, window_s: float = 0.01, max_batch: int = 128):
        self.window_s = window_s
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._queues: Dict[int, _PackQueue] = {}
        self._closed = False
        self.batches_executed = 0
        self.queries_executed = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            queues = list(self._queues.values())
            self._queues.clear()
        for q in queues:
            q.close()

    def _retire(self, queue: _PackQueue) -> None:
        with self._lock:
            if self._queues.get(id(queue.resident)) is queue:
                del self._queues[id(queue.resident)]

    def fail_pending(self, exc: BaseException) -> int:
        """Fail every not-yet-launched query with `exc` (typed batcher
        death): the supervisor calls this before detaching a dead or
        wedged batcher so no request waits out the full batch timeout.
        Queries already taken into a launch are the watchdog's to fail."""
        with self._lock:
            queues = list(self._queues.values())
        failed = 0
        for q in queues:
            with q.cv:
                pendings, q.pendings = q.pendings, []
                q.cv.notify_all()
            for p in pendings:
                if not p.future.done():
                    p.future.set_exception(exc)
                    failed += 1
        return failed

    def fail_pack_pending(self, resident: ResidentPack,
                          exc: BaseException) -> int:
        """Fail ONE pack's not-yet-launched queries typed and retire
        its queue (group failover: the pack's home group lost a device
        — waiting queries must not launch onto, or wait out a deadline
        against, the dead chip; the caller re-routes retries to a
        surviving replica group)."""
        with self._lock:
            queue = self._queues.pop(id(resident), None)
        if queue is None:
            return 0
        with queue.cv:
            pendings, queue.pendings = queue.pendings, []
            queue.closed = True
            queue.cv.notify_all()
        failed = 0
        for p in pendings:
            if not p.future.done():
                p.future.set_exception(exc)
                failed += 1
        return failed

    def retire_pack(self, resident: ResidentPack) -> None:
        """Called when the pack cache evicts/replaces a pack: drop its
        queue NOW so the queue's strong reference can't keep the evicted
        device arrays alive past the breaker release (the worker drains
        any in-flight pendings, then exits)."""
        with self._lock:
            queue = self._queues.pop(id(resident), None)
        if queue is not None:
            queue.close()

    def submit(self, resident: ResidentPack, flat: FlatQuery,
               k: int) -> Future:
        """The entry point the serving path (and fault-injection tests)
        hook; the `_Pending` with its batch_wait decomposition marks
        rides on the returned future as `.pending`."""
        return self.submit_pending(resident, flat, k).future

    def submit_pending(self, resident: ResidentPack, flat: FlatQuery,
                       k: int) -> _Pending:
        fut: Future = Future()
        # capture on the REQUEST thread — the batch workers have no
        # request thread-local to read
        pending = _Pending(flat, k, fut, tracing.current_span(),
                           t_submit=time.perf_counter(),
                           tenant=tenancy.current_tenant())
        fut.pending = pending  # type: ignore[attr-defined]
        while True:
            with self._lock:
                if self._closed:
                    raise RuntimeError("micro-batcher is closed")
                queue = self._queues.get(id(resident))
                if queue is None:
                    queue = _PackQueue(self, resident)
                    self._queues[id(resident)] = queue
            if queue.submit(pending):
                return pending
            # raced the queue's idle retirement — loop and respawn

    def queue_depths(self) -> Dict[str, int]:
        """Instantaneous queue gauges for the profiler timeline and the
        metrics registry (lock-light: len/int reads are GIL-atomic)."""
        with self._lock:
            queues = list(self._queues.values())
        return {
            "queues": len(queues),
            "pending": sum(len(q.pendings) for q in queues),
            "inflight": sum(q.n_inflight for q in queues),
        }

    # set by the owning TpuSearchService so batches reuse the mesh the
    # pack arrays were placed with (no per-batch mesh construction)
    mesh = None
    stages: Optional[StageTimes] = None
    # launch watchdog (None = unmonitored): workers stamp a deadline on
    # every device dispatch through it
    watchdog: Optional["LaunchWatchdog"] = None
    # set by the node: TenantQuotaService supplying lane weights for
    # fair batch composition (None ⇒ equal weights)
    tenants = None

    def tenant_weight(self, tenant: str) -> float:
        quotas = self.tenants
        if quotas is None:
            return 1.0
        return quotas.weight(tenant)


@dataclasses.dataclass
class FlatQueryResult:
    """Per-query kernel result, COLUMNAR: parallel numpy arrays best-first
    (scores f32[n], pack rows int32[n], local ordinals int32[n]). The
    serving path consumes the columns directly — external ids resolve via
    one fancy-index (`resident.resolve_ids`), never per-hit Python
    (VERDICT r3 #1). `hits` is the legacy tuple view for cold paths."""

    scores: np.ndarray
    rows: np.ndarray
    ords: np.ndarray
    total_hits: int
    max_score: Optional[float]
    resident: Optional[ResidentPack] = None  # for the fetch phase
    total_relation: str = "eq"  # "gte" when block-max pruning stopped
                                # counting (the reference's WAND behavior)
    variant: Optional[str] = None  # kernel variant that produced this
    _hits: Optional[List[Tuple[float, int, str, int, str]]] = None

    @classmethod
    def empty(cls) -> "FlatQueryResult":
        z = np.empty(0, dtype=np.int32)
        return cls(np.empty(0, dtype=np.float32), z, z, 0, None)

    def __len__(self) -> int:
        return len(self.scores)

    @property
    def hits(self) -> List[Tuple[float, int, str, int, str]]:
        """[(score, shard_num, segment_name, local_ord, doc_id)]."""
        if self._hits is None:
            r = self.resident
            if r is None or len(self.rows) == 0:
                self._hits = []
            else:
                ids = r.resolve_ids(self.rows, self.ords)
                self._hits = [
                    (float(s), *r.row_origin[row], int(o), i)
                    for s, row, o, i in zip(
                        self.scores.tolist(), self.rows.tolist(),
                        self.ords.tolist(), ids.tolist())]
        return self._hits


# block-max serving knobs: per-term impact prefix taken on device, and
# the candidate slack that absorbs approximate-order error before the
# exact host re-score. The pruned path pins every jit-signature dimension
# (T slots, window, chunk len, batch bucket, candidate k) to a handful of
# values so steady-state serving NEVER re-compiles.
#
# r5 routing (replaces r4's try-then-retry tiering, whose ~1-per-train
# validity retries each cost a full ~100ms launch): the HOST knows every
# term's postings length at lowering time, so each query routes to the
# smallest FULL-POSTINGS sort width that holds ALL its terms' rows —
# phase-A run totals are then EXACT BM25 (no prefixes, no rescore, no
# validity bound, nothing to escalate). Only queries too hot for the
# widest bucket (Σ slots > max(FULL_SLOT_BUCKETS) on some shard row)
# take the prefix+rescore path at PREFIX_CAP2, escalating PREFIX_CAP3 →
# exact on validity failures. Measured at 2.6M docs: exact-at-width
# ≈ prefix-at-the-same-width minus the whole rescore phase, and the
# 23%-invalid escalation storm of prefix@16k disappears.
FULL_SLOT_BUCKETS = (32, 128)   # sort widths 131k / 524k (x CHUNK_CAP)
PREFIX_CAP = 4096               # base prefix for ad-hoc prefix runs
PREFIX_CAP2 = 16384             # hot-tier prefix (queries over-width)
PREFIX_CAP3 = 65536             # escalation prefix
PRUNE_MAX_K = 1000
PRUNE_MAX_TERMS = 8          # > 8 query terms → exact path
_PRUNE_WINDOW = 8

# device-kernel variant selection (PERF.md round 8). packed_sort=True
# routes launches through the single-packed-key sort + hierarchical
# top-k kernels; choose_kernel_variant still falls back to "ref"
# per-launch whenever the pack/batch overflows the 16-bit packed layout
# (the setting is the ceiling, packability is the floor). Process-wide
# because the jitted kernels and their prewarmed signatures are too
# (`search.tpu_serving.kernel.packed_sort`).
KERNEL_CONFIG = {"packed_sort": True,
                 # compressed_pack=True builds RESIDENT packs in the
                 # 16-bit stream format (PERF.md round 11): ~2.7× fewer
                 # HBM bytes/doc, exact scores via residual tables,
                 # device-side block-max pruning. Default ON since PR 15
                 # (two rounds of parity sweeps + the SLO harness behind
                 # it; real-chip soak tracked in README). Build-time:
                 # toggling only affects packs built afterwards (the
                 # bench invalidates between phases). Incompressible
                 # packs (d_pad ≥ 2^16, non-finite impacts, > 65535
                 # distinct impacts per term) silently stay in the raw
                 # format (`search.tpu_serving.kernel.compressed_pack`).
                 "compressed_pack": True,
                 # pallas=True serves compressed packs through the fused
                 # Pallas kernel (ops/pallas_merge) when available —
                 # bit-identical to "compressed", same typed fallbacks.
                 # Off by default until the real-chip Mosaic soak lands
                 # (`search.tpu_serving.kernel.pallas`).
                 "pallas": False}

#: per-(kernel, variant) launch counters → es_tpu_kernel_variant_total
KERNEL_VARIANT_COUNTS = LabeledCounters("kernel", "variant")


def _choose_exact_variant(resident: ResidentPack, batch) -> str:
    """Lowering-time variant pick for one exact-kernel launch (the
    planner owns the decision rule; this just feeds it the pack's doc
    axis and the prepared batch's slot weights)."""
    from elasticsearch_tpu.search.planner import choose_kernel_variant
    return choose_kernel_variant(resident.pack.d_pad, batch.weights,
                                 enabled=KERNEL_CONFIG["packed_sort"],
                                 compressed=resident.comp_streams
                                 is not None,
                                 pallas=KERNEL_CONFIG["pallas"])


def _pruned_variant() -> str:
    """Under variant="packed" the pruned kernel always takes the
    hierarchical top-k half (unconditionally safe); whether a launch
    ALSO packs (gid, impact code) into one sort key is a separate
    per-launch gate (pack_keys in _launch_pruned: the group's gid range
    must fit 16 bits and the batch weights must be packable).
    Setting-gated so the bench can A/B it."""
    return "packed" if KERNEL_CONFIG["packed_sort"] else "ref"


def _prune_t_slots(prefix_cap: int) -> int:
    from elasticsearch_tpu.parallel.distributed import CHUNK_CAP
    return PRUNE_MAX_TERMS * max(1, prefix_cap // CHUNK_CAP)


def _candidate_k(k: int) -> int:
    """Static candidate-count buckets (k + slack, few jit signatures)."""
    return 128 if k <= 64 else 2048


def _serving_bucket(n: int, cap: int = 128) -> int:
    """Three batch buckets (8 / 64 / 128) — trains launch at whatever
    fill the host managed, so the mid bucket avoids ~2x padding when
    GIL-bound clients can't refill to 128 in one device cycle; every
    bucket×width×k signature is prewarmed."""
    if n <= 8:
        return 8
    if n <= 64:
        return 64
    if n <= cap:
        return cap
    return _batch_bucket(n, 1024)


def _slots_needed(resident: ResidentPack, flat: FlatQuery) -> int:
    """Max over shard rows of Σ_terms ceil(row_len/CHUNK): the slot
    count a FULL-postings sorted-merge of this query needs. Terms
    MISSING from a row still cost one (zero-length) slot — plan_slots
    keeps them for msm semantics, so the routed width must count them
    or the prepared batch lands on an unprewarmed jit signature.

    Memoized per pack by terms tuple: the scan walks EVERY shard row's
    vocab, which at many segments is the costliest host step per query
    — and repeated query shapes hit the same terms constantly."""
    memo_key = tuple(flat.terms)
    cached = resident.slots_memo.get(memo_key)
    if cached is not None:
        return cached
    pack = resident.pack
    worst = 0
    for si in range(len(pack.vocabs)):
        vocab = pack.vocabs[si]
        rstart = pack.row_starts[si]
        n = 0
        for t in flat.terms:
            r = vocab.get(t)
            if r is None:
                n += 1  # zero-length slot
                continue
            ln = int(rstart[r + 1] - rstart[r])
            n += max(1, (ln + dist.CHUNK_CAP - 1) // dist.CHUNK_CAP)
        worst = max(worst, n)
    result = max(worst, 1)
    if len(resident.slots_memo) < 65536:  # bound pathological cardinality
        resident.slots_memo[memo_key] = result
    return result


def _full_bucket(slots: int) -> Optional[int]:
    for b in FULL_SLOT_BUCKETS:
        if slots <= b:
            return b
    return None


def launch_flat_batch(resident: ResidentPack, flats: Sequence[FlatQuery],
                      k: int, mesh=None,
                      stages: Optional[StageTimes] = None) -> Dict[str, Any]:
    """Phase 1 of a micro-batch: host prep + ASYNC kernel dispatch for
    the tier-E pruned subset (rescore-free), the tier-H pruned subset,
    and the exact subset (msm/AND, big k, many terms). Returns an
    opaque launch state for finish_flat_batch. JAX dispatch is
    asynchronous, so the caller can launch batch N+1 while batch N
    executes on device (double-buffered serving; VERDICT r3 #1d)."""
    if mesh is None:
        mesh = make_mesh(shape=(1, _n_local_devices()))
    # fault seam: DeviceWedge blocks here — BEFORE any lock or device
    # work — so a "wedged" launch holds nothing the watchdog needs
    _dispatch_fault_point(mesh)
    pruned_idx = [i for i, f in enumerate(flats)
                  if f.min_count == 1 and k <= PRUNE_MAX_K
                  and len(f.terms) <= PRUNE_MAX_TERMS
                  and resident.imp_device_arrays is not None]
    exact_idx = [i for i in range(len(flats)) if i not in set(pruned_idx)]
    # route each query to the smallest exact-sort width that holds its
    # FULL postings; overflow goes to the prefix+rescore path
    full_groups: Dict[int, List[int]] = {b: [] for b in FULL_SLOT_BUCKETS}
    hot_idx: List[int] = []
    for i in pruned_idx:
        b = _full_bucket(_slots_needed(resident, flats[i]))
        if b is None:
            hot_idx.append(i)
        else:
            full_groups[b].append(i)
    # a tiny group isn't worth its own ~100ms launch floor: fold it into
    # the next WIDER bucket when that bucket launches anyway (always
    # correct — wider holds everything; folding into an EMPTY wider
    # bucket would save nothing and widen the sort for nothing)
    buckets = list(FULL_SLOT_BUCKETS)
    for bi, b in enumerate(buckets[:-1]):
        if 0 < len(full_groups[b]) < 16 and full_groups[buckets[bi + 1]]:
            full_groups[buckets[bi + 1]].extend(full_groups[b])
            full_groups[b] = []
    st: Dict[str, Any] = {"resident": resident, "flats": flats, "k": k,
                          "mesh": mesh, "stages": stages,
                          "full_groups": full_groups, "hot_idx": hot_idx,
                          "exact_idx": exact_idx}
    for b, idxs in full_groups.items():
        if idxs:
            st[f"full_launch_{b}"] = _launch_pruned(
                resident, [flats[i] for i in idxs], k, mesh,
                stages=stages, full_slots=b)
    if hot_idx:
        st["hot_launch"] = _launch_pruned(
            resident, [flats[i] for i in hot_idx], k, mesh,
            prefix_cap=PREFIX_CAP2, stages=stages)
    if exact_idx:
        st["exact_launch"] = _launch_exact(
            resident, [flats[i] for i in exact_idx], k, mesh,
            stages=stages)
    return st


def finish_flat_batch(st: Dict[str, Any]) -> List[FlatQueryResult]:
    """Phase 2: materialize device results; residual tier-H validity
    failures escalate to the deeper PREFIX_CAP3 prefix, then exact."""
    resident, flats, k, mesh, stages = (st["resident"], st["flats"],
                                        st["k"], st["mesh"], st["stages"])
    out: List[Optional[FlatQueryResult]] = [None] * len(flats)
    tier3_idx: List[int] = []
    escalate: List[int] = []
    for b, idxs in st["full_groups"].items():
        if not idxs:
            continue
        results, invalid = _finish_pruned(st[f"full_launch_{b}"],
                                          stages=stages)
        for j, i in enumerate(idxs):
            out[i] = results[j]
        # full-postings runs are exact ⇒ beta 0 ⇒ no invalids; if the
        # invariant ever breaks, escalate rather than crash serving
        escalate.extend(idxs[j] for j in invalid)
    if st["hot_idx"]:
        hot_idx = st["hot_idx"]
        results, invalid = _finish_pruned(st["hot_launch"],
                                          stages=stages)
        for j, i in enumerate(hot_idx):
            out[i] = results[j]
        escalate.extend(hot_idx[j] for j in invalid)
    if escalate:
        retry_idx = escalate
        if stages is not None:
            stages.add("pruned_invalid_t2", 0.0, n=len(retry_idx))
        results2, invalid2 = _execute_pruned(
            resident, [flats[i] for i in retry_idx], k, mesh,
            stages=stages, prefix_cap=PREFIX_CAP3)
        for j, i in enumerate(retry_idx):
            out[i] = results2[j]
        if invalid2 and stages is not None:
            stages.add("pruned_invalid_t3", 0.0, n=len(invalid2))
        tier3_idx = [retry_idx[j] for j in invalid2]
    if "exact_launch" in st:
        results = _finish_exact(st["exact_launch"], stages=stages)
        for j, i in enumerate(st["exact_idx"]):
            out[i] = results[j]
    if tier3_idx:
        t0 = time.perf_counter()
        results = _execute_exact(resident,
                                 [flats[i] for i in tier3_idx], k, mesh,
                                 stages=stages)
        if stages is not None:
            stages.add("exact_batch", time.perf_counter() - t0,
                       n=len(tier3_idx))
        for j, i in enumerate(tier3_idx):
            out[i] = results[j]
    return out  # type: ignore[return-value]


def execute_flat_batch(resident: ResidentPack, flats: Sequence[FlatQuery],
                       k: int, mesh=None,
                       stages: Optional[StageTimes] = None
                       ) -> List[FlatQueryResult]:
    """Run one micro-batch synchronously. OR-queries (min_count == 1,
    k ≤ 1000) go through the block-max pruned pipeline (tier E or H by
    per-term df); msm/AND queries and pruned queries whose validity
    bound fails escalate (64k prefix, then exact kernel)."""
    return finish_flat_batch(launch_flat_batch(resident, flats, k, mesh,
                                               stages=stages))


def _columnar_results(resident: ResidentPack, vals: np.ndarray,
                      gids: np.ndarray, totals: np.ndarray,
                      n_queries: int, relation_fn,
                      k_cap: Optional[int] = None,
                      variant: Optional[str] = None
                      ) -> List[FlatQueryResult]:
    """Decode a whole batch's [B, k'] kernel output into columnar results
    with vectorized numpy — the only per-query work is slicing views.
    Sentinel lanes (score -inf / ordinal == d_pad / padding rows) are
    dropped; they always sort to the tail, so each query's valid hits are
    a prefix."""
    pack = resident.pack
    d1 = pack.d_pad + 1
    rows = (gids // d1).astype(np.int32)
    ords = (gids - rows.astype(np.int64) * d1).astype(np.int32)
    valid = ((vals > dist.NEG_INF) & (ords < pack.d_pad)
             & (rows < len(resident.row_origin)))
    # prefix lengths (guard against non-prefix validity: stop at first 0)
    n_valid = np.where(valid.all(axis=1), valid.shape[1],
                       valid.argmin(axis=1))
    out = []
    for qi in range(n_queries):
        m = int(n_valid[qi])
        if k_cap is not None and m > k_cap:
            m = k_cap
        sc = vals[qi, :m]
        out.append(FlatQueryResult(
            sc, rows[qi, :m], ords[qi, :m], int(totals[qi]),
            float(sc[0]) if m else None, resident=resident,
            total_relation=relation_fn(qi), variant=variant))
    return out


def _launch_exact(resident: ResidentPack, flats: Sequence[FlatQuery],
                  k: int, mesh,
                  stages: Optional[StageTimes] = None,
                  variant: Optional[str] = None) -> Dict[str, Any]:
    """Full-postings kernel, async dispatch: exact scores, exact totals
    (tier 3 for OR queries whose validity bounds failed twice; tier 1
    for msm/AND). Every jit dimension is BUCKETED — batch (8/64/pow2),
    kernel k (128/1024/pow2), slot count (pow2 ≥ 8), window (≥ 8), chunk
    length (pinned CHUNK_CAP) — so steady-state serving re-uses a
    handful of compiled signatures (cold ones compile once ever,
    persisted by the compilation cache)."""
    import dataclasses as _dc

    t_prep = time.perf_counter()
    pack = resident.pack
    batch = dist.prepare_query_batch(
        pack, [f.terms for f in flats],
        boosts=[f.boost for f in flats],
        min_counts=[f.min_count for f in flats],
        pad_batch_to=_serving_bucket(len(flats)),
        pad_max_len=dist.CHUNK_CAP,
        compressed=resident.comp_streams)
    t_pin = 8
    while t_pin < batch.t_slots:
        t_pin *= 2
    if t_pin > batch.t_slots:
        s, b, t = batch.starts.shape
        pad = ((0, 0), (0, 0), (0, t_pin - t))
        extra = {}
        if batch.res_starts is not None:
            # zero-padded slots: length 0 ⇒ inert in grouping/rescore
            extra = dict(res_starts=np.pad(batch.res_starts, pad),
                         res_lens=np.pad(batch.res_lens, pad),
                         slot_terms=np.pad(batch.slot_terms, pad))
        batch = _dc.replace(
            batch, starts=np.pad(batch.starts, pad),
            lengths=np.pad(batch.lengths, pad),
            weights=np.pad(batch.weights, pad), t_slots=t_pin, **extra)
    k_kernel = 128 if k <= 128 else (1024 if k <= 1024
                                     else _batch_bucket(k, 16384))
    if variant is None:
        variant = _choose_exact_variant(resident, batch)
    KERNEL_VARIANT_COUNTS.inc("exact", variant)
    t_disp = time.perf_counter()
    vals, gids, totals = dist.distributed_search_raw(
        pack, batch, k_kernel, mesh, device_arrays=resident.device_arrays,
        t_window=max(_PRUNE_WINDOW, batch.window), materialize=False,
        variant=variant)
    if stages is not None:
        stages.add("exact_prep", t_disp - t_prep)
        stages.add(f"exact_dispatch.{variant}",
                   time.perf_counter() - t_disp)
    return {"resident": resident, "n": len(flats), "k": k,
            "vals": vals, "gids": gids, "totals": totals,
            "variant": variant}


def _finish_exact(launch: Dict[str, Any],
                  stages: Optional[StageTimes] = None
                  ) -> List[FlatQueryResult]:
    t_dev = time.perf_counter()
    vals = np.asarray(launch["vals"])
    gids = np.asarray(launch["gids"])
    totals = np.asarray(launch["totals"])
    if stages is not None:
        # variant-tagged: the bench's kernel_compare diffs these rings
        # per variant for device_ms_per_query
        stages.add(f"exact_device_wait.{launch['variant']}",
                   time.perf_counter() - t_dev)
    return _columnar_results(launch["resident"], vals, gids, totals,
                             launch["n"], lambda qi: "eq",
                             k_cap=launch["k"],
                             variant=launch.get("variant"))


def _execute_exact(resident: ResidentPack, flats: Sequence[FlatQuery],
                   k: int, mesh, stages: Optional[StageTimes] = None,
                   variant: Optional[str] = None) -> List[FlatQueryResult]:
    return _finish_exact(_launch_exact(resident, flats, k, mesh,
                                       stages=stages, variant=variant),
                         stages=stages)


def _launch_pruned(resident: ResidentPack, flats: Sequence[FlatQuery],
                   k: int, mesh, prefix_cap: int = PREFIX_CAP,
                   stages: Optional[StageTimes] = None,
                   with_rescore: bool = True,
                   full_slots: Optional[int] = None,
                   variant: Optional[str] = None) -> Dict[str, Any]:
    """One fused ASYNC launch. Two modes:
    - full_slots=N: FULL-postings sorted-merge at the N-slot width —
      run totals are exact BM25, no rescore (SURVEY.md §5.7 applied as
      width buckets instead of prefixes);
    - prefix mode (block-max, §7.3#3): candidate generation over
      impact-sorted prefixes + EXACT on-device re-score (binary search
      in the doc-sorted postings). Only [B, k] crosses device→host."""
    import jax

    t_prep = time.perf_counter()
    pack = resident.pack
    imp_docs, imp_impacts = resident.imp_host
    k_cand = _candidate_k(k)
    k_out = 128 if k_cand == 128 else 1024
    b_bucket = _serving_bucket(len(flats))
    if full_slots is not None:
        with_rescore = False
        k_cand = k_out  # exact totals: the candidate pool IS the result
        batch = dist.prepare_query_batch(
            pack, [f.terms for f in flats],
            boosts=[f.boost for f in flats],
            min_counts=[1] * len(flats),
            pad_batch_to=b_bucket,
            pad_t_slots=full_slots, pad_max_len=dist.CHUNK_CAP)
    else:
        batch = dist.prepare_query_batch(
            pack, [f.terms for f in flats],
            boosts=[f.boost for f in flats],
            min_counts=[1] * len(flats),
            pad_batch_to=b_bucket,
            prefix_cap=prefix_cap, imp_impacts=imp_impacts,
            pad_t_slots=_prune_t_slots(prefix_cap),
            pad_max_len=dist.CHUNK_CAP)
    t_starts, t_lengths, t_weights = dist.prepare_term_ranges(
        pack, [f.terms for f in flats],
        boosts=[f.boost for f in flats],
        pad_batch_to=b_bucket, pad_terms=PRUNE_MAX_TERMS)
    if variant is None:
        variant = _pruned_variant()
    KERNEL_VARIANT_COUNTS.inc("full" if full_slots is not None
                              else "pruned", variant)
    # single-key phase-A sort (PR 15): only when the batch's slot AND
    # rescore-term weights keep the 16-bit impact code monotone — the
    # group-size fit check is static inside make_pruned_search
    pack_keys = (variant == "packed" and with_rescore
                 and sparse.packable(pack.d_pad, batch.weights)
                 and sparse.packable(pack.d_pad, t_weights))
    fn = dist.make_pruned_search(
        mesh, max_len=batch.max_len, d_pad=pack.d_pad, p_pad=pack.p_pad,
        c_cand=k_cand, k_out=k_out,
        t_window=max(_PRUNE_WINDOW, batch.window),
        t_terms=PRUNE_MAX_TERMS, with_rescore=with_rescore,
        variant=variant, pack_keys=pack_keys)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from elasticsearch_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS
    sbt = NamedSharding(mesh, P(SHARD_AXIS, DATA_AXIS, None))
    ops = dist.pack_pruned_operands(batch, t_starts, t_lengths, t_weights)
    t_disp = time.perf_counter()
    with dist.DEVICE_DISPATCH_LOCK:
        packed = fn(
            resident.imp_device_arrays[0], resident.imp_device_arrays[1],
            resident.device_arrays[0], resident.device_arrays[1],
            jax.device_put(ops, sbt))
    t_dev = time.perf_counter()
    if stages is not None:
        stages.add("batch_prep", t_disp - t_prep)
        stages.add("batch_dispatch", t_dev - t_disp)
        stages.add(f"batch_dispatch.{variant}", t_dev - t_disp)
    return {"resident": resident, "flats": flats, "k": k,
            "packed": packed, "variant": variant}


def _finish_pruned(launch: Dict[str, Any],
                   stages: Optional[StageTimes] = None
                   ) -> Tuple[List[FlatQueryResult], List[int]]:
    """Materialize a pruned launch and check the WAND validity bound —
    any doc outside the candidates scores below (approx cutoff + Σ
    skipped-tail maxima); failures escalate. Returns (results, invalid
    indices)."""
    resident, flats, k = (launch["resident"], launch["flats"],
                          launch["k"])
    # one device→host transfer; split host-side (k derived from the
    # packed width — the kernel clamps k_out to its candidate pool)
    t_dev = time.perf_counter()
    vals, gids, totals, cutoff, beta = dist.unpack_pruned(
        np.asarray(launch["packed"]))
    t_decode = time.perf_counter()
    if stages is not None:
        stages.add("batch_device_wait", t_decode - t_dev)
        # variant-tagged sibling ring: kernel_compare reads per-variant
        # device time from here without disturbing the canonical stage
        stages.add(f"batch_device_wait.{launch['variant']}",
                   t_decode - t_dev)

    # vectorized batch decode (VERDICT r3 #1): clamp each query to its
    # first min(n_valid, k) entries, then check the WAND validity bound
    # with scalar numpy reads — no per-hit Python
    decoded = _columnar_results(
        resident, vals, gids.astype(np.int64), totals, len(flats),
        lambda qi: "gte" if beta[qi] > 0.0 else "eq",
        variant=launch.get("variant"))
    results: List[FlatQueryResult] = []
    invalid: List[int] = []
    for qi, res in enumerate(decoded):
        b_q = float(beta[qi])
        n = len(res.scores)
        if n > k:
            res = dataclasses.replace(res, scores=res.scores[:k],
                                      rows=res.rows[:k], ords=res.ords[:k])
            n = k
        if b_q > 0.0:
            # validity at the caller's k: docs outside the candidate set
            # score below cutoff+β (cut candidates) or β (tail-only)
            kth = float(res.scores[k - 1]) if n >= k else float("-inf")
            c_q = float(cutoff[qi])
            threshold = (c_q + b_q) if c_q > dist.NEG_INF else b_q
            if kth < threshold or n < k:
                results.append(None)  # type: ignore[arg-type]
                invalid.append(qi)
                continue
        results.append(res)
    if stages is not None:
        stages.add("batch_decode", time.perf_counter() - t_decode)
    return results, invalid


def _execute_pruned(resident: ResidentPack, flats: Sequence[FlatQuery],
                    k: int, mesh, stages: Optional[StageTimes] = None,
                    prefix_cap: int = PREFIX_CAP,
                    with_rescore: bool = True,
                    full_slots: Optional[int] = None,
                    variant: Optional[str] = None
                    ) -> Tuple[List[FlatQueryResult], List[int]]:
    """Synchronous pruned execution (escalations, prewarm, dryrun)."""
    return _finish_pruned(
        _launch_pruned(resident, flats, k, mesh, prefix_cap=prefix_cap,
                       stages=stages, with_rescore=with_rescore,
                       full_slots=full_slots, variant=variant),
        stages=stages)


def _n_local_devices() -> int:
    import jax
    return len(jax.devices())


# ---------------------------------------------------------------------------
# batcher supervision: launch watchdog + wedge/crash recovery
# ---------------------------------------------------------------------------

class DeviceWedgedError(RuntimeError):
    """A device dispatch exceeded its launch deadline (or the batcher
    was torn down underneath a queued query). Typed so try_search can
    decline to the planner without tripping the generic error path."""


# fault-injection seam: DeviceWedge/DeviceLoss append a blocking
# callable here; launch_flat_batch calls through before doing ANY
# device work, so a "wedged" launch holds no locks the watchdog or
# supervisor need. Hooks receive the launch mesh so device-scoped
# faults (DeviceLoss) only fire for launches touching the lost chip.
DISPATCH_FAULT_HOOKS: List[Any] = []


def _dispatch_fault_point(mesh=None) -> None:
    for hook in list(DISPATCH_FAULT_HOOKS):
        hook(mesh)


def _mesh_device_ids(mesh) -> Tuple[int, ...]:
    """Device ids a launch on `mesh` implicates — watchdog attribution."""
    if mesh is None:
        return ()
    try:
        return tuple(int(d.id) for d in mesh.devices.flat)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return ()


class LaunchWatchdog:
    """Deadline-stamps every device dispatch. Workers bracket each
    launch/finish with begin()/end(); a scan thread fails any dispatch
    still open past `deadline_ms` with a typed DeviceWedgedError and
    fires `on_wedge` — a wedged SPMD launch trips supervision within
    the deadline instead of hanging the micro-batcher until the batch
    timeout. deadline_ms <= 0 disables monitoring (no scan thread)."""

    def __init__(self, deadline_ms: float = 120_000.0, on_wedge=None):
        self.deadline_s = max(0.0, float(deadline_ms)) / 1e3
        self.on_wedge = on_wedge
        self.c_launches = CounterMetric()
        self.c_wedges = CounterMetric()
        self.last_wedge: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._entries: Dict[int, Dict[str, Any]] = {}
        self._next_token = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.deadline_s > 0:
            self._thread = threading.Thread(target=self._scan_loop,
                                            daemon=True,
                                            name="tpu-launch-watchdog")
            self._thread.start()

    def begin(self, label: str, pendings,
              devices: Tuple[int, ...] = ()) -> Optional[int]:
        """Open a monitored dispatch; returns the token end() takes
        (None when monitoring is off). The pendings list is what the
        scan thread fails if the dispatch goes overdue. `devices` is
        the launch's mesh device-id set — a wedge carries it so health
        scoring can attribute the fault per chip."""
        if self.deadline_s <= 0:
            return None
        self.c_launches.inc()
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._entries[token] = {"label": label, "t0": time.monotonic(),
                                    "pendings": list(pendings),
                                    "devices": tuple(devices)}
        return token

    def end(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._lock:
            self._entries.pop(token, None)

    def inflight(self) -> int:
        with self._lock:
            return len(self._entries)

    def _scan_loop(self) -> None:
        # scan often enough that detection lands within the deadline
        # even for sub-second deadlines (the chaos tests run ~300ms)
        interval = max(0.01, min(0.25, self.deadline_s / 4))
        while not self._stop.wait(interval):
            now = time.monotonic()
            overdue = []
            with self._lock:
                for token in [t for t, e in self._entries.items()
                              if now - e["t0"] > self.deadline_s]:
                    overdue.append(self._entries.pop(token))
            for e in overdue:
                age_ms = (now - e["t0"]) * 1e3
                self.c_wedges.inc()
                wedge = {"label": e["label"],
                         "age_ms": round(age_ms, 1),
                         "devices": list(e.get("devices", ())),
                         "queries": len(e["pendings"]),
                         # launch attribution: trace ids of the traced
                         # requests riding the wedged dispatch
                         "trace_ids": [p.trace_span.trace_id
                                       for p in e["pendings"]
                                       if getattr(p, "trace_span", None)
                                       is not None]}
                self.last_wedge = wedge
                exc = DeviceWedgedError(
                    f"device dispatch ({e['label']}) exceeded its "
                    f"{self.deadline_s * 1e3:.0f}ms launch deadline "
                    f"after {age_ms:.0f}ms")
                for p in e["pendings"]:
                    if not p.future.done():
                        p.future.set_exception(exc)
                if self.on_wedge is not None:
                    try:
                        # full attribution dict: label, age_ms, the
                        # launch's device-id set, query count
                        self.on_wedge(wedge)
                    except Exception:  # noqa: BLE001 — scan must survive
                        logger.exception("watchdog on_wedge failed")

    def stats(self) -> Dict[str, Any]:
        return {"deadline_ms": round(self.deadline_s * 1e3, 1),
                "launches": self.c_launches.count,
                "wedges": self.c_wedges.count,
                "inflight": self.inflight(),
                "last_wedge": self.last_wedge}

    def close(self) -> None:
        self._stop.set()


# recovery.state gauge encoding (Prometheus can't carry strings)
_SUPERVISION_STATES = {"serving": 0, "down": 1, "recovering": 2}


class BatcherSupervisor:
    """Crash/wedge recovery for the device-owning batcher. trigger()
    tears the current batcher down — queued queries fail typed, every
    resident pack drops so the HBM breaker drains to EXACTLY zero (the
    lifecycle invariant) — and flips the service to degraded planner
    serving. maybe_recover() respawns a fresh MicroBatcher
    single-flight and eagerly re-attains residency for every dropped
    pack through IndexPackCache (re-charging the breaker), after which
    the kernel path resumes."""

    def __init__(self, svc: "TpuSearchService"):
        self.svc = svc
        self.state = "serving"
        self.c_recoveries = CounterMetric()
        self.c_degraded_served = CounterMetric()
        self.c_remeshes = CounterMetric()
        self.last_reason: Optional[str] = None
        self.last_duration_s = 0.0
        self.last_remesh_duration_s = 0.0
        # device topology of the batcher currently serving: recovery
        # rebuilds the mesh over the health registry's survivors, so
        # these shrink to N-1 on quarantine and restore on readmission
        self._mesh_ids: Tuple[int, ...] = _mesh_device_ids(svc.batcher.mesh)
        self.full_device_count = len(self._mesh_ids)
        self.mesh_device_count = len(self._mesh_ids)
        # breaker bytes observed after EVERY teardown drain — the chaos
        # suite asserts each entry is exactly zero (the invalidate_all
        # exact-zero invariant extended across remeshes)
        self.teardown_breaker_bytes: List[int] = []
        # disruption schemes hold recovery open so tests can observe
        # the degraded window; heal() lifts the hold and recovers
        self.hold_recovery = False
        self._lock = threading.Lock()
        self._dropped_keys: List[Tuple[str, str]] = []
        self._recover_thread: Optional[threading.Thread] = None

    @property
    def degraded_active(self) -> bool:
        return self.state != "serving"

    def trigger(self, reason: str) -> None:
        """Batcher is dead or wedged: tear it down and go degraded.
        Idempotent while already down/recovering."""
        with self._lock:
            self.last_reason = reason
            if self.state != "serving":
                return
            self.state = "down"
        logger.error("batcher supervision tripped (%s): serving degraded "
                     "planner results while recovering", reason)
        events.emit("supervisor.state", severity="error",
                    from_state="serving", to_state="down", reason=reason)
        events.incident("batcher_death", reason=reason)
        self._tear_down(reason)
        self.maybe_recover()

    def _tear_down(self, reason: str) -> None:
        svc = self.svc
        old = svc.batcher
        exc = DeviceWedgedError(f"batcher down: {reason}")
        try:
            old.fail_pending(exc)
        except Exception:  # noqa: BLE001 — teardown must complete
            logger.exception("failing pending queries during teardown")
        try:
            old.close()
        except Exception:  # noqa: BLE001
            logger.exception("closing dead batcher")
        dropped = svc.packs.invalidate_all()
        breaker = svc.packs._breaker
        if breaker is not None:
            # drain audit: invalidate_all released every pack's charge,
            # so this MUST read zero — recorded so the chaos suite can
            # assert the invariant held across every remesh
            self.teardown_breaker_bytes.append(
                int(getattr(breaker, "used", 0)))
            events.emit("hbm.drain",
                        severity=("info" if self.teardown_breaker_bytes[-1]
                                  == 0 else "error"),
                        bytes=self.teardown_breaker_bytes[-1],
                        packs_dropped=len(dropped), reason=reason)
        if svc.placement is not None:
            # full teardown under placement drains every group cache
            # too, with the SAME exact-zero audit per group
            for gid, cache in sorted(svc.group_caches.items()):
                cache.invalidate_all()
                gb = svc.placement.group(gid).breaker
                if gb is not None:
                    svc.placement.record_drain(gid, int(gb.used))
        with self._lock:
            self._dropped_keys = dropped

    def maybe_recover(self) -> None:
        with self._lock:
            # single-flight: only the caller that flips down→recovering
            # spawns the thread (a live-thread check would race the
            # window between releasing this lock and t.start())
            if self.state != "down" or self.hold_recovery:
                return
            self.state = "recovering"
            t = threading.Thread(target=self._recover, daemon=True,
                                 name="batcher-recovery")
            self._recover_thread = t
        events.emit("supervisor.state", severity="warning",
                    from_state="down", to_state="recovering")
        t.start()

    def _recover(self) -> None:
        svc = self.svc
        t0 = time.monotonic()
        try:
            if svc.placement is not None:
                self._recover_placement(t0)
                return
            old = svc.batcher
            # partial-mesh topology: rebuild over the health registry's
            # surviving devices. With every device healthy this is the
            # original full mesh (same jax.Mesh — jit caches keyed on
            # it stay hot); with quarantines it's a fresh N-k grid
            # (factorize_2d handles odd counts: 7 → 1×7).
            health = svc.health
            full_ids = _mesh_device_ids(svc.full_mesh)
            active = health.active_devices() if health is not None else None
            if active is not None and not active:
                with self._lock:
                    self.state = "down"
                events.emit("supervisor.state", severity="error",
                            from_state="recovering", to_state="down",
                            reason="every device quarantined")
                logger.error("every device is quarantined; staying on "
                             "degraded planner serving")
                return
            if active is None or len(active) == len(full_ids):
                mesh = svc.full_mesh
                mesh_ids = full_ids
            else:
                mesh = make_mesh(devices=active)
                mesh_ids = tuple(int(d.id) for d in active)
            remeshed = tuple(sorted(mesh_ids)) != tuple(
                sorted(self._mesh_ids))
            if remeshed:
                events.emit("remesh.begin", severity="warning",
                            from_devices=sorted(self._mesh_ids),
                            to_devices=sorted(mesh_ids))
            # anything rebuilt since teardown (a racing prewarm) was
            # placed on the OLD mesh — drop it and fold its keys in so
            # set_mesh sees an empty cache and re-residency covers it
            stragglers = svc.packs.invalidate_all()
            with self._lock:
                for key in stragglers:
                    if key not in self._dropped_keys:
                        self._dropped_keys.append(key)
                keys = list(self._dropped_keys)
            svc.packs.set_mesh(mesh)
            fresh = MicroBatcher(window_s=old.window_s,
                                 max_batch=old.max_batch)
            # counters carry over so scrape monotonicity survives respawn
            fresh.batches_executed = old.batches_executed
            fresh.queries_executed = old.queries_executed
            fresh.mesh = mesh
            fresh.stages = svc.stages
            fresh.watchdog = svc.watchdog
            # quota enforcement and fair lanes stay active through the
            # degraded → recovering → serving transitions
            fresh.tenants = old.tenants
            svc.batcher = fresh
            svc.packs.on_evict = fresh.retire_pack
            # HBM headroom: a partial mesh has proportionally less HBM
            # than the breaker limit was sized for — admit re-residency
            # warmest-first against the shrunken budget and SHED the
            # coldest packs (typed 503 + Retry-After) instead of
            # overcommitting the survivors
            keys.sort(key=svc.packs.heat_of, reverse=True)
            breaker = svc.packs._breaker
            budget = None
            if (breaker is not None and full_ids
                    and len(mesh_ids) < len(full_ids)):
                budget = int(getattr(breaker, "limit", 0)
                             * len(mesh_ids) / len(full_ids))
            rebuild: List[Tuple[str, str]] = []
            shed: List[Tuple[str, str]] = []
            projected = 0
            for key in keys:
                est = svc.packs.bytes_of(key)
                if budget is not None and rebuild \
                        and projected + est > budget:
                    shed.append(key)
                    continue
                projected += est
                rebuild.append(key)
            svc.set_shed(shed)
            # eager re-residency: rebuild every admitted pack through
            # the cache (re-charging the breaker) before traffic
            # returns — jit caches live on module functions, so a
            # full-mesh respawn pays no recompile
            resolver = svc.index_resolver
            rebuilt = 0
            replayed_indices: set = set()
            if resolver is not None:
                for index_name, field in rebuild:
                    try:
                        index_service = resolver(index_name)
                    except Exception:  # noqa: BLE001 — index may be gone
                        index_service = None
                    if index_service is None:
                        continue
                    # translog-gated visibility: before re-attaining the
                    # device image, replay each index's translog tail
                    # above its last refresh checkpoint so every acked
                    # write is in the reader the rebuild snapshots —
                    # the kill→recover→replay→checkpoint chain the
                    # chaos drill asserts (zero lost acked writes)
                    if index_name not in replayed_indices:
                        replayed_indices.add(index_name)
                        try:
                            r = index_service.replay_visibility(
                                reason="supervisor recovery")
                            if svc.delta_stats is not None:
                                svc.delta_stats.replayed_ops += \
                                    r.get("scanned", 0)
                        except Exception:  # noqa: BLE001 — best effort
                            logger.exception("visibility replay for %s",
                                             index_name)
                    try:
                        if svc.packs.get(index_service, field) is not None:
                            rebuilt += 1
                    except Exception:  # noqa: BLE001 — best effort
                        logger.exception("re-attaining residency for %s/%s",
                                         index_name, field)
            with self._lock:
                self.state = "serving"
                self.last_duration_s = time.monotonic() - t0
                self._mesh_ids = mesh_ids
                self.mesh_device_count = len(mesh_ids)
                if remeshed:
                    self.last_remesh_duration_s = self.last_duration_s
            if remeshed:
                self.c_remeshes.inc()
                events.emit("remesh.end", severity="warning",
                            devices=sorted(mesh_ids),
                            devices_total=len(full_ids) or len(mesh_ids),
                            duration_s=round(self.last_duration_s, 4))
            self.c_recoveries.inc()
            events.emit("supervisor.state", from_state="recovering",
                        to_state="serving",
                        duration_s=round(self.last_duration_s, 4),
                        devices=len(mesh_ids), rebuilt=rebuilt,
                        shed=len(shed))
            svc._tripped = False
            logger.warning("batcher recovered in %.2fs on %d/%d device(s) "
                           "(%d/%d packs re-resident, %d shed)",
                           self.last_duration_s, len(mesh_ids),
                           len(full_ids) or len(mesh_ids), rebuilt,
                           len(rebuild), len(shed))
            # a device readmitted (or lost) while this recovery ran:
            # converge onto the now-current active set
            if health is not None:
                want = tuple(sorted(health.active_ids()))
                if want != tuple(sorted(mesh_ids)):
                    self.trigger("device set changed during recovery")
        except Exception:  # noqa: BLE001 — stay degraded, stay alive
            with self._lock:
                self.state = "down"
            events.emit("supervisor.state", severity="error",
                        from_state="recovering", to_state="down",
                        reason="recovery failed")
            logger.exception("batcher recovery failed; staying degraded")

    def _recover_placement(self, t0: float) -> None:
        """Full-teardown recovery under fault-domain placement: respawn
        the batcher and remesh EACH group over its own survivors (a
        group's mesh never spans another group's devices), then
        eagerly re-attain residency for every placed replica. Group-
        scoped failover (one quarantined chip) never comes through
        here — it runs without a teardown at all."""
        svc = self.svc
        pl = svc.placement
        old = svc.batcher
        health = svc.health
        active = (set(health.active_ids()) if health is not None
                  else None)
        for gid, cache in sorted(svc.group_caches.items()):
            # stragglers built since teardown were placed on the old
            # group mesh — drop them before remeshing
            cache.invalidate_all()
            if active is not None:
                g = pl.group(gid)
                for i in g.active_ids:
                    if i not in active:
                        pl.on_device_lost(i)
                for i in g.device_ids:
                    if i in active and i not in pl.group(gid).active_ids:
                        pl.on_device_restored(i)
            g = pl.group(gid)
            if g.alive:
                cache.set_mesh(g.mesh)
        fresh = MicroBatcher(window_s=old.window_s,
                             max_batch=old.max_batch)
        fresh.batches_executed = old.batches_executed
        fresh.queries_executed = old.queries_executed
        fresh.mesh = svc.full_mesh
        fresh.stages = svc.stages
        fresh.watchdog = svc.watchdog
        fresh.tenants = old.tenants
        svc.batcher = fresh
        svc.packs.on_evict = fresh.retire_pack
        # eager re-residency of every placed replica (lazy rebuild on
        # first traffic when no resolver is wired)
        for key in pl.keys():
            for gid in pl.groups_of(key):
                if (pl.group(gid).alive
                        and svc.group_caches[gid].peek(key) is None):
                    svc._eager_rebuild(key, gid)
        mesh_ids = tuple(sorted(i for g in pl.groups()
                                for i in g.active_ids))
        with self._lock:
            self.state = "serving"
            self.last_duration_s = time.monotonic() - t0
            remeshed = mesh_ids != tuple(sorted(self._mesh_ids))
            self._mesh_ids = mesh_ids
            self.mesh_device_count = len(mesh_ids)
            if remeshed:
                self.last_remesh_duration_s = self.last_duration_s
        if remeshed:
            self.c_remeshes.inc()
            events.emit("remesh.end", severity="warning",
                        devices=sorted(mesh_ids),
                        devices_total=self.full_device_count,
                        duration_s=round(self.last_duration_s, 4),
                        placement_groups=pl.num_groups)
        self.c_recoveries.inc()
        events.emit("supervisor.state", from_state="recovering",
                    to_state="serving",
                    duration_s=round(self.last_duration_s, 4),
                    devices=len(mesh_ids))
        svc._tripped = False
        logger.warning("batcher recovered in %.2fs over %d placement "
                       "group(s), %d/%d device(s)", self.last_duration_s,
                       pl.num_groups, len(mesh_ids),
                       self.full_device_count)

    def schedule_full_remesh(self, reason: str) -> None:
        """A quarantined device proved healthy again: recover onto the
        restored device set inside a DRAIN WINDOW — wait (bounded by
        `svc.drain_window_s`) for pending/in-flight work to drain so
        the remesh interrupts as little traffic as possible, then
        trigger a respawn that maps onto the registry's active set."""
        def run() -> None:
            svc = self.svc
            deadline = time.monotonic() + max(0.0, svc.drain_window_s)
            while time.monotonic() < deadline:
                depths = svc.batcher.queue_depths()
                wd = svc.watchdog
                if (depths["pending"] == 0 and depths["inflight"] == 0
                        and (wd is None or wd.inflight() == 0)):
                    break
                time.sleep(0.02)
            health = svc.health
            want = (tuple(sorted(health.active_ids()))
                    if health is not None else ())
            with self._lock:
                have = tuple(sorted(self._mesh_ids))
            if want == have:
                return  # already serving on this device set
            self.trigger(reason)
        threading.Thread(target=run, daemon=True,
                         name="device-full-remesh").start()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self.state,
                    "state_code": _SUPERVISION_STATES.get(self.state, -1),
                    "recoveries": self.c_recoveries.count,
                    "degraded_served": self.c_degraded_served.count,
                    "last_reason": self.last_reason,
                    "last_duration_seconds": round(self.last_duration_s, 4),
                    "remeshes": self.c_remeshes.count,
                    "last_remesh_duration_seconds":
                        round(self.last_remesh_duration_s, 4),
                    "mesh_devices": self.mesh_device_count,
                    "mesh_devices_full": self.full_device_count}


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------

class TpuSearchService:
    """Facade the coordinator calls: eligibility check, pack lookup,
    micro-batched execution. One instance per node."""

    def __init__(self, breaker=None, mesh=None, window_s: float = 0.01,
                 max_batch: int = 128, batch_timeout_s: float = 30.0,
                 plan_cache_size: int = 2048,
                 prewarm_concurrency: int = 4,
                 compile_cache_dir: Optional[str] = None,
                 packed_sort: bool = True,
                 compressed_pack: bool = True,
                 pallas: bool = False,
                 launch_deadline_ms: float = 120_000.0,
                 device_health: Optional[Dict[str, Any]] = None,
                 placement: Optional[Dict[str, Any]] = None,
                 delta: Optional[Dict[str, Any]] = None):
        _ensure_compile_cache(compile_cache_dir)
        KERNEL_CONFIG["packed_sort"] = bool(packed_sort)
        KERNEL_CONFIG["compressed_pack"] = bool(compressed_pack)
        KERNEL_CONFIG["pallas"] = bool(pallas)
        self.packs = IndexPackCache(mesh=mesh, breaker=breaker)
        self.plans = PlanCache(max_entries=plan_cache_size)
        self.batch_timeout_s = batch_timeout_s
        self.prewarm_concurrency = max(1, prewarm_concurrency)
        self.batcher = MicroBatcher(window_s=window_s, max_batch=max_batch)
        # pack eviction retires the pack's batch queue immediately
        self.packs.on_evict = self.batcher.retire_pack
        self.batcher.mesh = self.packs.mesh
        # the healthy-topology mesh: partial-mesh recovery shrinks
        # packs.mesh/batcher.mesh, full-mesh recovery restores THIS
        self.full_mesh = self.packs.mesh
        self.stages = StageTimes()
        self.batcher.stages = self.stages
        # device fault domains: per-device wedge scoring, micro-probe
        # quarantine, and flap-damped reintroduction (disable with
        # device_health={"enabled": False})
        hcfg = dict(device_health or {})
        self.health: Optional["DeviceHealthRegistry"] = None
        self.drain_window_s = float(hcfg.get("drain_window_seconds", 2.0))
        if hcfg.get("enabled", True):
            from elasticsearch_tpu.parallel.health import \
                DeviceHealthRegistry
            self.health = DeviceHealthRegistry(
                list(self.full_mesh.devices.flat),
                suspect_after=int(hcfg.get("suspect_after", 2)),
                probe_deadline_ms=float(
                    hcfg.get("probe_deadline_ms", 5_000.0)),
                reprobe_interval_s=float(
                    hcfg.get("reprobe_interval_seconds", 30.0)),
                hold_down_s=float(hcfg.get("hold_down_seconds", 60.0)),
                reintroduce_after=int(hcfg.get("reintroduce_after", 3)),
                on_quarantine=self._on_device_quarantine,
                on_reintroduce=self._on_device_reintroduced)
        # packs shed during a partial-mesh recovery: (index, field) →
        # shed info; try_search declines them and the coordinator
        # answers a typed 503 + Retry-After instead of silently
        # rebuilding into HBM the survivors don't have
        self._shed: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._shed_lock = threading.Lock()
        self.shed_retry_after_s = float(
            hcfg.get("shed_retry_after_seconds", 5.0))
        # pack-replica placement across device fault domains: partition
        # the mesh into `placement.groups` device groups and place each
        # pack's shard groups onto `placement.replicas` of them — a
        # quarantined chip then FAILS ITS GROUP OVER to a surviving
        # replica group instead of shedding. groups=1 (the default)
        # keeps the classic whole-mesh path byte-identical: placement
        # is None and every existing seam behaves exactly as before.
        pcfg = dict(placement or {})
        self.placement = None
        self.group_caches: Dict[int, "IndexPackCache"] = {}
        n_groups = int(pcfg.get("groups", 1))
        if n_groups > 1:
            from elasticsearch_tpu.parallel.placement import \
                PlacementService
            self.placement = PlacementService(
                list(self.full_mesh.devices.flat), n_groups,
                int(pcfg.get("replicas", 1)), breaker=breaker)
            for g in self.placement.groups():
                cache = IndexPackCache(mesh=g.mesh, breaker=g.breaker,
                                       group_id=g.gid)
                # route through self.batcher so a supervisor respawn
                # re-targets eviction at the live batcher automatically
                cache.on_evict = \
                    lambda r: self.batcher.retire_pack(r)
                self.group_caches[g.gid] = cache
        # (index, field) keys currently served by a surviving replica
        # group because their home group lost a device — the coordinator
        # stamps these responses `failed_over` (degraded but answered,
        # NEVER shed while any replica lives)
        self._failed_over: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._placement_lock = threading.RLock()
        # supervision: the watchdog deadline-stamps every dispatch and
        # trips the supervisor on a wedge; the supervisor respawns the
        # batcher (over the surviving devices) and re-attains residency
        self.watchdog = LaunchWatchdog(deadline_ms=launch_deadline_ms,
                                       on_wedge=self._on_wedge)
        self.batcher.watchdog = self.watchdog
        self.supervisor = BatcherSupervisor(self)
        # set by the node: index name → live IndexService (recovery's
        # eager re-residency path); None = rebuild lazily on traffic
        self.index_resolver = None
        self.served = 0      # queries answered by the kernel path
        self.fallback = 0    # queries declined to the planner path
        self.timeouts = 0    # kernel waits that hit the deadline
        self.last_error: Optional[str] = None  # most recent kernel failure
        # kernel-path breaker: after a batch-wait timeout the batcher
        # thread may be wedged (stuck XLA compile) — route everything to
        # the planner immediately, letting one probe through per cooldown
        # to detect recovery
        self._tripped = False
        self._next_probe = 0.0
        self.probe_cooldown_s = 30.0
        # while prewarm compiles run, try_search declines to the planner
        # (graceful cold start: early traffic must never stall a train
        # behind a cold XLA compile and trip the breaker)
        self._warming = False
        self._prewarm_lock = threading.Lock()
        self._prewarm_progress: Dict[str, Any] = {
            "state": "idle", "total": 0, "done": 0, "seconds": 0.0}
        # -- streaming delta chain (LSM resident path) -----------------
        # append-only refreshes chain small delta packs on the base
        # image; a background compactor folds them back in. Placement
        # group caches keep the classic full-rebuild path (replica
        # groups must stay byte-identical to each other).
        # opt-in: a bare TpuSearchService() keeps the classic
        # rebuild-on-refresh contract (tests and embedders rely on a
        # pack's bytes being the whole charge); Node passes the config
        # dict, so the serving stack runs with the chain on by default
        dcfg = dict(delta or {})
        self.delta_stats = DeltaStats()
        self.packs.delta_stats = self.delta_stats
        self.packs.delta_enabled = (delta is not None
                                    and bool(dcfg.get("enabled", True))
                                    and self.placement is None)
        self.packs.delta_max_packs = int(dcfg.get("max_packs", 4))
        self.packs.delta_max_docs = int(dcfg.get("max_docs", 50_000))
        self.packs.on_compact_needed = self._request_compaction
        self._compact_pending: set = set()
        self._compact_wakeup = threading.Event()
        self._compact_closed = False
        self._compact_thread: Optional[threading.Thread] = None

    # -- background compaction -----------------------------------------

    def _request_compaction(self, key) -> None:
        """Pack-cache callback: the delta chain for `key` crossed its
        fold threshold. Compaction runs on ONE background thread (a
        full pack build is minutes at scale — never on a serving
        thread), started lazily on first demand."""
        with self._prewarm_lock:
            self._compact_pending.add(tuple(key))
            if self._compact_thread is None and not self._compact_closed:
                self._compact_thread = threading.Thread(
                    target=self._compact_loop, daemon=True,
                    name="delta-compactor")
                self._compact_thread.start()
        self._compact_wakeup.set()

    def _compact_loop(self) -> None:
        while not self._compact_closed:
            self._compact_wakeup.wait(timeout=1.0)
            self._compact_wakeup.clear()
            while True:
                with self._prewarm_lock:
                    if self._compact_closed or not self._compact_pending:
                        break
                    key = self._compact_pending.pop()
                if self.degraded_active:
                    # a teardown is in flight — the chain dies with the
                    # residency drop; recovery rebuilds the full image
                    continue
                try:
                    self.packs.compact(key)
                except Exception:  # noqa: BLE001 — compact() reports
                    logger.exception("delta compaction for %s", key)

    def _on_wedge(self, wedge: Dict[str, Any]) -> None:
        """Watchdog callback (scan thread): an overdue dispatch means
        the device path is wedged — score the implicated devices
        (probing suspects synchronously, so recovery sees the updated
        quarantine set), then trip supervision."""
        label = wedge.get("label", "?")
        age_ms = float(wedge.get("age_ms", 0.0))
        self.last_error = (f"device_wedged: {label} overdue "
                           f"after {age_ms:.0f}ms")
        events.emit("watchdog.wedge", severity="error", label=label,
                    age_ms=age_ms, devices=wedge.get("devices", ()),
                    queries=wedge.get("queries", 0),
                    trace_ids=wedge.get("trace_ids", ()))
        events.incident("wedge", label=label, age_ms=age_ms,
                        devices=wedge.get("devices", ()),
                        trace_ids=wedge.get("trace_ids", ()))
        if self.health is not None:
            try:
                self.health.record_wedge(wedge.get("devices", ()),
                                         label=label)
            except Exception:  # noqa: BLE001 — supervision must trip
                logger.exception("device health scoring failed")
        if self.placement is not None and wedge.get("devices"):
            # group-attributed wedge under placement: any confirmed-bad
            # chip already failed its group over (the quarantine
            # callback ran synchronously inside record_wedge) — the
            # batcher itself is healthy, so a full teardown would
            # needlessly drop every OTHER group's residency. A wedge
            # whose probes all passed was transient: the watchdog
            # failed its cohort typed and serving continues.
            return
        self.supervisor.trigger(f"device wedge ({label}, {age_ms:.0f}ms)")

    def _on_device_quarantine(self, device_id: int) -> None:
        """Health-registry callback: a confirmed-bad chip left the
        active set. With placement, fail over ONLY the chip's group;
        classic path: respawn the whole batcher onto the survivors
        (idempotent while a wedge-triggered teardown is in flight)."""
        if self.placement is not None:
            self._group_failover(device_id,
                                 f"device {device_id} quarantined")
            return
        self.supervisor.trigger(f"device {device_id} quarantined")

    def _on_device_reintroduced(self, device_id: int) -> None:
        """Health-registry callback: a quarantined chip passed its
        consecutive-healthy-probe bar — schedule a drain-window
        recovery back onto the fuller mesh (placement: remesh only
        the chip's group and restore full placement)."""
        if self.placement is not None:
            self._schedule_group_restore(device_id)
            return
        self.supervisor.schedule_full_remesh(
            f"device {device_id} reintroduced")

    @property
    def degraded_active(self) -> bool:
        """True while the batcher is down or recovering: queries serve
        through the planner path with a degraded marker."""
        return self.supervisor.degraded_active

    @property
    def degraded_info(self) -> Optional[Dict[str, Any]]:
        """Structured degraded reason for responses/fronts/stats: None
        at full health; {"reason": "partial_mesh"|"recovering"|..,
        "devices": n, "devices_total": m} otherwise."""
        sup = self.supervisor
        total = sup.full_device_count
        if sup.degraded_active:
            return {"reason": sup.state if sup.state != "down"
                    else "batcher_down",
                    "devices": sup.mesh_device_count,
                    "devices_total": total}
        if self.placement is not None:
            active = self.placement.devices_active()
            p_total = self.placement.devices_total()
            if active < p_total:
                return {"reason": "partial_mesh",
                        "devices": active,
                        "devices_total": p_total}
        if sup.mesh_device_count < total:
            return {"reason": "partial_mesh",
                    "devices": sup.mesh_device_count,
                    "devices_total": total}
        return None

    # -- shed packs (N-1 HBM headroom) ---------------------------------

    def set_shed(self, keys: List[Tuple[str, str]],
                 retry_after_s: Optional[float] = None) -> None:
        """Replace the shed set (supervisor recovery): every listed
        (index, field) answers typed 503 + Retry-After until a fuller
        mesh re-admits it. An empty list clears the state."""
        retry = (self.shed_retry_after_s if retry_after_s is None
                 else float(retry_after_s))
        with self._shed_lock:
            self._shed = {tuple(k): {"retry_after_s": retry,
                                     "since": time.monotonic()}
                          for k in keys}
        if keys:
            logger.error("HBM headroom on the partial mesh cannot hold "
                         "%d pack(s): %s shed (503 + Retry-After %.0fs)",
                         len(keys), sorted(keys), retry)
            events.emit("pack.shed", severity="error",
                        keys=sorted(keys), retry_after_s=retry,
                        reason="partial_mesh_headroom")
            events.incident("pack_shed", keys=sorted(keys),
                            reason="partial_mesh_headroom")

    def shed_keys(self) -> List[Tuple[str, str]]:
        with self._shed_lock:
            return sorted(self._shed)

    def shed_info(self, index_name: str) -> Optional[Dict[str, Any]]:
        """Shed metadata when ANY field of `index_name` is shed (the
        coordinator's typed-503 check), else None."""
        with self._shed_lock:
            for (idx, field), info in self._shed.items():
                if idx == index_name:
                    return {"index": idx, "field": field, **info}
        return None

    def add_shed(self, keys: List[Tuple[str, str]],
                 retry_after_s: Optional[float] = None) -> None:
        """Add keys to the shed set without replacing it (placement
        failover sheds ONLY packs whose every replica is lost)."""
        retry = (self.shed_retry_after_s if retry_after_s is None
                 else float(retry_after_s))
        with self._shed_lock:
            for k in keys:
                self._shed[tuple(k)] = {"retry_after_s": retry,
                                        "since": time.monotonic()}
        if keys:
            logger.error("no placement group can hold %d pack(s): %s "
                         "shed (503 + Retry-After %.0fs)",
                         len(keys), sorted(tuple(k) for k in keys), retry)
            events.emit("pack.shed", severity="error",
                        keys=sorted(tuple(k) for k in keys),
                        retry_after_s=retry, reason="no_replica_group")
            events.incident("pack_shed",
                            keys=sorted(tuple(k) for k in keys),
                            reason="no_replica_group")

    def remove_shed(self, key: Tuple[str, str]) -> None:
        with self._shed_lock:
            self._shed.pop(tuple(key), None)

    # -- fault-domain placement (pack replicas across device groups) ---

    def failover_info(self, index_name: str) -> Optional[Dict[str, Any]]:
        """Failover metadata when ANY field of `index_name` is being
        served by a surviving replica group (the coordinator's
        `failed_over` degraded stamp), else None."""
        with self._placement_lock:
            for (idx, field), info in self._failed_over.items():
                if idx == index_name:
                    return {"index": idx, "field": field, **info}
        return None

    def _bytes_hint(self, key: Tuple[str, str]) -> int:
        """Best-known HBM cost of `key` across every group cache (0
        when never built — placement then admits and the build's own
        breaker charge is the backstop)."""
        return max((c.bytes_of(key) for c in self.group_caches.values()),
                   default=0)

    def _grouped_get(self, index_service,
                     field: str) -> Tuple[Optional[ResidentPack],
                                          Optional[int]]:
        """Placement-routed pack lookup: resolve (or create) the key's
        replica placement, route to the least-loaded healthy replica
        group, and serve from THAT group's cache. Replicas on the
        other placed groups build lazily (first access) and refresh
        whenever the routed copy observed newer readers — so a
        failover target is at most one refresh behind, and its own
        `get` re-validates against the live readers anyway."""
        pl = self.placement
        key = (index_service.name, field)
        with self._placement_lock:
            gids = pl.groups_of(key)
            if not gids:
                gids = tuple(pl.place(key,
                                      est_bytes=self._bytes_hint(key)))
        if not gids:
            return None, None
        gid = pl.route(key)
        if gid is None:
            return None, None
        resident = self.group_caches[gid].get(index_service, field)
        if resident is None:
            return None, gid
        # replica maintenance: the OTHER placed groups build/refresh
        # toward the routed copy's reader snapshot
        for g in gids:
            if g == gid or not pl.group(g).alive:
                continue
            cache = self.group_caches[g]
            peek = cache.peek(key)
            if peek is not None and peek.reader_key == resident.reader_key:
                continue
            try:
                cache.get(index_service, field)
            except Exception:  # noqa: BLE001 — a replica build failing
                # (group breaker full, transient) must not fail the
                # routed query; the key simply has one fewer warm copy
                logger.warning("replica build for %s on group %d failed",
                               key, g, exc_info=True)
        return resident, gid

    def _group_failover(self, device_id: int, reason: str) -> None:
        """A chip in one placement group was quarantined: fail over
        that group's packs to their surviving replica groups, remesh
        ONLY the affected group over its survivors, re-place only what
        has no live replica, and shed (typed 503) only packs whose
        every replica is lost."""
        pl = self.placement
        with self._placement_lock:
            gid = pl.on_device_lost(device_id)
            if gid is None:
                return
            group = pl.group(gid)
            cache = self.group_caches[gid]
            exc = DeviceWedgedError(
                f"placement group {gid} lost device {device_id} "
                f"({reason})")
            # queued queries on this group's replicas must not wait out
            # a deadline against the dead chip — fail them typed; the
            # NEXT request routes to a surviving replica group
            for resident in cache.residents():
                self.batcher.fail_pack_pending(resident, exc)
            dropped = cache.invalidate_all()
            if group.breaker is not None:
                # per-group exact-zero drain audit (the chaos suite
                # asserts every entry is exactly zero)
                pl.record_drain(gid, int(group.breaker.used))
            if group.alive:
                # remesh ONLY the affected group: the other groups'
                # meshes (and their jit caches) are untouched
                cache.set_mesh(group.mesh)
            heat = {key: cache.heat_of(key) for key in dropped}
            failed_over: List[Tuple[Tuple[str, str], int]] = []
            orphans: List[Tuple[str, str]] = []
            for key in dropped:
                pl.drop_replica(key, gid)
                live = [g for g in pl.groups_of(key) if pl.group(g).alive]
                built = [g for g in live
                         if self.group_caches[g].peek(key) is not None]
                if live:
                    failed_over.append((key, (built or live)[0]))
                else:
                    orphans.append(key)
            now = time.monotonic()
            for key, to_gid in failed_over:
                pl.c_failovers.inc()
                self._failed_over[key] = {
                    "reason": "failed_over", "from_group": gid,
                    "to_group": to_gid, "device": int(device_id),
                    "since": now}
            # re-place ONLY what has no live replica, warmest-first
            # under per-group headroom; what fits nowhere is shed
            orphans.sort(key=lambda k: heat.get(k, 0.0), reverse=True)
            shed: List[Tuple[str, str]] = []
            for key in orphans:
                placed = pl.place(key, est_bytes=self._bytes_hint(key),
                                  want=1)
                if placed:
                    pl.c_replacements.inc()
                    self._eager_rebuild(key, placed[-1])
                else:
                    pl.c_shed.inc()
                    shed.append(key)
        if shed:
            self.add_shed(shed)
        events.emit("placement.failover", severity="error", group=gid,
                    device=int(device_id), reason=reason,
                    failed_over=[k for k, _g in failed_over],
                    replaced=len(orphans) - len(shed), shed=len(shed))
        logger.error("placement failover for group %d (%s): %d pack(s) "
                     "failed over, %d re-placed, %d shed",
                     gid, reason, len(failed_over),
                     len(orphans) - len(shed), len(shed))

    def _eager_rebuild(self, key: Tuple[str, str], gid: int) -> None:
        """Best-effort eager re-residency of `key` on group `gid`
        through the index resolver; without a resolver (or on any
        build failure) the placement entry stands and the next access
        rebuilds lazily."""
        resolver = self.index_resolver
        if resolver is None:
            return
        index_name, field = key
        try:
            index_service = resolver(index_name)
        except Exception:  # noqa: BLE001 — index may be gone
            index_service = None
        if index_service is None:
            return
        try:
            self.group_caches[gid].get(index_service, field)
        except Exception:  # noqa: BLE001 — lazy rebuild remains
            logger.exception("re-attaining residency for %s/%s on "
                             "group %d", index_name, field, gid)

    def _schedule_group_restore(self, device_id: int) -> None:
        """Reintroduction under placement: wait out a drain window
        (bounded by `drain_window_s`) so the remesh interrupts as
        little in-flight work as possible, then restore the chip's
        group to full membership and the table to full placement."""
        def run() -> None:
            deadline = time.monotonic() + max(0.0, self.drain_window_s)
            while time.monotonic() < deadline:
                depths = self.batcher.queue_depths()
                wd = self.watchdog
                if (depths["pending"] == 0 and depths["inflight"] == 0
                        and (wd is None or wd.inflight() == 0)):
                    break
                time.sleep(0.02)
            try:
                self._group_restore(device_id)
            except Exception:  # noqa: BLE001 — restore must not die
                logger.exception("placement group restore failed")
        threading.Thread(target=run, daemon=True,
                         name="placement-group-restore").start()

    def _group_restore(self, device_id: int) -> None:
        pl = self.placement
        with self._placement_lock:
            gid = pl.on_device_restored(device_id)
            if gid is None:
                return
            group = pl.group(gid)
            cache = self.group_caches[gid]
            # packs resident on the group's PARTIAL mesh drop (their
            # arrays were placed with the old sharding) and rebuild on
            # the restored mesh — exact-zero drain per group, audited
            exc = DeviceWedgedError(
                f"placement group {gid} remeshing after device "
                f"{device_id} readmission")
            for resident in cache.residents():
                self.batcher.fail_pack_pending(resident, exc)
            cache.invalidate_all()
            if group.breaker is not None:
                pl.record_drain(gid, int(group.breaker.used))
            cache.set_mesh(group.mesh)
            # return to FULL placement: shed keys re-admit first
            # (they've been answering 503s), then every short placement
            # tops back up to R replicas
            for key in self.shed_keys():
                if pl.place(key, est_bytes=self._bytes_hint(key)):
                    self.remove_shed(key)
                    pl.c_replacements.inc()
            for key in pl.keys():
                if len(pl.groups_of(key)) < pl.replicas:
                    pl.place(key, est_bytes=self._bytes_hint(key))
            # failover stamps clear once a key's placement is whole
            # again (bounded by how many healthy groups exist)
            target = min(pl.replicas, len(pl.healthy_gids()))
            for key in list(self._failed_over):
                live = [g for g in pl.groups_of(key)
                        if pl.group(g).alive]
                if len(live) >= target:
                    self._failed_over.pop(key, None)
            # eager re-residency of everything placed on this group
            for key in pl.keys():
                if gid in pl.groups_of(key) and cache.peek(key) is None:
                    self._eager_rebuild(key, gid)
        events.emit("placement.restore", severity="warning", group=gid,
                    device=int(device_id),
                    devices_active=pl.devices_active(),
                    devices_total=pl.devices_total())
        logger.warning("placement group %d restored after device %d "
                       "readmission (%d/%d devices active)", gid,
                       device_id, pl.devices_active(),
                       pl.devices_total())

    def kill(self, reason: str = "killed") -> None:
        """Simulate batcher-process death (BatcherKill disruption, ops
        drills): tears down the batcher exactly as a wedge trip does."""
        self.supervisor.trigger(reason)

    def set_kernel_packed_sort(self, enabled: bool) -> None:
        """Flip the packed-sort kernel variant at runtime (the bench's
        kernel_compare mode A/Bs through this; per-launch packability
        fallback still applies when enabling)."""
        KERNEL_CONFIG["packed_sort"] = bool(enabled)

    @property
    def kernel_packed_sort(self) -> bool:
        return KERNEL_CONFIG["packed_sort"]

    def set_kernel_compressed_pack(self, enabled: bool) -> None:
        """Flip compressed-pack residency at runtime. BUILD-time: only
        packs built after the flip change format — callers that need the
        new format now (the bench's kernel_compare) also invalidate."""
        KERNEL_CONFIG["compressed_pack"] = bool(enabled)

    @property
    def kernel_compressed_pack(self) -> bool:
        return KERNEL_CONFIG["compressed_pack"]

    def set_kernel_pallas(self, enabled: bool) -> None:
        """Flip the fused-Pallas serving variant at runtime (launch-time:
        the next lowering pass picks it up; choose_kernel_variant still
        falls back to "compressed" when Pallas is unavailable or the
        batch isn't packable)."""
        KERNEL_CONFIG["pallas"] = bool(enabled)

    @property
    def kernel_pallas(self) -> bool:
        return KERNEL_CONFIG["pallas"]

    def invalidate_index(self, index_name: str) -> None:
        """Drop resident packs AND lowered plans of a deleted/closed
        index (releases HBM breaker bytes and pinned readers)."""
        self.packs.invalidate(index_name)
        self.plans.invalidate_index(index_name)

    def invalidate_plans(self, index_name: str) -> None:
        """Drop only the lowered-plan entries for an index (mapping
        updates: the pack may still be valid, the lowering isn't — and
        the generation key change has already made the old entries
        unreachable; this purge keeps the LRU from carrying them)."""
        self.plans.invalidate_index(index_name)

    def try_search(self, index_service, query: dsl.QueryNode, *,
                   k: int,
                   timeout_s: Optional[float] = None,
                   profile_sink: Optional[Dict[str, Any]] = None
                   ) -> Optional[FlatQueryResult]:
        """Returns the kernel result, or None → caller uses the planner.
        k = from + size (top window the coordinator needs). timeout_s
        bounds the batch wait (a request deadline); the service cap
        applies regardless. profile_sink (a `profile: true` search)
        receives the kernel-side story: variant, plan-cache outcome,
        and this query's per-stage host timings."""
        if k <= 0 or k > 10_000:
            self.fallback += 1
            return None
        if self._warming:
            # cold-start grace: prewarm compiles are in flight — first
            # traffic routes to the planner instead of stalling behind a
            # cold compile (the 8.8M-doc first-train stall + breaker trip)
            self.fallback += 1
            return None
        if self.supervisor.degraded_active:
            # batcher down or recovering: degraded-mode serving — the
            # planner answers (with a degraded marker) instead of
            # queueing behind a dead batcher
            self.fallback += 1
            self.supervisor.c_degraded_served.inc()
            self.supervisor.maybe_recover()
            return None
        t0 = time.perf_counter()
        pkey = plan_key(query)
        cache_key = None
        if pkey is not None:
            gen = getattr(index_service.mapper, "generation", 0)
            cache_key = (index_service.name, gen, pkey)
        cached = self.plans.get(cache_key) if cache_key is not None else None
        if cached is NOT_LOWERABLE:
            self.stages.add("lower", time.perf_counter() - t0)
            self.fallback += 1
            return None
        cached_rk = None
        if cached is not None:
            flat, cached_rk = cached
        else:
            flat = lower_query(query, index_service.mapper)
            if flat is None:
                if cache_key is not None:
                    self.plans.put(cache_key, NOT_LOWERABLE)
                self.stages.add("lower", time.perf_counter() - t0)
                self.fallback += 1
                return None
        t1 = time.perf_counter()
        with self._shed_lock:
            is_shed = (index_service.name, flat.field) in self._shed
        if is_shed:
            # the partial mesh shed this pack: never rebuild it here
            # (that would overcommit the survivors' HBM) — the
            # coordinator answers the typed 503 + Retry-After
            self.fallback += 1
            return None
        route_gid: Optional[int] = None
        chain: Optional[PackChain] = None
        if self.placement is not None:
            resident, route_gid = self._grouped_get(index_service,
                                                    flat.field)
            if resident is None and route_gid is None:
                # no healthy replica group right now — planner serves
                self.fallback += 1
                return None
        else:
            # chain-aware residency: an append-only refresh rides as a
            # small delta pack unioned into the result instead of a full
            # rebuild; with deltas disabled this degenerates to get()
            chain = self.packs.get_chain(index_service, flat.field)
            resident = None if chain is None else chain.base
        t2 = time.perf_counter()
        self.stages.add("lower", t1 - t0)
        self.stages.add("pack_get", t2 - t1)
        if resident is None:
            # field has no postings anywhere → zero hits, kernel-free
            self.served += 1
            if profile_sink is not None:
                profile_sink["empty_pack"] = True
            return FlatQueryResult.empty()
        # plans validate against the CHAIN's reader key when one exists:
        # the base pack keeps its (older) key while deltas cover the new
        # segments, and a plan is valid for exactly that reader set
        rkey = chain.reader_key if chain is not None else resident.reader_key
        plan_outcome = ("uncacheable" if cache_key is None
                        else "hit" if cached is not None else "miss")
        if cache_key is not None:
            if cached is None:
                self.plans.put(cache_key, (flat, rkey))
            elif cached_rk != rkey:
                plan_outcome = "revalidated"
                # the resident pack was rebuilt since this plan was
                # cached (refresh/merge mid-traffic): re-lower so no
                # plan ever runs against a pack it wasn't validated
                # on, then re-pin the entry to the live pack
                flat = lower_query(query, index_service.mapper)
                if flat is None:
                    self.plans.put(cache_key, NOT_LOWERABLE)
                    self.fallback += 1
                    return None
                self.plans.put(cache_key, (flat, rkey))
        if self._tripped:
            now = time.monotonic()
            if now < self._next_probe:
                self.fallback += 1
                return None
            self._next_probe = now + self.probe_cooldown_s  # one probe
        # The kernel path is an optional accelerator: any failure here
        # must degrade to the planner, never surface as an error
        # (EnginePlugin seam contract — an engine swap preserves behavior).
        try:
            t_sub = time.perf_counter()
            # go through submit() — the seam fault-injection tests hook —
            # and read the decomposition marks back off the future (a
            # mocked future simply has no marks: split degrades to None)
            fut = self.batcher.submit(resident, flat, k)
            if route_gid is not None:
                # per-group load accounting: route() balances launches
                # across a key's replica groups by in-flight count
                self.placement.note_submit(route_gid)
                fut.add_done_callback(
                    lambda _f, g=route_gid: self.placement.note_done(g))
            # the delta chain's packs are extra operands of the SAME
            # lowered query: each delta batches independently (its own
            # micro-batch queue keyed by pack identity) and the columns
            # merge host-side — disjoint row spaces, totals add
            delta_futs = []
            if chain is not None and chain.deltas:
                delta_futs = [self.batcher.submit(d, flat, k)
                              for d in chain.deltas]
            pending = getattr(fut, "pending", None)
            # the batch wait is bounded: the service cap (default 30s —
            # the FIRST batch on a signature pays XLA compile; if it
            # exceeds the cap the query plans instead and the compiled
            # kernel serves later probes) further tightened by the
            # request's own deadline. A stalled kernel must never pin an
            # HTTP thread for minutes (VERDICT r2 weak: 300s wait).
            wait = self.batch_timeout_s
            deadline_limited = (timeout_s is not None
                                and timeout_s < self.batch_timeout_s)
            if deadline_limited:
                wait = max(0.05, timeout_s)
            result = fut.result(timeout=wait)
            if delta_futs:
                # one SHARED deadline across the union: the base wait
                # already consumed part of it, the deltas get the rest
                deadline = t_sub + wait
                parts = [result]
                for df in delta_futs:
                    remaining = max(0.01, deadline - time.perf_counter())
                    parts.append(df.result(timeout=remaining))
                result = self._union_results(parts, chain, k)
        except FuturesTimeout:
            self.fallback += 1
            self.timeouts += 1
            if deadline_limited:
                # the REQUEST's deadline expired, which says nothing
                # about batcher health — fall back without tripping the
                # node-wide breaker
                self.last_error = "request deadline during kernel batch"
                return None
            # the full service cap elapsed: the batcher may be wedged
            # (stuck XLA compile) — trip the kernel-path breaker so
            # subsequent queries plan immediately
            self._tripped = True
            self._next_probe = time.monotonic() + self.probe_cooldown_s
            self.last_error = "timeout waiting for kernel batch"
            logger.error("tpu kernel batch timed out; tripping kernel "
                         "breaker (probe every %.0fs)", self.probe_cooldown_s)
            return None
        except DeviceWedgedError as exc:
            # typed wedge/teardown failure: the watchdog/supervisor
            # already handled the batcher — just degrade this query
            self.fallback += 1
            self.last_error = f"device_wedged: {exc}"
            return None
        except Exception as exc:  # noqa: BLE001 — degrade, never 500
            self.fallback += 1
            self.last_error = f"{type(exc).__name__}: {exc}"
            logger.exception("tpu kernel path failed; falling back")
            return None
        self._tripped = False  # a completed batch proves the path is live
        self.served += 1
        t_done = time.perf_counter()
        self.stages.add("batch_wait", t_done - t_sub)
        split = self._record_batch_wait_split(pending, t_sub, t_done)
        if profile_sink is not None:
            profile_sink.update({
                "variant": result.variant
                or ("packed" if KERNEL_CONFIG["packed_sort"] else "ref"),
                "plan_cache": plan_outcome,
                "stages_ms": {
                    "lower": round((t1 - t0) * 1e3, 4),
                    "pack_get": round((t2 - t1) * 1e3, 4),
                    "batch_wait": round((t_done - t_sub) * 1e3, 4),
                },
            })
            if split:
                profile_sink["stages_ms"]["batch_wait_split"] = {
                    name: round(dt * 1e3, 4) for name, dt in split.items()}
        return result

    def _record_batch_wait_split(self, pending, t_sub: float,
                                 t_done: float) -> Optional[Dict[str, float]]:
        """Decompose one query's batch_wait into queue (submit → the
        worker's train cycle), window (batching window), dispatch
        (host-side staging inside launch), and completion (device→host
        + decode + callback). All four are measured from marks the
        workers stamped on the `_Pending`, anchored to the same
        request-thread clock as `batch_wait` — so the parts sum to the
        aggregate exactly, by construction."""
        if pending is None:
            return None  # a mocked/foreign future carries no marks
        t_c, t_t, t_l = pending.t_cycle, pending.t_take, pending.t_launched
        if not t_t or not t_l:
            return None  # launch path didn't stamp (shouldn't happen)
        split = {
            "queue": max(0.0, t_c - t_sub),
            "window": max(0.0, t_t - max(t_sub, t_c)),
            "dispatch": max(0.0, t_l - t_t),
            "completion": max(0.0, t_done - t_l),
        }
        variant = "packed" if KERNEL_CONFIG["packed_sort"] else "ref"
        for name, dt in split.items():
            self.stages.add(f"batch_wait.{name}", dt)
            self.stages.add(f"batch_wait.{name}.{variant}", dt)
        return split

    @staticmethod
    def _union_results(parts: List["FlatQueryResult"], chain: PackChain,
                       k: int) -> "FlatQueryResult":
        """Merge base + delta kernel results into one top-k over the
        chain's concatenated row space. The operands score DISJOINT doc
        sets (deltas cover only segments the base doesn't), so totals
        add and no dedup is needed; ties prefer the base pack, then
        in-pack kernel rank (stable across chain growth)."""
        scores, rows, ords = sparse.union_topk(
            [p.scores for p in parts],
            [p.rows for p in parts],
            [p.ords for p in parts],
            chain.view.offsets, k)
        max_score = None
        candidates = [p.max_score for p in parts if p.max_score is not None]
        if candidates:
            max_score = float(max(candidates))
        return FlatQueryResult(
            scores=scores, rows=rows, ords=ords,
            total_hits=sum(int(p.total_hits) for p in parts),
            max_score=max_score,
            resident=chain.view,
            total_relation=("gte" if any(p.total_relation == "gte"
                                         for p in parts) else "eq"),
            variant=parts[0].variant)

    def prewarm(self, index_service, field: str,
                concurrency: Optional[int] = None) -> Dict[str, Any]:
        """Build the (index, field) resident pack and compile every
        steady-state serving signature NOW, instead of on the first
        query (the reference's index-warmer seam, `IndicesWarmer` /
        `index.warmer`; VERDICT r3 #3: first-compile must not stall or
        degrade production traffic). Returns timing info.

        The signature table is DEDUPED by canonical jit signature
        (batch bucket × candidate-k bucket × width/prefix) — the raw
        k values 10 and 1000 collapse into the same compiled kernel
        whenever they share a candidate bucket — and the compiles run
        on `concurrency` worker threads (XLA compilation releases the
        GIL). Traffic arriving mid-warm degrades to the planner via
        `_warming` instead of stalling a train. With the persistent
        compilation cache this whole pass is cache-replay fast after
        the first-ever run on a machine."""
        t0 = time.perf_counter()
        workers = max(1, concurrency or self.prewarm_concurrency)
        with self._prewarm_lock:
            self._prewarm_progress = {"state": "warming", "total": 0,
                                      "done": 0, "seconds": 0.0}
        self._warming = True
        try:
            replicas: List[ResidentPack] = []
            if self.placement is not None:
                # warm the copies serving will actually use: the routed
                # replica plus every other placed replica (a failover
                # target that is resident-but-cold would compile on its
                # first post-failover hit — exactly the stall the warmer
                # exists to prevent). The legacy full-mesh cache is NOT
                # touched: nothing serves from it under placement.
                resident, _gid = self._grouped_get(index_service, field)
                if resident is not None:
                    key = (index_service.name, field)
                    for g in self.placement.groups_of(key):
                        peek = self.group_caches[g].peek(key)
                        if peek is not None and peek is not resident:
                            replicas.append(peek)
            else:
                resident = self.packs.get(index_service, field)
            t_pack = time.perf_counter() - t0
            compiled: List[Dict[str, Any]] = []
            if resident is not None:
                for r in [resident] + replicas:
                    self._compile_signatures(r, field, compiled,
                                             workers)
            return {"pack_seconds": round(t_pack, 2),
                    "compiled": compiled,
                    "total_seconds": round(time.perf_counter() - t0, 2)}
        finally:
            self._warming = False
            with self._prewarm_lock:
                self._prewarm_progress["state"] = "done"
                self._prewarm_progress["seconds"] = round(
                    time.perf_counter() - t0, 2)

    def prewarm_async(self, index_service, field: str,
                      concurrency: Optional[int] = None) -> threading.Thread:
        """Kick prewarm off the caller's thread (node startup / first
        index of traffic). try_search degrades to the planner until the
        warm completes; progress is visible in stats()["prewarm"]."""
        t = threading.Thread(
            target=lambda: self.prewarm(index_service, field,
                                        concurrency=concurrency),
            daemon=True, name="tpu-prewarm")
        t.start()
        return t

    def _compile_signatures(self, resident: ResidentPack, field: str,
                            compiled: List[Dict[str, Any]],
                            workers: int) -> None:
        from concurrent.futures import ThreadPoolExecutor

        # a placement-group replica compiles against its group's
        # sub-mesh — warming it on the full mesh would populate a jit
        # cache serving never reads
        mesh = getattr(resident, "group_mesh", None) or self.packs.mesh
        terms = []
        for v in resident.pack.vocabs:
            if v:
                terms = [next(iter(v))]
                break
        flat = FlatQuery(field, terms or ["_warm_"], 1.0, 1)
        buckets = [8, 64, _serving_bucket(self.batcher.max_batch)]
        buckets = sorted(set(buckets))
        table = []   # (batch, k, slots|None, prefix|None)
        for b_bucket in buckets:
            for k in (10, PRUNE_MAX_K):
                for slots in FULL_SLOT_BUCKETS:
                    table.append((b_bucket, k, slots, None))
                table.append((b_bucket, k, None, PREFIX_CAP2))
        # the PREFIX_CAP3 escalation runs inline in the batch
        # completer with clients waiting — it must NEVER compile
        # there (a cold compile at multi-million-doc shapes blows
        # the batch timeout and trips the kernel breaker); BOTH
        # k-bucket signatures (k_cand 128 and 2048) are reachable
        for b_bucket in buckets:
            for k in (10, PRUNE_MAX_K):
                table.append((b_bucket, k, None, PREFIX_CAP3))
        # both kernel variants warm when packed sorting is on: "ref"
        # stays reachable (per-launch packability fallback, the runtime
        # toggle, the bench A/B) and must never cold-compile inside the
        # batch completer. Pruned kernels never pack their gid keys, so
        # their "packed" variant differs only in the top-k reduction.
        # compressed packs have no impact-sorted copy — the pruned table
        # is unreachable, and the exact kernel runs the compressed pair
        # (both reachable: per-launch weight fallback picks the exact
        # decode variant)
        if resident.comp_streams is not None:
            pruned_variants: Tuple[str, ...] = ()
        elif KERNEL_CONFIG["packed_sort"]:
            pruned_variants = ("packed", "ref")
        else:
            pruned_variants = ("ref",)
        from elasticsearch_tpu.ops import sparse as _sparse
        if resident.comp_streams is not None:
            exact_variants: Tuple[str, ...] = ("compressed",
                                               "compressed_exact")
            if KERNEL_CONFIG["pallas"]:
                from elasticsearch_tpu.ops import pallas_merge
                if pallas_merge.available():
                    exact_variants = ("pallas",) + exact_variants
        elif (KERNEL_CONFIG["packed_sort"]
                and _sparse.packable(resident.pack.d_pad)):
            exact_variants = ("packed", "ref")
        else:
            exact_variants = ("ref",)
        # dedupe to canonical jit signatures: the kernel is compiled per
        # (batch bucket, candidate-k bucket, width|prefix, variant) —
        # requested k values that bucket identically would recompile
        # NOTHING, so warming them again just serializes the warmer
        seen = set()
        jobs = []  # (entry, run)
        for b_bucket, k, slots, cap in table:
            for variant in pruned_variants:
                sig = (b_bucket, _candidate_k(k), slots, cap, variant)
                if sig in seen:
                    continue
                seen.add(sig)
                jobs.append(({"batch": b_bucket, "k": k, "slots": slots,
                              "prefix": cap, "variant": variant},
                             lambda b_bucket=b_bucket, k=k, slots=slots,
                             cap=cap, variant=variant: _execute_pruned(
                                 resident, [flat] * b_bucket, k,
                                 mesh,
                                 prefix_cap=cap or PREFIX_CAP2,
                                 full_slots=slots, variant=variant)))
        # exact kernel (msm/AND tier 1, OR tier 3) at its common
        # bucketed signatures; with_counts=True via min_count=2.
        # Hot-term slot buckets (t_slots > 8) compile once ever and
        # persist in the compilation cache.
        flat_and = FlatQuery(flat.field, flat.terms * 2, 1.0, 2)
        for b_bucket, k in ((8, 10), (64, PRUNE_MAX_K)):
            for variant in exact_variants:
                jobs.append(({"batch": b_bucket, "k": k, "exact": True,
                              "variant": variant},
                             lambda b_bucket=b_bucket, k=k,
                             variant=variant: _execute_exact(
                                 resident, [flat_and] * b_bucket, k,
                                 mesh, variant=variant)))
        with self._prewarm_lock:
            self._prewarm_progress["total"] += len(jobs)
        # prewarm is BEST-EFFORT per signature: one kernel that the
        # backend cannot compile at this pack's shapes (observed: the
        # compile helper dying on the exact kernel at MS-MARCO scale)
        # must not abort the warmer — serving degrades that one path to
        # the planner, the rest stay kernel-served. A run of failures
        # (>= 3 with no success in between) is systemic: skip the rest.
        fail_lock = threading.Lock()
        consecutive_failures = [0]

        def warm_one(entry, run):
            with fail_lock:
                if consecutive_failures[0] >= 3:
                    entry["error"] = "skipped: systemic prewarm failure"
                    compiled.append(entry)
                    with self._prewarm_lock:
                        self._prewarm_progress["done"] += 1
                    return
            t1 = time.perf_counter()
            try:
                run()
                with fail_lock:
                    consecutive_failures[0] = 0
            except Exception as exc:  # noqa: BLE001 — record, go on
                entry["error"] = f"{type(exc).__name__}: {exc}"[:160]
                with fail_lock:
                    consecutive_failures[0] += 1
                logger.warning("prewarm %s failed: %s", entry, exc)
            finally:
                # failures carry their cost too (a 90s compile that
                # dies is exactly what the warmer must surface)
                entry["seconds"] = round(time.perf_counter() - t1, 2)
            compiled.append(entry)
            with self._prewarm_lock:
                self._prewarm_progress["done"] += 1

        if workers <= 1 or len(jobs) <= 1:
            for entry, run in jobs:
                warm_one(entry, run)
            return
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="tpu-prewarm") as pool:
            futs = [pool.submit(warm_one, entry, run)
                    for entry, run in jobs]
            for f in futs:
                f.result()

    def stats(self) -> Dict[str, Any]:
        with self._prewarm_lock:
            prewarm = dict(self._prewarm_progress)
        d_packs, d_bytes = self.packs.delta_totals()
        ds = self.delta_stats
        return {"served": self.served, "fallback": self.fallback,
                "timeouts": self.timeouts, "tripped": self._tripped,
                "last_error": self.last_error,
                "batches": self.batcher.batches_executed,
                "batched_queries": self.batcher.queries_executed,
                "plan_cache": self.plans.stats(),
                "pack_cache": self.packs.stats(),
                "deltas": {"enabled": self.packs.delta_enabled,
                           "packs": d_packs, "bytes": d_bytes,
                           "appends": ds.appends, "seals": ds.seals,
                           "compactions": ds.compactions,
                           "compaction_failures": ds.compaction_failures,
                           "replayed_ops": ds.replayed_ops,
                           "compact_seconds": round(ds.compact_seconds, 4)},
                "prewarm": prewarm,
                "kernel": {"packed_sort": KERNEL_CONFIG["packed_sort"],
                           "compressed_pack":
                               KERNEL_CONFIG["compressed_pack"],
                           "pallas": KERNEL_CONFIG["pallas"],
                           "variants": KERNEL_VARIANT_COUNTS.counts()},
                "queue": self.batcher.queue_depths(),
                "supervision": self.supervisor.stats(),
                "watchdog": self.watchdog.stats(),
                "devices": self.device_stats(),
                "stages": self.stages.snapshot()}

    def device_stats(self) -> Dict[str, Any]:
        """The /_tpu/stats `devices` block: health registry view plus
        the supervisor's mesh topology and shed set."""
        sup = self.supervisor
        out: Dict[str, Any] = {
            "mesh_devices": sup.mesh_device_count,
            "mesh_devices_full": sup.full_device_count,
            "remeshes": sup.c_remeshes.count,
            "last_remesh_duration_seconds":
                round(sup.last_remesh_duration_s, 4),
            "shed_packs": [f"{i}/{f}" for i, f in self.shed_keys()],
            "degraded": self.degraded_info,
        }
        if self.health is not None:
            out["health"] = self.health.stats()
        if self.placement is not None:
            placement = self.placement.stats()
            with self._placement_lock:
                placement["failed_over"] = {
                    f"{i}/{f}": dict(info)
                    for (i, f), info in self._failed_over.items()}
            placement["group_packs"] = {
                str(gid): cache.resident_keys()
                for gid, cache in sorted(self.group_caches.items())}
            out["placement"] = placement
        return out

    def close(self) -> None:
        self._compact_closed = True
        self._compact_wakeup.set()
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self.watchdog.close()
        if self.health is not None:
            self.health.close()
        self.batcher.close()


_cache_configured = False


def _ensure_compile_cache(path: Optional[str] = None) -> None:
    """Persistent XLA compilation cache (VERDICT r3 #3): keyed on disk so
    a process restart reuses every serving-kernel compile instead of
    paying the 30-80s first-compile again. Precedence: the
    ES_TPU_JAX_CACHE_DIR env var (opt out with ''), then the caller's
    `path` (a node passes `search.tpu_serving.compile_cache_dir` or a
    directory under its data path), then ~/.cache. First caller wins —
    jax holds ONE cache dir per process."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    import os

    # shared with the seed_compile_cache exporter/importer so "the dir
    # the node compiles into" and "the dir the seeder packs/unpacks"
    # can never drift apart
    from elasticsearch_tpu.tools.seed_compile_cache import \
        compile_cache_dir
    path = compile_cache_dir(path)
    if not path:
        return
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # persist anything over ~100ms: at small corpus scales individual
        # serving signatures compile in 0.3-0.9s but the full prewarm
        # table of them still costs minutes — all of it cacheable
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as exc:  # cache is an optimization, never fatal
        logger.warning("persistent compile cache unavailable: %s", exc)
