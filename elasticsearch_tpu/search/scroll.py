"""Scroll + point-in-time search orchestration over pinned contexts.

Reference: `RestSearchScrollAction` / `RestClearScrollAction` /
`RestOpenPointInTimeAction`, `SearchService#executeQueryPhase` against a
ReaderContext (SURVEY.md §2.1#36). Kept contracts: `_scroll_id` in every
scroll response, pages end with an empty hits array, cleared scrolls
return num_freed, PIT search bodies name the context (`"pit": {"id"}`)
and responses echo `pit_id`, and a context is a STABLE snapshot —
deletes/writes after creation never change what it returns.

Contexts are node-local (like the reference). In cluster mode the
coordinating node serves them only when every target shard is local;
distributed contexts are not offered yet — callers get a clear 400
instead of wrong pages."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.search import coordinator
from elasticsearch_tpu.search.contexts import parse_keep_alive


def _resolve_and_check(node, index_expr: Optional[str]) -> List[str]:
    """Resolve target indices against the CLUSTER view (never just the
    local registry — a wildcard must see remote-hosted indices too) and
    reject any target whose shards aren't all local."""
    if node.cluster is None:
        return coordinator.resolve_indices(node.indices, index_expr)
    names = node.cluster.resolve_indices(index_expr)
    state = node.cluster.applied_state()
    local = node.node_id
    for name in names:
        meta = state.indices.get(name)
        if meta is None:
            continue
        for shard in range(meta.number_of_shards):
            primary = state.primary(name, shard)
            if primary is None or primary.node_id != local:
                raise IllegalArgumentException(
                    "scroll/point-in-time contexts require every target "
                    "shard on the coordinating node; distributed "
                    "contexts are not supported yet")
    return names


# ----------------------------------------------------------------------
# scroll
# ----------------------------------------------------------------------

def start_scroll(node, index_expr: Optional[str], body: Dict[str, Any],
                 params: Dict[str, str], task=None) -> Dict[str, Any]:
    keep_alive = parse_keep_alive(params["scroll"], "scroll")
    names = _resolve_and_check(node, index_expr)
    size = int(params.get("size", (body or {}).get("size", 10)))
    ctx = node.search_contexts.create(
        node.indices, index_expr, keep_alive, names=names,
        scroll_state={"body": dict(body or {}), "params": dict(params),
                      "offset": 0, "size": size, "cursor": None})
    return _scroll_execute(node, ctx, task=task)


def next_page(node, scroll_id: str,
              keep_alive: Optional[str] = None) -> Dict[str, Any]:
    ctx = node.search_contexts.get(scroll_id)
    if ctx.scroll_state is None:
        raise IllegalArgumentException(
            f"context [{scroll_id}] is a point-in-time, not a scroll")
    ctx.touch(parse_keep_alive(keep_alive, "scroll")
              if keep_alive else None)
    return _scroll_execute(node, ctx)


def _scroll_execute(node, ctx, task=None) -> Dict[str, Any]:
    state = ctx.scroll_state
    body = dict(state["body"])
    size = state["size"]
    body["size"] = size
    sorted_scroll = bool(body.get("sort"))
    appended_tiebreak = False
    if sorted_scroll:
        # sorted scrolls page via an internal search_after cursor over
        # the pinned snapshot: each page is O(size) per shard, not
        # O(offset+size) — sort by _doc for the cheapest deep scroll,
        # exactly the reference's guidance.
        # The cursor needs a per-doc tiebreaker or boundary TIES would
        # be skipped (strictly-after semantics): append an internal
        # _doc spec (shard-unique global ordinal) unless one is present,
        # and strip its value from the response hits.
        sort_spec = body["sort"]
        if not isinstance(sort_spec, list):
            sort_spec = [sort_spec]
        def _field_of(entry):
            return entry if isinstance(entry, str) \
                else next(iter(entry), None)
        if all(_field_of(e) != "_doc" for e in sort_spec):
            sort_spec = list(sort_spec) + ["_doc"]
            appended_tiebreak = True
        body["sort"] = sort_spec
        body["from"] = 0
        if state.get("cursor") is not None:
            body["search_after"] = state["cursor"]
    else:
        # score-ordered scroll (no sort): from/size re-pagination over
        # the snapshot — correct, but deep scrolls re-collect the
        # consumed prefix; sort by _doc to avoid that
        body["from"] = state["offset"]
    params = {k: v for k, v in state["params"].items()
              if k not in ("scroll", "size", "from")}
    out = coordinator.search(node.indices, None, body, params,
                             task=task, pinned=ctx.readers,
                             names_override=ctx.names)
    hits = out["hits"]["hits"]
    if out.get("timed_out"):
        # a partial page must not consume the cursor: the client retries
        # the same window instead of silently skipping unvisited shards
        pass
    elif sorted_scroll:
        if hits:
            state["cursor"] = hits[-1].get("sort")
    else:
        state["offset"] = state["offset"] + len(hits)
    if appended_tiebreak:
        # the internal tiebreaker is not part of the user's sort — keep
        # the response shape reference-faithful
        for h in hits:
            if isinstance(h.get("sort"), list) and h["sort"]:
                h["sort"] = h["sort"][:-1]
    out["_scroll_id"] = ctx.id
    return out


def clear(node, ids: Optional[List[str]]) -> Dict[str, Any]:
    if not ids or ids == ["_all"]:
        freed = node.search_contexts.free_all(scroll_only=True)
    else:
        freed = sum(1 for i in ids
                    if node.search_contexts.free(i, kind="scroll"))
    return {"succeeded": True, "num_freed": freed}


# ----------------------------------------------------------------------
# point-in-time
# ----------------------------------------------------------------------

def open_pit(node, index_expr: Optional[str],
             keep_alive: str) -> Dict[str, Any]:
    seconds = parse_keep_alive(keep_alive, "open_point_in_time")
    names = _resolve_and_check(node, index_expr)
    ctx = node.search_contexts.create(node.indices, index_expr, seconds,
                                      names=names)
    return {"id": ctx.id}


def search_pit(node, body: Dict[str, Any], params: Dict[str, str],
               task=None) -> Dict[str, Any]:
    pit = body.get("pit") or {}
    pit_id = pit.get("id")
    if not pit_id:
        raise IllegalArgumentException("[pit] requires [id]")
    ctx = node.search_contexts.get(pit_id)
    if ctx.scroll_state is not None:
        raise IllegalArgumentException(
            f"context [{pit_id}] is a scroll, not a point-in-time")
    if pit.get("keep_alive"):
        ctx.touch(parse_keep_alive(pit["keep_alive"], "pit"))
    else:
        ctx.touch()
    body = {k: v for k, v in body.items() if k != "pit"}
    out = coordinator.search(node.indices, None, body, params,
                             task=task, pinned=ctx.readers,
                             names_override=ctx.names)
    out["pit_id"] = ctx.id
    return out


def close_pit(node, pit_id: str) -> Dict[str, Any]:
    freed = node.search_contexts.free(pit_id, kind="pit")
    return {"succeeded": freed, "num_freed": 1 if freed else 0}
