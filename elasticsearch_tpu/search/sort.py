"""Field sort + search_after — doc-values-backed result ordering.

Reference: `search/sort/FieldSortBuilder`, `ScoreSortBuilder`,
`SearchAfterBuilder` (SURVEY.md §2.1#50). Semantics kept:

  - sort spec grammar: "field" | {"field": "asc"} |
    {"field": {"order": ..., "missing": "_last"|"_first"|value}} |
    "_score" (desc default) | "_doc"
  - missing values default to _last regardless of direction
  - search_after is a stateless cursor of the previous page's last sort
    values; a doc qualifies iff its sort tuple is strictly after the
    cursor in sort order
  - hits carry their "sort" values; max_score is null when sorting by
    anything but _score (the reference's behavior without track_scores)

Keys are built per segment from the pack's doc-value columns (numeric
i64/f64, keyword ordinals mapped through ord_terms); the cross-segment /
cross-shard merge compares python value tuples with direction-aware
comparators.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.index.segment import MISSING_I64


@dataclasses.dataclass
class SortSpec:
    field: str                      # field name | "_score" | "_doc"
    order: str = "asc"              # "asc" | "desc"
    missing: Any = "_last"          # "_last" | "_first" | literal value


def parse_sort(spec: Any) -> List[SortSpec]:
    """Reference grammar (FieldSortBuilder#fromXContent)."""
    if spec is None:
        return []
    if not isinstance(spec, list):
        spec = [spec]
    out: List[SortSpec] = []
    for entry in spec:
        if isinstance(entry, str):
            default = "desc" if entry == "_score" else "asc"
            out.append(SortSpec(entry, default))
        elif isinstance(entry, dict):
            if len(entry) != 1:
                raise IllegalArgumentException(
                    "[sort] entry must name exactly one field")
            field, opts = next(iter(entry.items()))
            if isinstance(opts, str):
                opts = {"order": opts}
            if not isinstance(opts, dict):
                raise IllegalArgumentException(
                    f"[sort] malformed options for [{field}]")
            order = opts.get("order", "desc" if field == "_score" else "asc")
            if order not in ("asc", "desc"):
                raise IllegalArgumentException(
                    f"[sort] unknown order [{order}]")
            out.append(SortSpec(field, order, opts.get("missing", "_last")))
        else:
            raise IllegalArgumentException("[sort] malformed sort entry")
    return out


# ---------------------------------------------------------------------------
# per-segment key extraction
# ---------------------------------------------------------------------------

def segment_sort_values(reader, view_idx: int,
                        specs: Sequence[SortSpec],
                        scores: np.ndarray) -> List[np.ndarray]:
    """One value array per spec, aligned to segment doc ordinals.
    Numeric → f64 (NaN = missing), keyword → object array (None =
    missing), _score → scores, _doc → ordinals."""
    view = reader.views[view_idx]
    seg = view.segment
    n = seg.num_docs
    out: List[np.ndarray] = []
    for spec in specs:
        if spec.field == "_score":
            out.append(np.asarray(scores[:n], dtype=np.float64))
            continue
        if spec.field == "_doc":
            out.append(np.arange(n, dtype=np.float64))
            continue
        col = seg.doc_values.get(spec.field)
        if col is None:
            vals = np.full(n, np.nan)
            out.append(vals)
            continue
        if col.kind == "ord":
            obj = np.empty(n, dtype=object)
            terms = col.ord_terms or []
            for i in range(n):
                o = int(col.values[i])
                obj[i] = terms[o] if o >= 0 else None
            out.append(obj)
        elif col.kind == "f64":
            out.append(col.values.astype(np.float64, copy=True))
        else:
            vals = col.values.astype(np.float64, copy=True)
            vals[col.values == MISSING_I64] = np.nan
            out.append(vals)
    return out


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


def _element_key(spec: SortSpec, v: Any) -> Tuple:
    """Ascending-comparable key for one sort element honoring order +
    missing placement. Shape: (missing_rank, direction-adjusted value)."""
    if _is_missing(v):
        if spec.missing == "_first":
            return (0, 0)
        if spec.missing == "_last":
            return (2, 0)
        v = spec.missing  # literal replacement value
    if isinstance(v, str):
        # strings can't negate: desc uses an inverted-codepoint key
        key: Any = v if spec.order == "asc" else _invert_str(v)
    else:
        key = v if spec.order == "asc" else -float(v)
    return (1, key)


def _invert_str(s: str) -> Tuple:
    return tuple(-ord(c) for c in s) + (float("inf"),)


def sort_key(specs: Sequence[SortSpec], values: Sequence[Any]) -> Tuple:
    return tuple(_element_key(s, v) for s, v in zip(specs, values))


def after_mask(specs: Sequence[SortSpec], value_arrays: List[np.ndarray],
               cursor: Sequence[Any]) -> np.ndarray:
    """bool[n]: docs whose sort tuple is STRICTLY after the cursor."""
    if len(cursor) != len(specs):
        raise IllegalArgumentException(
            f"[search_after] expects {len(specs)} values, "
            f"got {len(cursor)}")
    n = len(value_arrays[0]) if value_arrays else 0
    after = np.zeros(n, dtype=bool)
    equal = np.ones(n, dtype=bool)
    for spec, vals, cur in zip(specs, value_arrays, cursor):
        ck = _element_key(spec, cur)
        gt = np.zeros(n, dtype=bool)
        eq = np.zeros(n, dtype=bool)
        for i in range(n):
            k = _element_key(spec, vals[i])
            if k > ck:
                gt[i] = True
            elif k == ck:
                eq[i] = True
        after |= equal & gt
        equal &= eq
    return after


def plain_value(v: Any) -> Any:
    """JSON-safe sort value for the response's "sort" array."""
    if _is_missing(v):
        return None
    if isinstance(v, (np.floating, np.integer)):
        v = v.item()
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return v
