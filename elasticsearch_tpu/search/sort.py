"""Field sort + search_after — doc-values-backed result ordering.

Reference: `search/sort/FieldSortBuilder`, `ScoreSortBuilder`,
`SearchAfterBuilder` (SURVEY.md §2.1#50). Semantics kept:

  - sort spec grammar: "field" | {"field": "asc"} |
    {"field": {"order": ..., "missing": "_last"|"_first"|value}} |
    "_score" (desc default) | "_doc"
  - missing values default to _last regardless of direction
  - search_after is a stateless cursor of the previous page's last sort
    values; a doc qualifies iff its sort tuple is strictly after the
    cursor in sort order
  - hits carry their "sort" values; max_score is null when sorting by
    anything but _score (the reference's behavior without track_scores)

Keys are built per segment from the pack's doc-value columns (numeric
i64/f64, keyword ordinals mapped through ord_terms); the cross-segment /
cross-shard merge compares python value tuples with direction-aware
comparators.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.index.segment import MISSING_I64
# The spec grammar and comparable-key builders live in `sort_keys` (a
# stdlib-only module importable without the device stack — serving
# fronts and merge-pool workers build the same keys the coordinator
# does). Re-exported here so every historical import site keeps working.
from elasticsearch_tpu.search.sort_keys import (  # noqa: F401
    SortSpec, _element_key, _invert_str, _is_missing, parse_sort,
    sort_key)


# ---------------------------------------------------------------------------
# per-segment key extraction
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SortColumn:
    """One spec's per-segment sort keys in NUMERIC form end-to-end:
    floats (NaN = missing) or keyword ordinals (-1 = missing, terms
    sorted so ordinal order IS term order). Strings are resolved only
    for the final response window via resolve() — never for every doc
    (VERDICT r2 weak #6: no O(n)-Python phase)."""

    kind: str                       # "num" | "ord"
    values: np.ndarray              # f64[n] | i64[n] ordinals
    terms: Optional[List[str]] = None

    def resolve(self, ord_: int) -> Any:
        v = self.values[ord_]
        if self.kind == "ord":
            o = int(v)
            return self.terms[o] if o >= 0 else None
        f = float(v)
        return None if np.isnan(f) else f


def segment_sort_values(reader, view_idx: int,
                        specs: Sequence[SortSpec],
                        scores: np.ndarray) -> List[SortColumn]:
    """One SortColumn per spec, aligned to segment doc ordinals."""
    view = reader.views[view_idx]
    seg = view.segment
    n = seg.num_docs
    out: List[SortColumn] = []
    for spec in specs:
        if spec.field == "_score":
            out.append(SortColumn("num",
                                  np.asarray(scores[:n], dtype=np.float64)))
            continue
        if spec.field == "_doc":
            # GLOBAL doc ordinal (cumulative across the reader's
            # segments) so _doc is unique per shard — a per-segment
            # ordinal would collide across segments and break strictly-
            # after cursors on tied prefixes
            base = sum(v.segment.num_docs
                       for v in reader.views[:view_idx])
            out.append(SortColumn(
                "num", np.arange(base, base + n, dtype=np.float64)))
            continue
        col = seg.doc_values.get(spec.field)
        if col is None:
            out.append(SortColumn("num", np.full(n, np.nan)))
            continue
        if col.kind == "ord":
            out.append(SortColumn("ord",
                                  col.values[:n].astype(np.int64),
                                  col.ord_terms or []))
        elif col.kind == "f64":
            out.append(SortColumn(
                "num", col.values[:n].astype(np.float64, copy=True)))
        else:
            vals = col.values[:n].astype(np.float64, copy=True)
            vals[col.values[:n] == MISSING_I64] = np.nan
            out.append(SortColumn("num", vals))
    return out


def column_ranks(spec: SortSpec, col: SortColumn
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """(rank i8[n], adj f64[n]): lexicographic (missing placement,
    direction-adjusted value) as pure numeric arrays."""
    if col.kind == "ord":
        missing = col.values < 0
        adj = col.values.astype(np.float64)
        if spec.missing not in ("_last", "_first"):
            raise IllegalArgumentException(
                "[sort] literal [missing] values are not supported on "
                "keyword fields")
    else:
        missing = np.isnan(col.values)
        adj = np.where(missing, 0.0, col.values)
        if spec.missing not in ("_last", "_first"):
            adj = np.where(missing, float(spec.missing), adj)
            missing = np.zeros_like(missing)
    if spec.order == "desc":
        adj = -adj
    missing_rank = 0 if spec.missing == "_first" else 2
    rank = np.where(missing, np.int8(missing_rank), np.int8(1))
    return rank, adj


def _cursor_compare(spec: SortSpec, col: SortColumn, cur: Any,
                    rank: np.ndarray, adj: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """(gt bool[n], eq bool[n]) of each doc's sort element vs the
    cursor value, honoring order + missing placement. A keyword cursor
    absent from this segment's term dict still resolves exactly via its
    insertion point."""
    if _is_missing(cur):
        ck_rank = 0 if spec.missing == "_first" else 2
        if spec.missing not in ("_first", "_last"):
            cur = spec.missing  # literal replacement, fall through
        else:
            return rank > ck_rank, rank == ck_rank
    if col.kind == "ord":
        terms = col.terms or []
        lo = int(np.searchsorted(terms, str(cur), side="left"))
        hi = int(np.searchsorted(terms, str(cur), side="right"))
        present = hi > lo
        if spec.order == "asc":     # adj = ordinal
            gt_val = adj >= hi
            eq_val = adj == lo if present else np.zeros_like(rank,
                                                             dtype=bool)
        else:                       # adj = -ordinal; after ⇔ term < cur
            gt_val = adj > -lo
            eq_val = adj == -lo if present else np.zeros_like(rank,
                                                              dtype=bool)
    else:
        try:
            v = float(cur)
        except (TypeError, ValueError):
            # a string cursor against a numeric column: legitimate when
            # this segment simply has no values for the (keyword
            # elsewhere) field — every doc is missing-rank and only rank
            # decides. Comparing it against ACTUAL numeric values is a
            # type mismatch the reference 400s on.
            if bool(np.any(rank == 1)):
                raise IllegalArgumentException(
                    f"[search_after] value [{cur}] does not match the "
                    f"sort field [{spec.field}] type") from None
            return rank > 1, np.zeros_like(rank, dtype=bool)
        if spec.order == "desc":
            v = -v
        gt_val = adj > v
        eq_val = adj == v
    gt = (rank > 1) | ((rank == 1) & gt_val)
    eq = (rank == 1) & eq_val
    return gt, eq


def after_mask(specs: Sequence[SortSpec], columns: List[SortColumn],
               cursor: Sequence[Any],
               ranks: Optional[List[Tuple[np.ndarray, np.ndarray]]] = None
               ) -> np.ndarray:
    """bool[n]: docs whose sort tuple is STRICTLY after the cursor —
    fully vectorized over numeric rank/adjusted-value arrays. `ranks`
    accepts precomputed column_ranks output so callers that also lexsort
    don't pay the O(n) pass twice."""
    if len(cursor) != len(specs):
        raise IllegalArgumentException(
            f"[search_after] expects {len(specs)} values, "
            f"got {len(cursor)}")
    n = len(columns[0].values) if columns else 0
    after = np.zeros(n, dtype=bool)
    equal = np.ones(n, dtype=bool)
    for i, (spec, col, cur) in enumerate(zip(specs, columns, cursor)):
        rank, adj = ranks[i] if ranks is not None \
            else column_ranks(spec, col)
        gt, eq = _cursor_compare(spec, col, cur, rank, adj)
        after |= equal & gt
        equal &= eq
    return after


def plain_value(v: Any) -> Any:
    """JSON-safe sort value for the response's "sort" array."""
    if _is_missing(v):
        return None
    if isinstance(v, (np.floating, np.integer)):
        v = v.item()
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return v
