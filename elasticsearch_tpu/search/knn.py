"""kNN search over dense_vector fields — brute-force matmul top-k.

Reference: the `knn` search section + KnnScoreDocQueryBuilder
(SURVEY.md §7.2.9, BASELINE.json config #5). The reference wraps
Lucene HNSW (approximate, graph-walk per query); the TPU design is a
dense [D_pad, dims] @ [dims, B] matmul per segment — the single most
MXU-friendly workload in the blueprint — giving EXACT top-k (recall
1.0 by construction), batched across queries.

Phase shape mirrors the reference's two-phase knn:
  1. candidate phase (`shard_candidates`): every shard scores its
     vectors against the query, returns its top `num_candidates`;
  2. the coordinator keeps the GLOBAL top k per clause and rewrites
     them into per-shard KnnScoreDocQuery nodes (dsl.KnnScoreDocQuery)
     that the normal query phase unions with the text query —
     hybrid BM25 + kNN scoring is (query_score + knn_score·boost) on
     docs in both sets, exactly the reference's combination rule.

Similarity → score maps (reference: DenseVectorFieldMapper):
  cosine      → (1 + cos(q, d)) / 2
  dot_product → (1 + q·d) / 2        (vectors should be unit-norm)
  l2_norm     → 1 / (1 + ||q - d||²)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.search import dsl


@dataclasses.dataclass
class KnnSpec:
    field: str
    query_vector: np.ndarray     # f32[dims]
    k: int
    num_candidates: int
    filter_query: Optional[dsl.QueryNode] = None
    boost: float = 1.0
    similarity: Optional[float] = None  # min raw-similarity cutoff


def parse_knn(spec: Any) -> List[KnnSpec]:
    """The `knn` search-body section: one object or a list of them
    (reference: RestSearchAction knn parsing)."""
    specs = spec if isinstance(spec, list) else [spec]
    out: List[KnnSpec] = []
    for s in specs:
        if not isinstance(s, dict):
            raise IllegalArgumentException("[knn] must be an object")
        unknown = set(s) - {"field", "query_vector", "k",
                            "num_candidates", "filter", "boost",
                            "similarity"}
        if unknown:
            raise IllegalArgumentException(
                f"[knn] unknown parameter {sorted(unknown)}")
        field = s.get("field")
        qv = s.get("query_vector")
        if not field or qv is None:
            raise IllegalArgumentException(
                "[knn] requires [field] and [query_vector]")
        if not isinstance(qv, list) or not qv or not all(
                isinstance(x, (int, float)) and not isinstance(x, bool)
                for x in qv):
            raise IllegalArgumentException(
                "[knn] [query_vector] must be a non-empty array of "
                "numbers")
        k = int(s.get("k", 10))
        num_candidates = int(s.get("num_candidates", max(k * 10, 100)))
        if k < 1:
            raise IllegalArgumentException("[knn] [k] must be >= 1")
        if num_candidates < k:
            raise IllegalArgumentException(
                f"[knn] [num_candidates] ({num_candidates}) cannot be "
                f"less than [k] ({k})")
        filt = None
        if s.get("filter") is not None:
            f = s["filter"]
            if isinstance(f, list):
                filt = dsl.BoolQuery(filter=[dsl.parse_query(x)
                                             for x in f])
            else:
                filt = dsl.parse_query(f)
        out.append(KnnSpec(
            field=str(field),
            query_vector=np.asarray(qv, dtype=np.float32),
            k=k, num_candidates=num_candidates, filter_query=filt,
            boost=float(s.get("boost", 1.0)),
            similarity=(None if s.get("similarity") is None
                        else float(s["similarity"]))))
    return out


def _similarity_scores(vectors: jnp.ndarray, q: jnp.ndarray,
                       kind: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (raw similarity, score) per doc row. NaN rows (missing docs)
    yield NaN; callers mask them."""
    if kind == "l2_norm":
        d2 = jnp.sum((vectors - q[None, :]) ** 2, axis=1)
        return -jnp.sqrt(d2), 1.0 / (1.0 + d2)
    dot = vectors @ q
    if kind == "dot_product":
        return dot, (1.0 + dot) / 2.0
    # cosine
    norms = jnp.sqrt(jnp.sum(vectors * vectors, axis=1))
    qn = jnp.sqrt(jnp.sum(q * q))
    cos = dot / jnp.maximum(norms * qn, 1e-12)
    return cos, (1.0 + cos) / 2.0


def shard_candidates(reader, spec: KnnSpec
                     ) -> List[Tuple[float, str, int, str]]:
    """Candidate phase on one shard: → [(score, segment_name, ord,
    doc_id)] top num_candidates (score desc, already
    similarity-filtered and live/filter-masked)."""
    ft = reader.mapper.field_type(spec.field)
    from elasticsearch_tpu.mapping.types import DenseVectorFieldType
    if ft is None or not isinstance(ft, DenseVectorFieldType):
        raise IllegalArgumentException(
            f"[knn] field [{spec.field}] is not a [dense_vector] field")
    if len(spec.query_vector) != ft.dims:
        raise IllegalArgumentException(
            f"[knn] query_vector has length [{len(spec.query_vector)}] "
            f"but field [{spec.field}] has [dims={ft.dims}]")
    out: List[Tuple[float, str, int, str]] = []
    q = jnp.asarray(spec.query_vector)
    for idx, view in enumerate(reader.views):
        mat = view.pack.dv_vec.get(spec.field)
        if mat is None:
            continue
        vectors = jnp.asarray(mat)
        raw, score = _similarity_scores(vectors, q, ft.similarity)
        ok = ~jnp.isnan(raw) & jnp.asarray(view.live_mask)
        if spec.filter_query is not None:
            from elasticsearch_tpu.search.planner import \
                SegmentQueryExecutor
            fmask, _ = SegmentQueryExecutor(reader, idx)._eval(
                spec.filter_query, scoring=False)
            ok = ok & fmask
        if spec.similarity is not None:
            # reference semantics: for cosine/dot_product `similarity`
            # is the MIN raw similarity; for l2_norm it is the MAX
            # distance (raw here is -distance, so flip the sign)
            if ft.similarity == "l2_norm":
                ok = ok & (raw >= -spec.similarity)
            else:
                ok = ok & (raw >= spec.similarity)
        score = jnp.where(ok, score, -jnp.inf)
        n = min(spec.num_candidates, int(score.shape[0]))
        vals, ords = jax.lax.top_k(score, n)
        vals = np.asarray(vals)
        ords = np.asarray(ords)
        seg = view.segment
        for v, d in zip(vals, ords):
            if v == -np.inf:
                break
            out.append((float(v), seg.name, int(d),
                        seg.doc_ids[int(d)]))
    out.sort(key=lambda t: (-t[0], t[1], t[2]))
    return out[: spec.num_candidates]


def global_topk(per_shard: Dict[Tuple[str, int], List[Tuple[float, str, int, str]]],
                k: int) -> Dict[Tuple[str, int], Dict[str, Tuple[np.ndarray, np.ndarray]]]:
    """Reduce candidate lists from every shard to the GLOBAL top k,
    then re-group by shard → {segment_name: (ords, scores)} for the
    KnnScoreDocQuery rewrite (reference: the coordinator's
    knn-results-per-shard in DfsQueryPhase)."""
    merged: List[Tuple[float, Tuple[str, int], str, int]] = []
    for shard_key, cands in per_shard.items():
        for score, seg_name, ord_, _doc_id in cands:
            merged.append((score, shard_key, seg_name, ord_))
    merged.sort(key=lambda t: (-t[0], t[1], t[2], t[3]))
    winners = merged[:k]
    grouped: Dict[Tuple[str, int], Dict[str, Tuple[List[int], List[float]]]] = {}
    for score, shard_key, seg_name, ord_ in winners:
        seg_map = grouped.setdefault(shard_key, {})
        ords, scores = seg_map.setdefault(seg_name, ([], []))
        ords.append(ord_)
        scores.append(score)
    return {
        shard: {seg: (np.asarray(o, dtype=np.int64),
                      np.asarray(s, dtype=np.float32))
                for seg, (o, s) in seg_map.items()}
        for shard, seg_map in grouped.items()}


def wrap_query(base: Optional[dsl.QueryNode],
               knn_doc_sets: List[Tuple[Dict[str, Tuple[np.ndarray, np.ndarray]], float]]
               ) -> dsl.QueryNode:
    """base query + resolved knn winners → the per-shard union node the
    query phase executes. knn_doc_sets: one (segment→(ords, scores),
    boost) entry per knn clause."""
    return dsl.KnnScoreDocQuery(
        query=base,
        doc_sets=[ds for ds, _ in knn_doc_sets],
        boosts=[b for _, b in knn_doc_sets])
