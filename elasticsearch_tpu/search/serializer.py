"""Vectorized response assembly for the TPU serving path.

The reference builds a SearchHit object per hit and serializes it
field-by-field; at k=1000 that is ~1000 dict constructions + ~1000
per-hit dumps per response and it shows up as the `assemble` stage in
PERF.md (12.8 s over one bench run). Here the hot response shape —
metadata-only hits (`"_source": false`), the shape high-QPS serving
traffic uses — is serialized COLUMNAR: external ids resolve via one
fancy-index over the pack's id table, ids and scores are JSON-encoded as
whole arrays in single C-level `json.dumps` calls, and the hits block is
assembled from the encoded fragments without ever constructing a per-hit
dict (BM25S, arXiv 2407.03618: lexical serving throughput is won by
moving per-item Python into batch array work).

`ColumnarHits` is a lazy Sequence: in-process consumers (tests, ccs,
rank_eval) that index or iterate it see ordinary hit dicts — built once,
on first touch, via the same assembly loop the planner path uses — while
the REST layer serializes it straight from the columns via
`dumps_response` without materializing anything.
"""

from __future__ import annotations

import json
from collections.abc import Sequence
from typing import Any, Dict, List, Optional

__all__ = ["ColumnarHits", "assemble_hits_list", "dumps_response"]


def assemble_hits_list(name: str, resident, scores, rows, ords, source,
                       version: bool, seq_no_primary_term: bool
                       ) -> List[Dict[str, Any]]:
    """Columnar window → response hit dicts (the materialized form).
    ids via one fancy-index; stored fields (when requested) read
    directly from the pinned segments the pack was scored against (same
    snapshot contract as the fetch phase)."""
    if resident is None or len(scores) == 0:
        return []
    ids = resident.resolve_ids(rows, ords).tolist()
    scores_l = scores.tolist()
    if source is False and not version and not seq_no_primary_term:
        return [{"_index": name, "_id": i, "_score": s}
                for i, s in zip(ids, scores_l)]
    from elasticsearch_tpu.search.query_phase import filter_source
    segs = resident.row_segments
    rows_l = rows.tolist()
    ords_l = ords.tolist()
    out = []
    for i, s, row, o in zip(ids, scores_l, rows_l, ords_l):
        doc: Dict[str, Any] = {"_index": name, "_id": i, "_score": s}
        seg = segs[row]
        if source is not False:
            src = seg.stored_source[o]
            if isinstance(source, (list, tuple)):
                src = filter_source(src or {}, list(source))
            doc["_source"] = src
        if version:
            doc["_version"] = int(seg.doc_versions[o])
        if seq_no_primary_term:
            doc["_seq_no"] = int(seg.seq_nos[o])
            doc["_primary_term"] = int(seg.primary_terms[o])
        out.append(doc)
    return out


class ColumnarHits(Sequence):
    """Lazy hits block over kernel result columns.

    Reads like a list of hit dicts (len / index / slice / iterate);
    materializes that list at most once and caches it, so consumers that
    MUTATE hits (ccs rewrites `_index`) keep their edits visible to a
    later serialization. `to_json()` renders the block; for the
    metadata-only shape it never touches per-hit Python at all."""

    __slots__ = ("name", "resident", "scores", "rows", "ords", "source",
                 "version", "seq_no_primary_term", "_hits")

    def __init__(self, name: str, resident, scores, rows, ords,
                 source=False, version: bool = False,
                 seq_no_primary_term: bool = False):
        self.name = name
        self.resident = resident
        self.scores = scores
        self.rows = rows
        self.ords = ords
        self.source = source
        self.version = version
        self.seq_no_primary_term = seq_no_primary_term
        self._hits: Optional[List[Dict[str, Any]]] = None

    # ---- list protocol --------------------------------------------------

    def _materialize(self) -> List[Dict[str, Any]]:
        if self._hits is None:
            self._hits = assemble_hits_list(
                self.name, self.resident, self.scores, self.rows,
                self.ords, self.source, self.version,
                self.seq_no_primary_term)
        return self._hits

    def __len__(self) -> int:
        return len(self.scores)

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())

    def __eq__(self, other):
        if isinstance(other, ColumnarHits):
            other = other._materialize()
        if isinstance(other, list):
            return self._materialize() == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"ColumnarHits(n={len(self.scores)}, index={self.name!r})"

    # ---- serialization --------------------------------------------------

    def to_json(self) -> str:
        if self._hits is not None:
            # already materialized (possibly mutated) — honor the dicts
            return json.dumps(self._hits, separators=(",", ":"))
        fast = self._fast_json()
        if fast is not None:
            return fast
        return json.dumps(self._materialize(), separators=(",", ":"))

    def _fast_json(self) -> Optional[str]:
        """Single-pass serialization of the metadata-only shape, or None
        when this block needs the materialized path (_source / _version
        / seq_no, or non-string ids)."""
        if not (self.source is False and not self.version
                and not self.seq_no_primary_term):
            return None
        if self.resident is None or len(self.scores) == 0:
            return "[]"
        ids = self.resident.resolve_ids(self.rows, self.ords).tolist()
        if not all(type(i) is str for i in ids):
            return None
        # one C-level dumps per column, then split into per-hit
        # fragments. Splitting the id array on '","' is exact: inside an
        # encoded JSON string a quote can only appear escaped (\"), so
        # the quote-comma-quote byte sequence occurs ONLY between
        # adjacent array elements.
        ids_json = json.dumps(ids, separators=(",", ":"))
        core = ids_json[1:-1]
        parts = core.split('","')
        if len(parts) == 1:
            id_frags = [core]
        else:
            id_frags = [parts[0] + '"']
            id_frags.extend('"' + p + '"' for p in parts[1:-1])
            id_frags.append('"' + parts[-1])
        # floats contain no commas, so the score array splits trivially
        score_frags = json.dumps(
            self.scores.tolist(), separators=(",", ":"))[1:-1].split(",")
        prefix = '{"_index":' + json.dumps(self.name) + ',"_id":'
        mid = ',"_score":'
        return "[" + ",".join(
            prefix + i + mid + s + "}"
            for i, s in zip(id_frags, score_frags)) + "]"


def dumps_response(payload: Any) -> str:
    """json.dumps that renders embedded ColumnarHits blocks via their
    columnar serializer. Works at any nesting depth (plain search,
    msearch `responses`, ...): the encoder emits a unique placeholder
    token per block, then the tokens are spliced with the real JSON."""
    blocks: Dict[str, ColumnarHits] = {}

    def default(obj):
        if isinstance(obj, ColumnarHits):
            token = f"\x00columnar:{id(obj)}\x00"
            blocks[token] = obj
            return token
        raise TypeError(
            f"Object of type {type(obj).__name__} is not JSON serializable")

    text = json.dumps(payload, default=default)
    for token, block in blocks.items():
        text = text.replace(json.dumps(token), block.to_json())
    return text
