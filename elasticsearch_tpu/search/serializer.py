"""Vectorized response assembly for the TPU serving path.

The reference builds a SearchHit object per hit and serializes it
field-by-field; at k=1000 that is ~1000 dict constructions + ~1000
per-hit dumps per response and it shows up as the `assemble` stage in
PERF.md (12.8 s over one bench run). Here the hot response shape —
metadata-only hits (`"_source": false`), the shape high-QPS serving
traffic uses — is serialized COLUMNAR: external ids resolve via one
fancy-index over the pack's id table, ids and scores are JSON-encoded as
whole arrays in single C-level `json.dumps` calls, and the hits block is
assembled from the encoded fragments without ever constructing a per-hit
dict (BM25S, arXiv 2407.03618: lexical serving throughput is won by
moving per-item Python into batch array work).

The fragment assembly itself is the **response splicer**
(`native/response_splice.c`): the columns ship as whole encoded arrays
and the C side splits them into elements and concatenates the per-hit
objects. `_py_splice` is the automatic byte-identical fallback when the
`.so` is absent (same element scanner, same concatenation), so a missing
toolchain degrades speed, never bytes. The `SpliceColumns` wire form is
also how the batcher process hands result columns to the serving-front
processes (`serving/front.py`): `encode_wire_response` splits the
envelope around each hits block so the front splices the final bytes on
its own core.

`ColumnarHits` is a lazy Sequence: in-process consumers (tests, ccs,
rank_eval) that index or iterate it see ordinary hit dicts — built once,
on first touch, via the same assembly loop the planner path uses — while
the REST layer serializes it straight from the columns via
`dumps_response` without materializing anything. `SplicedHits` wraps
already-materialized hit dicts (the multi-index merge path) so their
rendering goes through the splicer too.
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
import os
from collections.abc import Sequence
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ColumnarHits", "SplicedHits", "SpliceColumns",
           "assemble_hits_list", "dumps_response", "hits_columns_from_dicts",
           "splice_hits_bytes", "encode_wire_response", "splice_wire"]

_COMPACT = (",", ":")


def assemble_hits_list(name: str, resident, scores, rows, ords, source,
                       version: bool, seq_no_primary_term: bool
                       ) -> List[Dict[str, Any]]:
    """Columnar window → response hit dicts (the materialized form).
    ids via one fancy-index; stored fields (when requested) read
    directly from the pinned segments the pack was scored against (same
    snapshot contract as the fetch phase)."""
    if resident is None or len(scores) == 0:
        return []
    ids = resident.resolve_ids(rows, ords).tolist()
    scores_l = scores.tolist()
    if source is False and not version and not seq_no_primary_term:
        return [{"_index": name, "_id": i, "_score": s}
                for i, s in zip(ids, scores_l)]
    from elasticsearch_tpu.search.query_phase import filter_source
    segs = resident.row_segments
    rows_l = rows.tolist()
    ords_l = ords.tolist()
    out = []
    for i, s, row, o in zip(ids, scores_l, rows_l, ords_l):
        doc: Dict[str, Any] = {"_index": name, "_id": i, "_score": s}
        seg = segs[row]
        if source is not False:
            src = seg.stored_source[o]
            if isinstance(source, (list, tuple)):
                src = filter_source(src or {}, list(source))
            doc["_source"] = src
        if version:
            doc["_version"] = int(seg.doc_versions[o])
        if seq_no_primary_term:
            doc["_seq_no"] = int(seg.seq_nos[o])
            doc["_primary_term"] = int(seg.primary_terms[o])
        out.append(doc)
    return out


# ---------------------------------------------------------------------------
# the response splicer: pre-encoded columns → final hits-array bytes
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SpliceColumns:
    """Wire form of a hits block: whole-array json.dumps encodings.

    Every byte of the final output comes from one of these strings, so
    splicing (C or Python) is byte-identical to per-hit json.dumps with
    compact separators. Picklable — this is also the shape the batcher
    process ships to the serving fronts."""

    n: int
    ids_json: str                      # '["a","b"]'
    scores_json: str                   # '[1.5,null]'
    names_json: str                    # '["idx"]' (deduped _index names)
    name_idx: List[int]                # per-hit index into names_json
    extras_json: Optional[str] = None  # '[{...},{}]' residual fields


_SPLICE_FN = None
_SPLICE_TRIED = False


def _native_splice():
    global _SPLICE_FN, _SPLICE_TRIED
    if not _SPLICE_TRIED:
        _SPLICE_TRIED = True
        if not os.environ.get("ES_TPU_NO_NATIVE_SPLICE"):
            from elasticsearch_tpu import native
            _SPLICE_FN = native.bind(
                "response_splice", "es_splice_hits", ctypes.c_long,
                [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                 ctypes.POINTER(ctypes.c_int32), ctypes.c_char_p,
                 ctypes.c_int32, ctypes.c_char_p, ctypes.c_long])
    return _SPLICE_FN


def splice_hits_bytes(cols: SpliceColumns) -> str:
    """Columns → the hits-array JSON text, via the C splicer when the
    native library is available, else the byte-identical Python path."""
    if cols.n == 0:
        return "[]"
    fn = _native_splice()
    if fn is not None:
        ids_b = cols.ids_json.encode("ascii", "replace")
        scores_b = cols.scores_json.encode("ascii", "replace")
        names_b = cols.names_json.encode("ascii", "replace")
        extras_b = (cols.extras_json.encode("ascii", "replace")
                    if cols.extras_json is not None else None)
        idx = (ctypes.c_int32 * cols.n)(*cols.name_idx)
        cap = (len(ids_b) + len(scores_b) + (len(extras_b or b""))
               + cols.n * (len(names_b) + 32) + 16)
        for _ in range(2):
            buf = ctypes.create_string_buffer(cap)
            rc = fn(ids_b, scores_b, names_b, idx, extras_b, cols.n,
                    buf, cap)
            if rc >= 0:
                return buf.raw[:rc].decode("ascii")
            if rc != -1:
                break  # malformed input — let Python decide
            cap *= 4
    return _py_splice(cols)


def _scan_elements(s: str) -> Optional[List[str]]:
    """Split a compact JSON array into its top-level element strings —
    the Python twin of the C scanner (string-escape + depth aware)."""
    if not s or s[0] != "[":
        return None
    if s.startswith("[]"):
        return []
    out: List[str] = []
    depth = 0
    in_str = esc = False
    start = 1
    for i in range(1, len(s)):
        c = s[i]
        if in_str:
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
            continue
        if c == '"':
            in_str = True
        elif c in "{[":
            depth += 1
        elif c == "}":
            depth -= 1
        elif c == "]":
            if depth == 0:
                out.append(s[start:i])
                return out
            depth -= 1
        elif c == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    return None


def _py_splice(cols: SpliceColumns) -> str:
    """Pure-Python splice — same element spans, same concatenation, so
    bytes match the native path exactly."""
    ids = _scan_elements(cols.ids_json)
    scores = _scan_elements(cols.scores_json)
    names = _scan_elements(cols.names_json)
    extras = (_scan_elements(cols.extras_json)
              if cols.extras_json is not None else None)
    if (ids is None or scores is None or not names
            or len(ids) != cols.n or len(scores) != cols.n
            or (extras is not None and len(extras) != cols.n)):
        raise ValueError("malformed splice columns")
    frags = []
    for i in range(cols.n):
        hit = ('{"_index":' + names[cols.name_idx[i]]
               + ',"_id":' + ids[i] + ',"_score":' + scores[i])
        if extras is not None and len(extras[i]) > 2:
            hit += "," + extras[i][1:-1]
        frags.append(hit + "}")
    return "[" + ",".join(frags) + "]"


_META_KEYS = ["_index", "_id", "_score"]


def hits_columns_from_dicts(hits: List[Dict[str, Any]]
                            ) -> Optional[SpliceColumns]:
    """Materialized hit dicts → splice columns, or None when the hits
    don't lead with the canonical (_index, _id, _score) key order (the
    caller then falls back to plain json.dumps)."""
    if not hits:
        return SpliceColumns(0, "[]", "[]", "[]", [])
    names: List[str] = []
    name_pos: Dict[str, int] = {}
    name_idx: List[int] = []
    ids: List[Any] = []
    scores: List[Any] = []
    extras: List[Dict[str, Any]] = []
    any_extra = False
    for h in hits:
        if not isinstance(h, dict):
            return None
        keys = list(h)
        if keys[:3] != _META_KEYS:
            return None
        name = h["_index"]
        if not isinstance(name, str):
            return None
        pos = name_pos.get(name)
        if pos is None:
            pos = name_pos[name] = len(names)
            names.append(name)
        name_idx.append(pos)
        ids.append(h["_id"])
        scores.append(h["_score"])
        extra = {k: h[k] for k in keys[3:]}
        if extra:
            any_extra = True
        extras.append(extra)
    try:
        return SpliceColumns(
            len(hits),
            json.dumps(ids, separators=_COMPACT),
            json.dumps(scores, separators=_COMPACT),
            json.dumps(names, separators=_COMPACT),
            name_idx,
            json.dumps(extras, separators=_COMPACT) if any_extra else None)
    except (TypeError, ValueError):
        return None  # unserializable value — plain dumps raises the same


class ColumnarHits(Sequence):
    """Lazy hits block over kernel result columns.

    Reads like a list of hit dicts (len / index / slice / iterate);
    materializes that list at most once and caches it, so consumers that
    MUTATE hits (ccs rewrites `_index`) keep their edits visible to a
    later serialization. `to_json()` renders the block via the response
    splicer; for the metadata-only shape it never touches per-hit Python
    at all."""

    __slots__ = ("name", "resident", "scores", "rows", "ords", "source",
                 "version", "seq_no_primary_term", "_hits")

    def __init__(self, name: str, resident, scores, rows, ords,
                 source=False, version: bool = False,
                 seq_no_primary_term: bool = False):
        self.name = name
        self.resident = resident
        self.scores = scores
        self.rows = rows
        self.ords = ords
        self.source = source
        self.version = version
        self.seq_no_primary_term = seq_no_primary_term
        self._hits: Optional[List[Dict[str, Any]]] = None

    # ---- list protocol --------------------------------------------------

    def _materialize(self) -> List[Dict[str, Any]]:
        if self._hits is None:
            self._hits = assemble_hits_list(
                self.name, self.resident, self.scores, self.rows,
                self.ords, self.source, self.version,
                self.seq_no_primary_term)
        return self._hits

    def __len__(self) -> int:
        return len(self.scores)

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())

    def __eq__(self, other):
        if isinstance(other, (ColumnarHits, SplicedHits)):
            other = list(other)
        if isinstance(other, list):
            return self._materialize() == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"ColumnarHits(n={len(self.scores)}, index={self.name!r})"

    # ---- serialization --------------------------------------------------

    def splice_columns(self) -> Optional[SpliceColumns]:
        """This block as splice columns (None ⇒ caller must dumps)."""
        if self._hits is not None:
            # already materialized (possibly mutated) — honor the dicts
            return hits_columns_from_dicts(self._hits)
        cols = self._fast_columns()
        if cols is not None:
            return cols
        return hits_columns_from_dicts(self._materialize())

    def _fast_columns(self) -> Optional[SpliceColumns]:
        """Columns straight from the kernel result arrays — the
        metadata-only shape, no per-hit dict ever exists. None when this
        block needs the materialized path (_source / _version / seq_no,
        or non-string ids)."""
        if not (self.source is False and not self.version
                and not self.seq_no_primary_term):
            return None
        if self.resident is None or len(self.scores) == 0:
            return SpliceColumns(0, "[]", "[]", "[]", [])
        ids = self.resident.resolve_ids(self.rows, self.ords).tolist()
        if not all(type(i) is str for i in ids):
            return None
        n = len(ids)
        return SpliceColumns(
            n, json.dumps(ids, separators=_COMPACT),
            json.dumps(self.scores.tolist(), separators=_COMPACT),
            "[" + json.dumps(self.name) + "]", [0] * n)

    def _fast_json(self) -> Optional[str]:
        """Single-pass serialization of the metadata-only shape, or None
        when this block needs the materialized path."""
        cols = self._fast_columns()
        if cols is None:
            return None
        return splice_hits_bytes(cols)

    def to_json(self) -> str:
        cols = self.splice_columns()
        if cols is not None:
            return splice_hits_bytes(cols)
        return json.dumps(self._materialize(), separators=_COMPACT)


class SplicedHits(Sequence):
    """Materialized hit dicts whose JSON rendering goes through the
    response splicer (the multi-index merge path: hits already exist as
    dicts, but per-hit serialization is still worth batching)."""

    __slots__ = ("_hits",)

    def __init__(self, hits: List[Dict[str, Any]]):
        self._hits = hits

    def __len__(self) -> int:
        return len(self._hits)

    def __getitem__(self, i):
        return self._hits[i]

    def __iter__(self):
        return iter(self._hits)

    def __eq__(self, other):
        if isinstance(other, (ColumnarHits, SplicedHits)):
            other = list(other)
        if isinstance(other, list):
            return self._hits == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"SplicedHits(n={len(self._hits)})"

    def append(self, hit: Dict[str, Any]) -> None:
        self._hits.append(hit)

    def splice_columns(self) -> Optional[SpliceColumns]:
        return hits_columns_from_dicts(self._hits)

    def to_json(self) -> str:
        cols = self.splice_columns()
        if cols is not None:
            return splice_hits_bytes(cols)
        return json.dumps(self._hits, separators=_COMPACT)


_HITS_BLOCKS = (ColumnarHits, SplicedHits)


def _tokenize(payload: Any) -> Tuple[str, Dict[str, Any]]:
    """json.dumps with every hits block replaced by a unique placeholder
    token; blocks come back keyed by token in document order."""
    blocks: Dict[str, Any] = {}

    def default(obj):
        if isinstance(obj, _HITS_BLOCKS):
            token = f"\x00columnar:{id(obj)}\x00"
            blocks[token] = obj
            return token
        raise TypeError(
            f"Object of type {type(obj).__name__} is not JSON serializable")

    return json.dumps(payload, default=default), blocks


def dumps_response(payload: Any) -> str:
    """json.dumps that renders embedded hits blocks via the response
    splicer. Works at any nesting depth (plain search, msearch
    `responses`, ...): the encoder emits a unique placeholder token per
    block, then the tokens are spliced with the real JSON."""
    text, blocks = _tokenize(payload)
    for token, block in blocks.items():
        text = text.replace(json.dumps(token), block.to_json())
    return text


def encode_wire_response(payload: Any
                         ) -> Tuple[List[str], List[SpliceColumns]]:
    """Batcher→front wire form: envelope parts + splice columns, where
    the final bytes are parts[0] + splice(columns[0]) + parts[1] + ...
    (len(parts) == len(columns) + 1). Blocks that can't column-encode
    are rendered batcher-side into the envelope, so the front's splice
    loop needs no special cases."""
    text, blocks = _tokenize(payload)
    if not blocks:
        return [text], []
    parts: List[str] = []
    columns: List[SpliceColumns] = []
    pending = ""
    tail = text
    for token, block in blocks.items():
        pre, _, tail = tail.partition(json.dumps(token))
        cols = block.splice_columns()
        if cols is None:
            pending += pre + block.to_json()
        else:
            parts.append(pending + pre)
            columns.append(cols)
            pending = ""
    parts.append(pending + tail)
    return parts, columns


def splice_wire(parts: List[str], columns: List[SpliceColumns]) -> str:
    """Front-side inverse of encode_wire_response — where the C splicer
    actually runs on the serving front's own core."""
    out = [parts[0]]
    for cols, part in zip(columns, parts[1:]):
        out.append(splice_hits_bytes(cols))
        out.append(part)
    return "".join(out)
