"""Metric aggregations: min/max/sum/avg/value_count/stats/cardinality/
percentiles/top_hits (reference: search/aggregations/metrics/**,
SURVEY.md §2.1#38).

Cardinality uses a real HyperLogLog++-style sketch (murmur3-hashed values,
2^p registers, reduce = register max — the reference's
HyperLogLogPlusPlus), with the linear-counting correction for small
cardinalities. Percentiles uses a merging t-digest (the reference's
TDigestState): per-shard partials and the cross-shard reduce are both
O(compression) centroids, never O(values)."""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.search.aggregations.base import (
    Aggregator,
    AggregatorFactories,
    InternalAggregation,
    SegmentAggContext,
    register_agg,
)


# ---------------------------------------------------------------------------
# simple numeric metrics
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalNumericMetric(InternalAggregation):
    kind: str                    # min|max|sum|avg|value_count
    total: float = 0.0
    count: int = 0
    minv: float = math.inf
    maxv: float = -math.inf

    def reduce(self, others):
        out = dataclasses.replace(self)
        for o in others:
            out.total += o.total
            out.count += o.count
            out.minv = min(out.minv, o.minv)
            out.maxv = max(out.maxv, o.maxv)
        return out

    def to_response(self) -> Dict[str, Any]:
        if self.kind == "value_count":
            return {"value": self.count}
        if self.kind == "sum":
            return {"value": self.total}
        if self.kind == "avg":
            return {"value": self.total / self.count if self.count else None}
        if self.kind == "min":
            return {"value": self.minv if self.count else None}
        if self.kind == "max":
            return {"value": self.maxv if self.count else None}
        if self.kind == "stats":
            return {
                "count": self.count,
                "min": self.minv if self.count else None,
                "max": self.maxv if self.count else None,
                "avg": self.total / self.count if self.count else None,
                "sum": self.total,
            }
        raise AssertionError(self.kind)


class NumericMetricAggregator(Aggregator):
    def __init__(self, name, kind, field, missing=None, sub=None):
        super().__init__(name, sub or AggregatorFactories({}))
        self.kind = kind
        self.field = field
        self.missing = missing

    def collect(self, ctx: SegmentAggContext, mask) -> InternalNumericMetric:
        if self.missing is None:
            res = self._collect_device(ctx, mask)
            if res is not None:
                return res
        vals, docs, ord_terms = ctx.field_values(self.field, mask)
        out = InternalNumericMetric(self.kind)
        if ord_terms is not None and self.kind != "value_count":
            raise IllegalArgumentException(
                f"agg [{self.name}]: field [{self.field}] is not numeric")
        if self.missing is not None:
            n_mask = int(np.asarray(mask)[:ctx.view.segment.num_docs].sum())
            missing_docs = n_mask - len(np.unique(docs)) if len(docs) else n_mask
            if missing_docs > 0:
                vals = np.concatenate(
                    [np.asarray(vals, dtype=np.float64),
                     np.full(missing_docs, float(self.missing))])
        if len(vals):
            v = np.asarray(vals, dtype=np.float64)
            out.total = float(v.sum())
            out.count = int(len(v))
            out.minv = float(v.min())
            out.maxv = float(v.max())
        return out

    def _collect_device(self, ctx: SegmentAggContext, mask
                        ) -> "Optional[InternalNumericMetric]":
        """count/sum/min/max as masked device reductions over the numeric
        column (SURVEY.md §7.2.8); None → host path."""
        seg = ctx.view.segment
        col = seg.doc_values.get(self.field)
        if col is None or col.kind == "ord" or col.extra:
            return None
        from elasticsearch_tpu.search.aggregations import device
        stats = device.numeric_stats(ctx.view.pack, self.field,
                                     np.asarray(mask))
        if stats is None:
            return None
        cnt, total, mn, mx = stats
        out = InternalNumericMetric(self.kind)
        if cnt:
            out.count = cnt
            out.total = total
            out.minv = mn
            out.maxv = mx
        return out

    def empty(self) -> InternalNumericMetric:
        return InternalNumericMetric(self.kind)


for _kind in ("min", "max", "sum", "avg", "value_count", "stats"):
    def _mk(kind):
        @register_agg(kind)
        def _parse(name, body, sub, kind=kind):
            field = body.get("field")
            if field is None:
                raise IllegalArgumentException(f"[{kind}] requires a field")
            return NumericMetricAggregator(name, kind, field,
                                           body.get("missing"), sub)
        return _parse
    _mk(_kind)


# ---------------------------------------------------------------------------
# cardinality (HLL++-style)
# ---------------------------------------------------------------------------

HLL_P = 12  # 4096 registers ≈ 1.6% relative error (ES default ~precision 3000)


@dataclasses.dataclass
class InternalCardinality(InternalAggregation):
    registers: np.ndarray  # uint8[2^p]

    def reduce(self, others):
        regs = self.registers.copy()
        for o in others:
            regs = np.maximum(regs, o.registers)
        return InternalCardinality(regs)

    def to_response(self) -> Dict[str, Any]:
        return {"value": self.estimate()}

    def estimate(self) -> int:
        m = len(self.registers)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        est = alpha * m * m / np.sum(np.exp2(-self.registers.astype(np.float64)))
        zeros = int((self.registers == 0).sum())
        if est <= 2.5 * m and zeros > 0:
            est = m * math.log(m / zeros)  # linear counting for small n
        return int(round(est))


class CardinalityAggregator(Aggregator):
    def __init__(self, name, field, sub=None):
        super().__init__(name, sub or AggregatorFactories({}))
        self.field = field

    def collect(self, ctx: SegmentAggContext, mask) -> InternalCardinality:
        from elasticsearch_tpu.indices.service import murmur3_hash
        keys = self._device_distinct_keys(ctx, mask)
        if keys is None:
            vals, _, ord_terms = ctx.field_values(self.field, mask)
            keys = []
            if len(vals):
                if ord_terms is not None:
                    uniq = np.unique(np.asarray(vals, dtype=np.int64))
                    keys = [ord_terms[int(v)] for v in uniq]
                else:
                    keys = [repr(v) for v in np.unique(vals)]
        regs = np.zeros(1 << HLL_P, dtype=np.uint8)
        for k in keys:
            h = murmur3_hash(k) & 0xFFFFFFFF
            idx = h >> (32 - HLL_P)
            w = (h << HLL_P) & 0xFFFFFFFF
            rank = (32 - HLL_P) + 1 if w == 0 else (32 - w.bit_length()) + 1
            if rank > regs[idx]:
                regs[idx] = rank
        return InternalCardinality(regs)

    def _device_distinct_keys(self, ctx, mask):
        """Keyword cardinality, device half (SURVEY.md §7.2.8): a
        scatter-max presence bitmap over the ord column gives this
        segment's DISTINCT ordinals — the host hashes only those into
        the HLL (the cross-shard merge representation), not every doc.
        None → host path (non-keyword, or multi-valued extras)."""
        seg = ctx.view.segment
        col = seg.doc_values.get(self.field)
        if col is None or col.kind != "ord" or col.extra:
            return None
        from elasticsearch_tpu.search.aggregations import device
        present = device.ord_presence(ctx.view.pack, self.field,
                                      np.asarray(mask))
        if present is None:
            return None
        terms = ctx.view.pack.dv_ord_terms[self.field]
        return [terms[i] for i in np.nonzero(present)[0]]

    def empty(self) -> InternalCardinality:
        return InternalCardinality(np.zeros(1 << HLL_P, dtype=np.uint8))


@register_agg("cardinality")
def _parse_cardinality(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[cardinality] requires a field")
    return CardinalityAggregator(name, field, sub)


# ---------------------------------------------------------------------------
# percentiles (merging t-digest — reduce memory is O(compression), not
# O(values); reference: TDigestState / AbstractTDigestPercentilesAggregator)
# ---------------------------------------------------------------------------

DEFAULT_PERCENTS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)


class TDigest:
    """Merging t-digest (Dunning's MergingDigest essentials): centroids
    kept sorted by mean; compression bounds their number via the k1
    scale-function size limit, giving tighter bins at the tails."""

    __slots__ = ("compression", "means", "weights", "_min", "_max")

    def __init__(self, compression: float = 100.0,
                 means: Optional[np.ndarray] = None,
                 weights: Optional[np.ndarray] = None,
                 vmin: float = math.inf, vmax: float = -math.inf):
        self.compression = compression
        self.means = means if means is not None else np.empty(0)
        self.weights = weights if weights is not None else np.empty(0)
        self._min = vmin
        self._max = vmax

    @property
    def total_weight(self) -> float:
        return float(self.weights.sum()) if len(self.weights) else 0.0

    def add_values(self, values: np.ndarray) -> "TDigest":
        if len(values) == 0:
            return self
        return self._merged(np.concatenate([self.means, values]),
                            np.concatenate([self.weights,
                                            np.ones(len(values))]),
                            min(self._min, float(values.min())),
                            max(self._max, float(values.max())))

    def merge(self, other: "TDigest") -> "TDigest":
        if len(other.means) == 0:
            return self
        if len(self.means) == 0:
            return other
        return self._merged(
            np.concatenate([self.means, other.means]),
            np.concatenate([self.weights, other.weights]),
            min(self._min, other._min), max(self._max, other._max))

    def _merged(self, means: np.ndarray, weights: np.ndarray,
                vmin: float, vmax: float) -> "TDigest":
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        total = weights.sum()
        out_m: List[float] = []
        out_w: List[float] = []
        acc_m, acc_w, q0 = means[0], weights[0], 0.0
        for m, w in zip(means[1:], weights[1:]):
            q = q0 + (acc_w + w) / total
            # k1 scale function size bound: centroids may hold at most
            # 4·total·q(1−q)/compression weight — small near the tails
            k_size = max(1.0,
                         4.0 * total * q * (1.0 - q) / self.compression)
            if acc_w + w <= k_size:
                acc_m = (acc_m * acc_w + m * w) / (acc_w + w)
                acc_w += w
            else:
                out_m.append(acc_m)
                out_w.append(acc_w)
                q0 += acc_w / total
                acc_m, acc_w = m, w
        out_m.append(acc_m)
        out_w.append(acc_w)
        return TDigest(self.compression, np.asarray(out_m),
                       np.asarray(out_w), vmin, vmax)

    def quantile(self, q: float) -> Optional[float]:
        if len(self.means) == 0:
            return None
        if len(self.means) == 1:
            return float(self.means[0])
        total = self.weights.sum()
        target = q / 100.0 * total
        # centroid i covers cumulative weight centered at its midpoint
        cum = np.cumsum(self.weights) - self.weights / 2.0
        if target <= cum[0]:
            return self._min if q <= 0 else float(
                self._min + (self.means[0] - self._min)
                * max(0.0, target) / max(cum[0], 1e-12))
        if target >= cum[-1]:
            return self._max if q >= 100 else float(
                self.means[-1] + (self._max - self.means[-1])
                * (target - cum[-1]) / max(total - cum[-1], 1e-12))
        i = int(np.searchsorted(cum, target)) - 1
        span = cum[i + 1] - cum[i]
        frac = (target - cum[i]) / max(span, 1e-12)
        return float(self.means[i] + frac * (self.means[i + 1]
                                             - self.means[i]))


@dataclasses.dataclass
class InternalPercentiles(InternalAggregation):
    percents: Sequence[float]
    digest: TDigest

    def reduce(self, others):
        d = self.digest
        for o in others:
            d = d.merge(o.digest)
        return InternalPercentiles(self.percents, d)

    def to_response(self) -> Dict[str, Any]:
        return {"values": {f"{p:g}": self.digest.quantile(p)
                           for p in self.percents}}


class PercentilesAggregator(Aggregator):
    def __init__(self, name, field, percents, compression=100.0, sub=None):
        super().__init__(name, sub or AggregatorFactories({}))
        self.field = field
        self.percents = percents
        self.compression = compression

    def collect(self, ctx, mask) -> InternalPercentiles:
        vals, _, ord_terms = ctx.field_values(self.field, mask)
        if ord_terms is not None:
            raise IllegalArgumentException(
                f"agg [{self.name}]: field [{self.field}] is not numeric")
        digest = TDigest(self.compression).add_values(
            np.asarray(vals, dtype=np.float64))
        return InternalPercentiles(self.percents, digest)

    def empty(self) -> InternalPercentiles:
        return InternalPercentiles(self.percents,
                                   TDigest(self.compression))


@register_agg("percentiles")
def _parse_percentiles(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[percentiles] requires a field")
    percents = tuple(body.get("percents", DEFAULT_PERCENTS))
    compression = float((body.get("tdigest") or {}).get(
        "compression", 100.0))
    return PercentilesAggregator(name, field, percents, compression, sub)


# ---------------------------------------------------------------------------
# top_hits
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalTopHits(InternalAggregation):
    size: int
    hits: List[Dict[str, Any]]  # {"_id", "_score", "_source"}
    total: int

    def reduce(self, others):
        merged = list(self.hits)
        total = self.total
        for o in others:
            merged.extend(o.hits)
            total += o.total
        merged.sort(key=lambda h: (-(h["_score"] or 0.0), h["_id"]))
        return InternalTopHits(self.size, merged[: self.size], total)

    def to_response(self) -> Dict[str, Any]:
        return {"hits": {
            "total": {"value": self.total, "relation": "eq"},
            "hits": self.hits}}


class TopHitsAggregator(Aggregator):
    def __init__(self, name, size, source, sub=None):
        super().__init__(name, sub or AggregatorFactories({}))
        self.size = size
        self.source = source

    def collect(self, ctx, mask) -> InternalTopHits:
        seg = ctx.view.segment
        m = np.asarray(mask)[: seg.num_docs]
        docs = np.nonzero(m)[0][: self.size]  # doc-order hits (no scores here)
        hits = []
        for d in docs:
            h = {"_id": seg.doc_ids[int(d)], "_score": None}
            if self.source:
                h["_source"] = seg.stored_source[int(d)]
            hits.append(h)
        return InternalTopHits(self.size, hits, int(m.sum()))

    def empty(self) -> InternalTopHits:
        return InternalTopHits(self.size, [], 0)


@register_agg("top_hits")
def _parse_top_hits(name, body, sub):
    return TopHitsAggregator(name, int(body.get("size", 3)),
                             body.get("_source", True), sub)
