"""Aggregations framework: parse → per-segment collect → tree reduce.

Reference: `search/aggregations/**` (SURVEY.md §2.1#38), the largest
subsystem: `AggregatorFactories` parse the JSON tree, per-segment leaf
collectors fill buckets, per-shard `InternalAggregation`s stream to the
coordinator and merge via `InternalAggregation#reduce`. Kept contracts:
the request JSON shape, the response JSON shape, the two-level reduce
(segment→shard→coordinator), sub-aggregation nesting, and terms ordering
(doc_count desc, key asc tie-break).

TPU shape: a bucket IS a boolean mask over the segment's padded doc axis,
and metrics are masked reductions over doc-value columns — the same dense
mask algebra as the query planner, so filters/sub-aggs compose by mask
AND. Collection here runs on host numpy over the pack's columns (they are
the same arrays jax would see; swapping `np` for `jnp` per column is a
device-offload decision left to the profiler, not a semantic change).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.index.reader import SegmentView, ShardReader
from elasticsearch_tpu.index.segment import MISSING_I64


class SegmentAggContext:
    """Access to one segment's doc values + query machinery for one
    collect pass (reference: the LeafReaderContext + doc-value readers a
    leaf collector sees)."""

    def __init__(self, reader: ShardReader, view_idx: int):
        self.reader = reader
        self.view_idx = view_idx
        self.view: SegmentView = reader.views[view_idx]

    def field_values(self, field: str, mask: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
        """(values, doc_ords, ord_terms): all values of `field` for docs
        where mask is True, multi-values expanded. For keyword fields the
        values are ordinals and ord_terms maps them to strings."""
        seg = self.view.segment
        pack = self.view.pack
        n = seg.num_docs
        m = np.asarray(mask)[:n]
        col = seg.doc_values.get(field)
        if col is None:
            return np.empty(0), np.empty(0, dtype=np.int64), None
        if col.kind == "ord":
            base = col.values[:n]
            sel = m & (base >= 0)
            vals = base[sel].astype(np.int64)
            docs = np.nonzero(sel)[0]
        elif col.kind == "f64":
            base = col.values[:n]
            sel = m & ~np.isnan(base)
            vals = base[sel]
            docs = np.nonzero(sel)[0]
        else:
            base = col.values[:n]
            sel = m & (base != MISSING_I64)
            vals = base[sel]
            docs = np.nonzero(sel)[0]
        if col.extra:
            ev, ed = [], []
            for d, extra_vals in col.extra.items():
                if d < n and m[d]:
                    for v in extra_vals:
                        ev.append(v)
                        ed.append(d)
            if ev:
                if col.kind == "ord":
                    # extras for ord columns are stored as ordinals
                    vals = np.concatenate([vals, np.asarray(ev, dtype=np.int64)])
                else:
                    vals = np.concatenate([vals, np.asarray(ev, dtype=vals.dtype)])
                docs = np.concatenate([docs, np.asarray(ed, dtype=np.int64)])
        return vals, docs, col.ord_terms

    def query_mask(self, query) -> np.ndarray:
        """Evaluate a DSL query to a doc mask (filters/filter agg)."""
        from elasticsearch_tpu.search.planner import SegmentQueryExecutor
        executor = SegmentQueryExecutor(self.reader, self.view_idx)
        mask, _ = executor._eval(query, scoring=False)
        return np.asarray(mask)

    @property
    def live_mask(self) -> np.ndarray:
        return np.asarray(self.view.live_mask)


class InternalAggregation:
    """Shard-level partial result; reduce() merges across shards
    (reference: InternalAggregation#reduce)."""

    def reduce(self, others: Sequence["InternalAggregation"]) -> "InternalAggregation":
        raise NotImplementedError

    def to_response(self) -> Dict[str, Any]:
        raise NotImplementedError


class Aggregator:
    """One aggregation node: collect(segment ctx, mask) → partial."""

    def __init__(self, name: str, sub: "AggregatorFactories"):
        self.name = name
        self.sub = sub

    def collect(self, ctx: SegmentAggContext,
                mask: np.ndarray) -> InternalAggregation:
        raise NotImplementedError

    def empty(self) -> InternalAggregation:
        """Partial for a shard with no matching segment data."""
        raise NotImplementedError


class AggregatorFactories:
    """A parsed {name: aggregator} level of the tree. Pipelines at this
    level run at response-build time on the reduced results (reference:
    PipelineAggregator#reduce over InternalAggregations)."""

    def __init__(self, aggregators: Dict[str, Aggregator],
                 pipelines: Optional[Dict[str, Any]] = None):
        self.aggregators = aggregators
        self.pipelines = pipelines or {}

    def __bool__(self) -> bool:
        return bool(self.aggregators) or bool(self.pipelines)

    def collect(self, ctx: SegmentAggContext,
                mask: np.ndarray) -> Dict[str, InternalAggregation]:
        return {name: agg.collect(ctx, mask)
                for name, agg in self.aggregators.items()}

    def empty(self) -> Dict[str, InternalAggregation]:
        return {name: agg.empty() for name, agg in self.aggregators.items()}

    @staticmethod
    def reduce(parts: Sequence[Dict[str, InternalAggregation]]
               ) -> Dict[str, InternalAggregation]:
        """Merge segment- or shard-level partial maps."""
        if not parts:
            return {}
        out: Dict[str, InternalAggregation] = {}
        for name in parts[0]:
            first, rest = parts[0][name], [p[name] for p in parts[1:]]
            out[name] = first.reduce(rest)
        return out

    @staticmethod
    def to_response(aggs: Dict[str, InternalAggregation]) -> Dict[str, Any]:
        return {name: a.to_response() for name, a in aggs.items()}


_PARSERS: Dict[str, Any] = {}
_PIPELINE_PARSERS: Dict[str, Any] = {}


def register_agg(type_name: str):
    def deco(fn):
        _PARSERS[type_name] = fn
        return fn
    return deco


def register_pipeline(type_name: str):
    def deco(fn):
        _PIPELINE_PARSERS[type_name] = fn
        return fn
    return deco


def parse_aggregations(spec: Dict[str, Any]) -> AggregatorFactories:
    """Parse the request's "aggs" tree (reference: AggregatorFactories#
    parseAggregators): {name: {<type>: {...}, "aggs": {...}}}."""
    aggregators: Dict[str, Aggregator] = {}
    pipelines: Dict[str, Any] = {}
    for name, body in (spec or {}).items():
        if not isinstance(body, dict):
            raise IllegalArgumentException(f"invalid agg [{name}]")
        sub_spec = body.get("aggs") or body.get("aggregations") or {}
        type_keys = [k for k in body if k not in ("aggs", "aggregations", "meta")]
        if len(type_keys) != 1:
            raise IllegalArgumentException(
                f"expected exactly one aggregation type for [{name}], "
                f"got {type_keys}")
        t = type_keys[0]
        if t in _PIPELINE_PARSERS:
            if sub_spec:
                raise IllegalArgumentException(
                    f"pipeline aggregation [{name}] cannot hold sub-"
                    f"aggregations")
            pipelines[name] = _PIPELINE_PARSERS[t](name, body[t])
            continue
        parser = _PARSERS.get(t)
        if parser is None:
            raise IllegalArgumentException(f"unknown aggregation type [{t}]")
        sub = parse_aggregations(sub_spec)
        aggregators[name] = parser(name, body[t], sub)
    return AggregatorFactories(aggregators, pipelines)
