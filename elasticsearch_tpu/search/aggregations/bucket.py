"""Bucket aggregations: terms, histogram, date_histogram, range, filter(s),
missing, global (reference: search/aggregations/bucket/**, SURVEY.md
§2.1#38). A bucket is a doc mask; sub-aggregations collect under
mask & bucket_mask — the dense-mask composition that makes nesting free
on the TPU data model."""

from __future__ import annotations

import dataclasses
import datetime
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.units import TimeValue
from elasticsearch_tpu.search.aggregations.base import (
    Aggregator,
    AggregatorFactories,
    InternalAggregation,
    SegmentAggContext,
    register_agg,
)


@dataclasses.dataclass
class Bucket:
    key: Any
    doc_count: int
    sub: Dict[str, InternalAggregation]
    key_as_string: Optional[str] = None

    def to_response(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"key": self.key, "doc_count": self.doc_count}
        if self.key_as_string is not None:
            out["key_as_string"] = self.key_as_string
        for name, agg in self.sub.items():
            out[name] = agg.to_response()
        return out


def _merge_buckets(parts: Sequence[Dict[Any, Bucket]]) -> Dict[Any, Bucket]:
    merged: Dict[Any, Bucket] = {}
    for part in parts:
        for key, b in part.items():
            cur = merged.get(key)
            if cur is None:
                merged[key] = Bucket(b.key, b.doc_count, dict(b.sub),
                                     b.key_as_string)
            else:
                cur.doc_count += b.doc_count
                cur.sub = AggregatorFactories.reduce([cur.sub, b.sub]) \
                    if cur.sub or b.sub else {}
    return merged


# ---------------------------------------------------------------------------
# terms
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalTerms(InternalAggregation):
    size: int
    min_doc_count: int
    buckets: Dict[Any, Bucket]
    order_by: str = "_count"     # "_count" | "_key"
    order_asc: bool = False

    def reduce(self, others):
        merged = _merge_buckets([self.buckets] + [o.buckets for o in others])
        return InternalTerms(self.size, self.min_doc_count, merged,
                             self.order_by, self.order_asc)

    def _sorted(self) -> List[Bucket]:
        bs = [b for b in self.buckets.values()
              if b.doc_count >= self.min_doc_count]
        if self.order_by == "_key":
            bs.sort(key=lambda b: b.key, reverse=not self.order_asc)
        else:
            # count order; tie-break key asc (the reference's compound order)
            key_fn = (lambda b: (b.doc_count, _neg_key(b.key))) if not \
                self.order_asc else (lambda b: (-b.doc_count, _neg_key(b.key)))
            bs.sort(key=key_fn, reverse=True)
        return bs[: self.size]

    def to_response(self) -> Dict[str, Any]:
        ordered = self._sorted()
        other = sum(b.doc_count for b in self.buckets.values()
                    if b.doc_count >= self.min_doc_count) - \
            sum(b.doc_count for b in ordered)
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": int(other),
                "buckets": [b.to_response() for b in ordered]}


def _neg_key(key):
    """Invert ordering for tie-break key asc inside a reverse sort."""
    if isinstance(key, (int, float)):
        return -key
    return _StrDesc(key)


class _StrDesc(str):
    def __lt__(self, other):
        return str.__gt__(self, other)


class TermsAggregator(Aggregator):
    DEFAULT_SIZE = 10

    def __init__(self, name, field, size, shard_size, min_doc_count,
                 order_by, order_asc, sub):
        super().__init__(name, sub)
        self.field = field
        self.size = size
        self.shard_size = shard_size
        self.min_doc_count = min_doc_count
        self.order_by = order_by
        self.order_asc = order_asc

    def collect(self, ctx: SegmentAggContext, mask) -> InternalTerms:
        metric_subs = self._device_metric_subs() if self.sub else {}
        if metric_subs is not None:
            res = self._collect_device(ctx, mask, metric_subs or {})
            if res is not None:
                return res
        vals, docs, ord_terms = ctx.field_values(self.field, mask)
        buckets: Dict[Any, Bucket] = {}
        if len(vals):
            if ord_terms is not None:
                ords = np.asarray(vals, dtype=np.int64)
                counts = np.bincount(ords, minlength=len(ord_terms))
                hot = np.nonzero(counts)[0]
                # keep the top shard_size per segment (reference: shard_size
                # over-fetch bounds coordinator error)
                if len(hot) > self.shard_size:
                    top = hot[np.argsort(-counts[hot], kind="stable")]
                    hot = top[: self.shard_size]
                for o in hot:
                    key = ord_terms[int(o)]
                    sub = self._collect_sub(ctx, mask, docs, ords == o)
                    buckets[key] = Bucket(key, int(counts[o]), sub)
            else:
                uniq, inv = np.unique(vals, return_inverse=True)
                counts = np.bincount(inv)
                order = np.argsort(-counts, kind="stable")[: self.shard_size]
                for i in order:
                    key = uniq[i]
                    key = int(key) if float(key).is_integer() and not \
                        isinstance(key, np.floating) else float(key)
                    sub = self._collect_sub(ctx, mask, docs, inv == i)
                    buckets[key] = Bucket(key, int(counts[i]), sub)
        return InternalTerms(self.size, self.min_doc_count, buckets,
                             self.order_by, self.order_asc)

    def _device_metric_subs(self):
        """→ {name: NumericMetricAggregator} when EVERY sub-agg is a
        plain numeric metric (the one-level sub-agg shape the device
        serves via per-ordinal scatter-reductions, VERDICT r4 item 8);
        None otherwise."""
        from elasticsearch_tpu.search.aggregations.metrics import \
            NumericMetricAggregator
        if not self.sub or self.sub.pipelines:
            return None
        out = {}
        for name, agg in self.sub.aggregators.items():
            if not isinstance(agg, NumericMetricAggregator) or \
                    agg.missing is not None or agg.sub.aggregators or \
                    agg.sub.pipelines:
                return None
            out[name] = agg
        return out or None

    def _collect_device(self, ctx: SegmentAggContext, mask,
                        metric_subs) -> Optional[InternalTerms]:
        """Keyword terms counts as one device scatter-add over the ord
        column; numeric-metric sub-aggs as per-ordinal scatter
        reductions (SURVEY.md §7.2.8); None → host path (multi-valued
        extras or no servable column)."""
        from elasticsearch_tpu.search.aggregations import device
        from elasticsearch_tpu.search.aggregations.metrics import \
            InternalNumericMetric
        seg = ctx.view.segment
        col = seg.doc_values.get(self.field)
        if col is None or col.kind != "ord" or col.extra:
            return None
        counts = device.terms_counts(ctx.view.pack, self.field,
                                     np.asarray(mask))
        if counts is None:
            return None
        sub_stats = {}
        by_field = {}  # sub-aggs sharing a value field share one kernel
        for name, agg in metric_subs.items():
            vcol = seg.doc_values.get(agg.field)
            if vcol is None or vcol.kind == "ord" or vcol.extra:
                return None  # host path handles it
            stats = by_field.get(agg.field)
            if stats is None:
                stats = device.terms_numeric_stats(
                    ctx.view.pack, self.field, agg.field,
                    np.asarray(mask))
                if stats is None:
                    return None
                by_field[agg.field] = stats
            sub_stats[name] = (agg.kind, stats)
        ord_terms = ctx.view.pack.dv_ord_terms[self.field]
        hot = np.nonzero(counts)[0]
        if len(hot) > self.shard_size:
            top = hot[np.argsort(-counts[hot], kind="stable")]
            hot = top[: self.shard_size]
        buckets = {}
        for o in hot:
            key = ord_terms[int(o)]
            sub = {}
            for name, (kind, (cnt, s, mn, mx)) in sub_stats.items():
                m = InternalNumericMetric(kind)
                c = int(cnt[int(o)])
                if c:
                    m.count = c
                    m.total = float(s[int(o)])
                    m.minv = float(mn[int(o)])
                    m.maxv = float(mx[int(o)])
                sub[name] = m
            buckets[key] = Bucket(key, int(counts[o]), sub)
        return InternalTerms(self.size, self.min_doc_count, buckets,
                             self.order_by, self.order_asc)

    def _collect_sub(self, ctx, mask, docs, val_sel) -> Dict[str, InternalAggregation]:
        if not self.sub:
            return {}
        bucket_mask = np.zeros_like(np.asarray(mask))
        bucket_mask[docs[val_sel]] = True
        return self.sub.collect(ctx, np.asarray(mask) & bucket_mask)

    def empty(self) -> InternalTerms:
        return InternalTerms(self.size, self.min_doc_count, {},
                             self.order_by, self.order_asc)


@register_agg("terms")
def _parse_terms(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[terms] requires a field")
    size = int(body.get("size", TermsAggregator.DEFAULT_SIZE))
    # reference default: size * 1.5 + 10
    shard_size = int(body.get("shard_size", size * 3 // 2 + 10))
    order_by, order_asc = "_count", False
    order = body.get("order")
    if isinstance(order, dict) and order:
        order_by, direction = next(iter(order.items()))
        order_asc = str(direction).lower() == "asc"
        if order_by not in ("_count", "_key"):
            raise IllegalArgumentException(
                f"[terms] order by [{order_by}] not supported")
    return TermsAggregator(name, field, size, max(size, shard_size),
                           int(body.get("min_doc_count", 1)),
                           order_by, order_asc, sub)


# ---------------------------------------------------------------------------
# histogram / date_histogram
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalHistogram(InternalAggregation):
    buckets: Dict[Any, Bucket]
    min_doc_count: int = 0
    interval: Optional[float] = None   # for empty-bucket fill
    date_format: bool = False

    def reduce(self, others):
        merged = _merge_buckets([self.buckets] + [o.buckets for o in others])
        return InternalHistogram(merged, self.min_doc_count, self.interval,
                                 self.date_format)

    def to_response(self) -> Dict[str, Any]:
        keys = sorted(self.buckets.keys())
        out = []
        if (self.min_doc_count == 0 and self.interval and len(keys) > 1):
            # fill gaps (reference: histogram empty buckets when
            # min_doc_count=0)
            filled = []
            k = keys[0]
            while k <= keys[-1] + 1e-9:
                filled.append(k)
                k += self.interval
            keys = [int(k) if self.date_format else k for k in filled]
        for k in keys:
            b = self.buckets.get(k)
            if b is None:
                b = Bucket(k, 0, {},
                           _millis_iso(k) if self.date_format else None)
            if b.doc_count >= self.min_doc_count:
                out.append(b.to_response())
        return {"buckets": out}


def _millis_iso(ms: float) -> str:
    dt = datetime.datetime.fromtimestamp(ms / 1000.0, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


class HistogramAggregator(Aggregator):
    def __init__(self, name, field, interval, offset, min_doc_count, sub,
                 date: bool = False, calendar: Optional[str] = None):
        super().__init__(name, sub)
        self.field = field
        self.interval = interval
        self.offset = offset
        self.min_doc_count = min_doc_count
        self.date = date
        self.calendar = calendar

    def collect(self, ctx, mask) -> InternalHistogram:
        if not self.sub:
            res = (self._collect_device_calendar(ctx, mask)
                   if self.calendar else
                   self._collect_device(ctx, mask))
            if res is not None:
                return res
        vals, docs, ord_terms = ctx.field_values(self.field, mask)
        if ord_terms is not None:
            raise IllegalArgumentException(
                f"agg [{self.name}]: field [{self.field}] is not numeric")
        buckets: Dict[Any, Bucket] = {}
        if len(vals):
            v = np.asarray(vals, dtype=np.float64)
            if self.calendar:
                keys = np.asarray([_calendar_floor(int(x), self.calendar)
                                   for x in v], dtype=np.int64)
            else:
                keys = np.floor((v - self.offset) / self.interval) \
                    * self.interval + self.offset
                if self.date:
                    keys = keys.astype(np.int64)
            uniq, inv = np.unique(keys, return_inverse=True)
            counts = np.bincount(inv)
            for i, k in enumerate(uniq):
                key = int(k) if self.date else float(k)
                sub = {}
                if self.sub:
                    bucket_mask = np.zeros_like(np.asarray(mask))
                    bucket_mask[docs[inv == i]] = True
                    sub = self.sub.collect(ctx, np.asarray(mask) & bucket_mask)
                buckets[key] = Bucket(key, int(counts[i]), sub,
                                      _millis_iso(key) if self.date else None)
        interval = None if self.calendar else self.interval
        return InternalHistogram(buckets, self.min_doc_count, interval,
                                 self.date)

    MAX_DEVICE_BUCKETS = 65536

    def _collect_device(self, ctx, mask) -> Optional[InternalHistogram]:
        """Fixed-interval histogram as one device scatter-add; the static
        bucket span comes from the segment's min/max column stats
        (SURVEY.md §7.2.8). None → host path."""
        seg = ctx.view.segment
        col = seg.doc_values.get(self.field)
        if col is None or col.kind == "ord" or col.extra:
            return None
        from elasticsearch_tpu.search.aggregations import device
        from elasticsearch_tpu.search.can_match import _segment_minmax
        mm = _segment_minmax(seg, self.field)
        if mm is None:
            return InternalHistogram({}, self.min_doc_count,
                                     self.interval, self.date)
        import math as _math
        lo_idx = int(_math.floor((mm[0] - self.offset) / self.interval))
        hi_idx = int(_math.floor((mm[1] - self.offset) / self.interval))
        n_buckets = hi_idx - lo_idx + 1
        if n_buckets <= 0 or n_buckets > self.MAX_DEVICE_BUCKETS:
            return None
        counts = device.histogram_counts(
            ctx.view.pack, self.field, np.asarray(mask), self.offset,
            self.interval, lo_idx, n_buckets)
        if counts is None:
            return None
        buckets: Dict[Any, Bucket] = {}
        for i in np.nonzero(counts)[0]:
            k = (lo_idx + int(i)) * self.interval + self.offset
            key = int(k) if self.date else float(k)
            buckets[key] = Bucket(key, int(counts[i]), {},
                                  _millis_iso(key) if self.date else None)
        return InternalHistogram(buckets, self.min_doc_count,
                                 self.interval, self.date)

    MAX_CALENDAR_BUCKETS = 16384

    def _collect_device_calendar(self, ctx, mask
                                 ) -> Optional[InternalHistogram]:
        """Calendar intervals on device (VERDICT r4 item 8): the host
        precomputes the calendar bucket BOUNDARIES spanning the
        segment's min/max, the device does one searchsorted +
        scatter-add. None → host path."""
        seg = ctx.view.segment
        col = seg.doc_values.get(self.field)
        if col is None or col.kind == "ord" or col.extra:
            return None
        from elasticsearch_tpu.search.aggregations import device
        from elasticsearch_tpu.search.can_match import _segment_minmax
        mm = _segment_minmax(seg, self.field)
        if mm is None:
            return InternalHistogram({}, self.min_doc_count, None,
                                     self.date)
        start = _calendar_floor(int(mm[0]), self.calendar)
        bounds = [start]
        while bounds[-1] <= mm[1]:
            if len(bounds) > self.MAX_CALENDAR_BUCKETS:
                return None
            nxt = _calendar_floor(
                int(bounds[-1]) + _CAL_STEP_MS[self.calendar],
                self.calendar)
            if nxt <= bounds[-1]:  # DST/guard: force progress
                nxt = bounds[-1] + _CAL_STEP_MS[self.calendar]
            bounds.append(nxt)
        boundaries = np.asarray(bounds, dtype=np.float64)
        counts = device.bounded_bucket_counts(
            ctx.view.pack, self.field, np.asarray(mask), boundaries)
        if counts is None:
            return None
        buckets: Dict[Any, Bucket] = {}
        for i in np.nonzero(counts)[0]:
            key = int(bounds[int(i)])
            buckets[key] = Bucket(key, int(counts[i]), {},
                                  _millis_iso(key) if self.date
                                  else None)
        return InternalHistogram(buckets, self.min_doc_count, None,
                                 self.date)

    def empty(self) -> InternalHistogram:
        return InternalHistogram({}, self.min_doc_count,
                                 None if self.calendar else self.interval,
                                 self.date)


# a step guaranteed to land inside the NEXT calendar bucket when added
# to a bucket start (then re-floored); calendar buckets are never
# shorter than these
_CAL_STEP_MS = {
    "month": 32 * 86400_000, "1M": 32 * 86400_000,
    "year": 367 * 86400_000, "1y": 367 * 86400_000,
    "quarter": 93 * 86400_000, "1q": 93 * 86400_000,
    "week": 7 * 86400_000, "1w": 7 * 86400_000,
    "day": 86400_000, "1d": 86400_000,
    "hour": 3600_000, "1h": 3600_000,
    "minute": 60_000, "1m": 60_000,
}


def _calendar_floor(ms: int, unit: str) -> int:
    dt = datetime.datetime.fromtimestamp(ms / 1000.0, datetime.timezone.utc)
    if unit in ("month", "1M"):
        dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    elif unit in ("year", "1y"):
        dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0,
                        microsecond=0)
    elif unit in ("quarter", "1q"):
        month = ((dt.month - 1) // 3) * 3 + 1
        dt = dt.replace(month=month, day=1, hour=0, minute=0, second=0,
                        microsecond=0)
    elif unit in ("week", "1w"):
        dt = dt.replace(hour=0, minute=0, second=0, microsecond=0)
        dt -= datetime.timedelta(days=dt.weekday())
    elif unit in ("day", "1d"):
        dt = dt.replace(hour=0, minute=0, second=0, microsecond=0)
    elif unit in ("hour", "1h"):
        dt = dt.replace(minute=0, second=0, microsecond=0)
    elif unit in ("minute", "1m"):
        dt = dt.replace(second=0, microsecond=0)
    else:
        raise IllegalArgumentException(f"unknown calendar interval [{unit}]")
    return int(dt.timestamp() * 1000)


@register_agg("histogram")
def _parse_histogram(name, body, sub):
    field = body.get("field")
    interval = body.get("interval")
    if field is None or interval is None:
        raise IllegalArgumentException("[histogram] requires field + interval")
    return HistogramAggregator(name, field, float(interval),
                               float(body.get("offset", 0.0)),
                               int(body.get("min_doc_count", 0)), sub)


@register_agg("date_histogram")
def _parse_date_histogram(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[date_histogram] requires a field")
    calendar = body.get("calendar_interval")
    fixed = body.get("fixed_interval", body.get("interval"))
    if calendar:
        return HistogramAggregator(name, field, None, 0.0,
                                   int(body.get("min_doc_count", 0)), sub,
                                   date=True, calendar=calendar)
    if not fixed:
        raise IllegalArgumentException(
            "[date_histogram] requires calendar_interval or fixed_interval")
    ms = TimeValue.parse(str(fixed)).millis()
    return HistogramAggregator(name, field, float(ms), 0.0,
                               int(body.get("min_doc_count", 0)), sub,
                               date=True)


# ---------------------------------------------------------------------------
# range
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalRange(InternalAggregation):
    buckets: Dict[Any, Bucket]
    order: List[Any]
    bounds: Dict[Any, Tuple[float, float]]

    def reduce(self, others):
        merged = _merge_buckets([self.buckets] + [o.buckets for o in others])
        return InternalRange(merged, self.order, self.bounds)

    def to_response(self):
        out = []
        for k in self.order:
            if k not in self.buckets:
                continue
            resp = self.buckets[k].to_response()
            lo, hi = self.bounds[k]
            if np.isfinite(lo):
                resp["from"] = lo
            if np.isfinite(hi):
                resp["to"] = hi
            out.append(resp)
        return {"buckets": out}


class RangeAggregator(Aggregator):
    def __init__(self, name, field, ranges, keyed, sub):
        super().__init__(name, sub)
        self.field = field
        self.ranges = ranges

    def _keys_bounds(self):
        order, bounds = [], {}
        for r in self.ranges:
            lo = float(r.get("from", -np.inf))
            hi = float(r.get("to", np.inf))
            key = r.get("key") or _range_key(lo, hi)
            order.append(key)
            bounds[key] = (lo, hi)
        return order, bounds

    def collect(self, ctx, mask) -> InternalRange:
        vals, docs, ord_terms = ctx.field_values(self.field, mask)
        if ord_terms is not None:
            raise IllegalArgumentException(
                f"agg [{self.name}]: field [{self.field}] is not numeric")
        order, bounds = self._keys_bounds()
        buckets: Dict[Any, Bucket] = {}
        v = np.asarray(vals, dtype=np.float64)
        for key in order:
            lo, hi = bounds[key]
            sel = (v >= lo) & (v < hi) if len(v) else np.zeros(0, dtype=bool)
            sub = {}
            if self.sub:
                bucket_mask = np.zeros_like(np.asarray(mask))
                if len(v):
                    bucket_mask[docs[sel]] = True
                sub = self.sub.collect(ctx, np.asarray(mask) & bucket_mask)
            buckets[key] = Bucket(key, int(sel.sum()) if len(v) else 0, sub)
        return InternalRange(buckets, order, bounds)

    def empty(self) -> InternalRange:
        order, bounds = self._keys_bounds()
        return InternalRange({k: Bucket(k, 0, {}) for k in order}, order,
                             bounds)


def _range_key(lo, hi) -> str:
    lo_s = "*" if not np.isfinite(lo) else f"{lo:g}"
    hi_s = "*" if not np.isfinite(hi) else f"{hi:g}"
    return f"{lo_s}-{hi_s}"


@register_agg("range")
def _parse_range(name, body, sub):
    field = body.get("field")
    ranges = body.get("ranges")
    if field is None or not ranges:
        raise IllegalArgumentException("[range] requires field + ranges")
    return RangeAggregator(name, field, ranges, body.get("keyed", False), sub)


# ---------------------------------------------------------------------------
# filter / filters / missing / global
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalSingleBucket(InternalAggregation):
    doc_count: int
    sub: Dict[str, InternalAggregation]

    def reduce(self, others):
        count = self.doc_count + sum(o.doc_count for o in others)
        sub = AggregatorFactories.reduce(
            [self.sub] + [o.sub for o in others]) if self.sub else {}
        return InternalSingleBucket(count, sub)

    def to_response(self):
        out = {"doc_count": self.doc_count}
        for name, agg in self.sub.items():
            out[name] = agg.to_response()
        return out


class FilterAggregator(Aggregator):
    def __init__(self, name, query_spec, sub):
        super().__init__(name, sub)
        from elasticsearch_tpu.search import dsl
        self.query = dsl.parse_query(query_spec)

    def collect(self, ctx, mask) -> InternalSingleBucket:
        fmask = np.asarray(mask) & ctx.query_mask(self.query) & ctx.live_mask
        sub = self.sub.collect(ctx, fmask) if self.sub else {}
        n = ctx.view.segment.num_docs
        return InternalSingleBucket(int(fmask[:n].sum()), sub)

    def empty(self) -> InternalSingleBucket:
        return InternalSingleBucket(0, self.sub.empty() if self.sub else {})


@register_agg("filter")
def _parse_filter(name, body, sub):
    return FilterAggregator(name, body, sub)


@dataclasses.dataclass
class InternalFilters(InternalAggregation):
    buckets: Dict[str, InternalSingleBucket]
    order: List[str]

    def reduce(self, others):
        merged = {}
        for key in self.order:
            merged[key] = self.buckets[key].reduce(
                [o.buckets[key] for o in others])
        return InternalFilters(merged, self.order)

    def to_response(self):
        return {"buckets": {k: self.buckets[k].to_response()
                            for k in self.order}}


class FiltersAggregator(Aggregator):
    def __init__(self, name, named_filters, sub):
        super().__init__(name, sub)
        from elasticsearch_tpu.search import dsl
        self.filters = {k: dsl.parse_query(v) for k, v in named_filters.items()}

    def collect(self, ctx, mask) -> InternalFilters:
        buckets = {}
        n = ctx.view.segment.num_docs
        for key, q in self.filters.items():
            fmask = np.asarray(mask) & ctx.query_mask(q) & ctx.live_mask
            sub = self.sub.collect(ctx, fmask) if self.sub else {}
            buckets[key] = InternalSingleBucket(int(fmask[:n].sum()), sub)
        return InternalFilters(buckets, sorted(self.filters.keys()))

    def empty(self) -> InternalFilters:
        return InternalFilters(
            {k: InternalSingleBucket(0, self.sub.empty() if self.sub else {})
             for k in self.filters}, sorted(self.filters.keys()))


@register_agg("filters")
def _parse_filters(name, body, sub):
    named = body.get("filters")
    if not isinstance(named, dict) or not named:
        raise IllegalArgumentException("[filters] requires named filters")
    return FiltersAggregator(name, named, sub)


class MissingAggregator(Aggregator):
    def __init__(self, name, field, sub):
        super().__init__(name, sub)
        self.field = field

    def collect(self, ctx, mask) -> InternalSingleBucket:
        n = ctx.view.segment.num_docs
        has = ctx.reader.has_field_mask(ctx.view_idx, self.field)
        m = np.asarray(mask) & ~np.asarray(has)
        sub = self.sub.collect(ctx, m) if self.sub else {}
        return InternalSingleBucket(int(m[:n].sum()), sub)

    def empty(self) -> InternalSingleBucket:
        return InternalSingleBucket(0, self.sub.empty() if self.sub else {})


@register_agg("missing")
def _parse_missing(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[missing] requires a field")
    return MissingAggregator(name, field, sub)


class GlobalAggregator(Aggregator):
    """Ignores the query: collects over ALL live docs (reference:
    GlobalAggregator)."""

    def collect(self, ctx, mask) -> InternalSingleBucket:
        n = ctx.view.segment.num_docs
        m = ctx.live_mask.copy()
        sub = self.sub.collect(ctx, m) if self.sub else {}
        return InternalSingleBucket(int(m[:n].sum()), sub)

    def empty(self) -> InternalSingleBucket:
        return InternalSingleBucket(0, self.sub.empty() if self.sub else {})


@register_agg("global")
def _parse_global(name, body, sub):
    return GlobalAggregator(name, sub)


# ---------------------------------------------------------------------------
# composite (after-key paging over a multi-source key space; reference:
# search/aggregations/bucket/composite/CompositeAggregator)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _CompositeSource:
    name: str
    kind: str                 # "terms" | "histogram" | "date_histogram"
    field: str
    interval: Optional[float] = None
    calendar: Optional[str] = None


@dataclasses.dataclass
class InternalComposite(InternalAggregation):
    size: int
    source_names: List[str]
    buckets: Dict[tuple, Bucket]

    def reduce(self, others):
        merged = _merge_buckets([self.buckets]
                                + [o.buckets for o in others])
        return InternalComposite(self.size, self.source_names, merged)

    def to_response(self) -> Dict[str, Any]:
        ordered = sorted(self.buckets.values(),
                         key=lambda b: b.key)[: self.size]
        out_buckets = []
        for b in ordered:
            entry: Dict[str, Any] = {
                "key": dict(zip(self.source_names, b.key)),
                "doc_count": b.doc_count}
            for sname, agg in b.sub.items():
                entry[sname] = agg.to_response()
            out_buckets.append(entry)
        out: Dict[str, Any] = {"buckets": out_buckets}
        if out_buckets:
            out["after_key"] = out_buckets[-1]["key"]
        return out


class CompositeAggregator(Aggregator):
    def __init__(self, name, sources: List[_CompositeSource], size: int,
                 after: Optional[tuple], sub):
        super().__init__(name, sub)
        self.sources = sources
        self.size = size
        self.after = after

    def _source_values(self, ctx, mask, src: _CompositeSource):
        """doc ordinal → single value for this source (first value wins
        on multi-valued fields)."""
        vals, docs, ord_terms = ctx.field_values(src.field, mask)
        if src.kind == "terms":
            if ord_terms is not None:
                resolved = [ord_terms[int(v)] for v in vals]
            else:
                resolved = [float(v) if not float(v).is_integer()
                            else int(v) for v in vals]
        else:
            if ord_terms is not None:
                raise IllegalArgumentException(
                    f"composite source [{src.name}]: field [{src.field}] "
                    f"is not numeric")
            v = np.asarray(vals, dtype=np.float64)
            if src.calendar:
                resolved = [_calendar_floor(int(x), src.calendar)
                            for x in v]
            else:
                keys = np.floor(v / src.interval) * src.interval
                resolved = [int(k) if src.kind == "date_histogram"
                            else float(k) for k in keys]
        first: Dict[int, Any] = {}
        for d, val in zip(docs, resolved):
            first.setdefault(int(d), val)
        return first

    def collect(self, ctx, mask) -> InternalComposite:
        per_source = [self._source_values(ctx, mask, s)
                      for s in self.sources]
        if not per_source:
            return self.empty()
        common = set(per_source[0])
        for m in per_source[1:]:
            common &= set(m)
        by_key: Dict[tuple, List[int]] = {}
        for d in common:
            key = tuple(m[d] for m in per_source)
            if self.after is not None:
                try:
                    if key <= self.after:
                        continue  # paging: strictly after the cursor
                except TypeError:
                    raise IllegalArgumentException(
                        f"[composite] [after] values {list(self.after)} "
                        f"do not match the source key types") from None
            by_key.setdefault(key, []).append(d)
        # keep only the shard-level first `size` keys in key order — the
        # reduce re-sorts and trims identically, so this loses nothing
        buckets: Dict[tuple, Bucket] = {}
        for key in sorted(by_key)[: self.size]:
            doc_list = by_key[key]
            sub = {}
            if self.sub:
                bucket_mask = np.zeros_like(np.asarray(mask))
                bucket_mask[np.asarray(doc_list, dtype=np.int64)] = True
                sub = self.sub.collect(ctx,
                                       np.asarray(mask) & bucket_mask)
            buckets[key] = Bucket(key, len(doc_list), sub)
        return InternalComposite(self.size,
                                 [s.name for s in self.sources], buckets)

    def empty(self) -> InternalComposite:
        return InternalComposite(self.size,
                                 [s.name for s in self.sources], {})


@register_agg("composite")
def _parse_composite(name, body, sub):
    raw_sources = body.get("sources")
    if not isinstance(raw_sources, list) or not raw_sources:
        raise IllegalArgumentException("[composite] requires [sources]")
    sources: List[_CompositeSource] = []
    for entry in raw_sources:
        if not isinstance(entry, dict) or len(entry) != 1:
            raise IllegalArgumentException(
                "[composite] each source is {name: {type: {...}}}")
        sname, spec = next(iter(entry.items()))
        if not isinstance(spec, dict) or len(spec) != 1:
            raise IllegalArgumentException(
                f"[composite] source [{sname}] needs exactly one type")
        kind, opts = next(iter(spec.items()))
        if kind not in ("terms", "histogram", "date_histogram"):
            raise IllegalArgumentException(
                f"[composite] unsupported source type [{kind}]")
        field = (opts or {}).get("field")
        if field is None:
            raise IllegalArgumentException(
                f"[composite] source [{sname}] requires [field]")
        interval = None
        calendar = None
        if kind == "histogram":
            if opts.get("interval") is None:
                raise IllegalArgumentException(
                    f"[composite] histogram source [{sname}] requires "
                    f"[interval]")
            interval = float(opts["interval"])
        elif kind == "date_histogram":
            calendar = opts.get("calendar_interval")
            fixed = opts.get("fixed_interval")
            if calendar is None and fixed is None:
                raise IllegalArgumentException(
                    f"[composite] date_histogram source [{sname}] needs "
                    f"calendar_interval or fixed_interval")
            if fixed is not None:
                interval = float(TimeValue.parse(str(fixed)).millis())
                calendar = None
        sources.append(_CompositeSource(sname, kind, field, interval,
                                        calendar))
    after_raw = body.get("after")
    after = None
    if after_raw is not None:
        if not isinstance(after_raw, dict):
            raise IllegalArgumentException("[composite] [after] must be "
                                           "an object")
        missing = [s.name for s in sources if s.name not in after_raw]
        if missing:
            raise IllegalArgumentException(
                f"[composite] [after] missing keys {missing}")
        after = tuple(after_raw[s.name] for s in sources)
    return CompositeAggregator(name, sources,
                               int(body.get("size", 10)), after, sub)


# ---------------------------------------------------------------------------
# significant_terms (JLH heuristic; reference: search/aggregations/
# bucket/terms/SignificantTermsAggregatorFactory + JLHScore)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InternalSignificantTerms(InternalAggregation):
    size: int
    min_doc_count: int
    subset_size: int
    superset_size: int
    # key → [subset_df, superset_df, sub]
    stats: Dict[Any, List]

    def reduce(self, others):
        subset = self.subset_size
        superset = self.superset_size
        merged = {k: [v[0], v[1], dict(v[2])]
                  for k, v in self.stats.items()}
        for o in others:
            subset += o.subset_size
            superset += o.superset_size
            for k, (s_df, b_df, sub) in o.stats.items():
                cur = merged.get(k)
                if cur is None:
                    merged[k] = [s_df, b_df, dict(sub)]
                else:
                    cur[0] += s_df
                    cur[1] += b_df
                    cur[2] = AggregatorFactories.reduce([cur[2], sub]) \
                        if cur[2] or sub else {}
        return InternalSignificantTerms(self.size, self.min_doc_count,
                                        subset, superset, merged)

    @staticmethod
    def _jlh(s_df, s_size, b_df, b_size) -> float:
        if s_size == 0 or b_size == 0 or s_df == 0:
            return 0.0
        fg = s_df / s_size
        bg = b_df / b_size
        if fg <= bg or bg == 0:
            return 0.0
        return (fg - bg) * (fg / bg)

    def to_response(self) -> Dict[str, Any]:
        scored = []
        for key, (s_df, b_df, sub) in self.stats.items():
            if s_df < self.min_doc_count:
                continue
            score = self._jlh(s_df, self.subset_size, b_df,
                              self.superset_size)
            if score <= 0:
                continue
            scored.append((score, key, s_df, b_df, sub))
        scored.sort(key=lambda t: (-t[0], t[1]))
        buckets = []
        for score, key, s_df, b_df, sub in scored[: self.size]:
            entry = {"key": key, "doc_count": int(s_df),
                     "score": float(score), "bg_count": int(b_df)}
            for sname, agg in sub.items():
                entry[sname] = agg.to_response()
            buckets.append(entry)
        return {"doc_count": int(self.subset_size),
                "bg_count": int(self.superset_size),
                "buckets": buckets}


class SignificantTermsAggregator(Aggregator):
    def __init__(self, name, field, size, shard_size, min_doc_count, sub):
        super().__init__(name, sub)
        self.field = field
        self.size = size
        self.shard_size = shard_size
        self.min_doc_count = min_doc_count

    def collect(self, ctx, mask) -> InternalSignificantTerms:
        n = ctx.view.segment.num_docs
        fg_mask = np.asarray(mask)
        bg_mask = ctx.live_mask
        subset_size = int(fg_mask[:n].sum())
        superset_size = int(np.asarray(bg_mask)[:n].sum())
        fg_vals, fg_docs, ord_terms = ctx.field_values(self.field, fg_mask)
        bg_vals, _, _ = ctx.field_values(self.field, bg_mask)

        def count(vals):
            if ord_terms is not None:
                ords = np.asarray(vals, dtype=np.int64)
                c = np.bincount(ords, minlength=len(ord_terms))
                return {ord_terms[i]: int(c[i])
                        for i in np.nonzero(c)[0]}
            uniq, counts = np.unique(vals, return_counts=True)
            return {(int(u) if float(u).is_integer() else float(u)): int(c)
                    for u, c in zip(uniq, counts)}

        fg_counts = count(fg_vals) if len(fg_vals) else {}
        bg_counts = count(bg_vals) if len(bg_vals) else {}
        # shard-side trim by local JLH score bounds coordinator work
        scored = sorted(
            fg_counts.items(),
            key=lambda kv: -InternalSignificantTerms._jlh(
                kv[1], subset_size, bg_counts.get(kv[0], kv[1]),
                superset_size))[: self.shard_size]
        stats: Dict[Any, List] = {}
        if self.sub and ord_terms is not None:
            fg_ords = np.asarray(fg_vals, dtype=np.int64)
            term_ord = {t: i for i, t in enumerate(ord_terms)}
        for key, s_df in scored:
            sub = {}
            if self.sub:
                if ord_terms is not None:
                    sel = fg_ords == term_ord[key]
                else:
                    sel = np.asarray(fg_vals) == key
                bucket_mask = np.zeros_like(fg_mask)
                bucket_mask[fg_docs[sel]] = True
                sub = self.sub.collect(ctx, fg_mask & bucket_mask)
            stats[key] = [s_df, bg_counts.get(key, s_df), sub]
        return InternalSignificantTerms(self.size, self.min_doc_count,
                                        subset_size, superset_size, stats)

    def empty(self) -> InternalSignificantTerms:
        return InternalSignificantTerms(self.size, self.min_doc_count,
                                        0, 0, {})


@register_agg("significant_terms")
def _parse_significant_terms(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[significant_terms] requires a "
                                       "field")
    size = int(body.get("size", 10))
    shard_size = int(body.get("shard_size", size * 3 // 2 + 10))
    return SignificantTermsAggregator(
        name, field, size, max(size, shard_size),
        int(body.get("min_doc_count", 3)), sub)


# ---------------------------------------------------------------------------
# geohash_grid
# ---------------------------------------------------------------------------

def geohash_encode_batch(lats: np.ndarray, lons: np.ndarray,
                         precision: int) -> List[str]:
    """Vectorized geohash: interleave lon/lat bisection bits across the
    whole array (reference: Geohash utils behind GeoHashGridAggregator).
    5·precision bisection steps over numpy arrays, no per-doc loop."""
    from elasticsearch_tpu.mapping.types import GeoPointFieldType
    n = len(lats)
    nbits = 5 * precision
    lat_lo = np.full(n, -90.0)
    lat_hi = np.full(n, 90.0)
    lon_lo = np.full(n, -180.0)
    lon_hi = np.full(n, 180.0)
    bits = np.zeros((nbits, n), dtype=np.int8)
    for b in range(nbits):
        if b % 2 == 0:  # even bit: longitude
            mid = (lon_lo + lon_hi) / 2
            hi = lons >= mid
            bits[b] = hi
            lon_lo = np.where(hi, mid, lon_lo)
            lon_hi = np.where(hi, lon_hi, mid)
        else:
            mid = (lat_lo + lat_hi) / 2
            hi = lats >= mid
            bits[b] = hi
            lat_lo = np.where(hi, mid, lat_lo)
            lat_hi = np.where(hi, lat_hi, mid)
    alphabet = GeoPointFieldType._GEOHASH32
    chars = np.zeros((precision, n), dtype=np.int8)
    for c in range(precision):
        for k in range(5):
            chars[c] = chars[c] * 2 + bits[c * 5 + k]
    return ["".join(alphabet[chars[c, i]] for c in range(precision))
            for i in range(n)]


class GeoHashGridAggregator(Aggregator):
    """{"geohash_grid": {"field": f, "precision": 1..12, "size": N}} —
    bucket geo points by geohash cell (reference:
    geogrid/GeoHashGridAggregator, SURVEY.md §2.1#55). Reduces through
    the InternalTerms machinery (count-ordered cells)."""

    def __init__(self, name, field, precision, size, shard_size, sub):
        super().__init__(name, sub)
        self.field = field
        self.precision = precision
        self.size = size
        self.shard_size = shard_size

    def _points(self, ctx: SegmentAggContext, mask):
        from elasticsearch_tpu.mapping.types import GeoPointFieldType
        pack = ctx.view.pack
        n = ctx.view.segment.num_docs
        lat = pack.dv_f64.get(self.field + GeoPointFieldType.LAT_SUFFIX)
        lon = pack.dv_f64.get(self.field + GeoPointFieldType.LON_SUFFIX)
        if lat is None or lon is None:
            return (np.empty(0), np.empty(0),
                    np.empty(0, dtype=np.int64))
        m = np.asarray(mask)[:n] & ~np.isnan(lat[:n])
        docs = np.nonzero(m)[0]
        return lat[:n][m], lon[:n][m], docs

    def collect(self, ctx: SegmentAggContext, mask) -> InternalTerms:
        lats, lons, docs = self._points(ctx, mask)
        buckets: Dict[Any, Bucket] = {}
        if len(lats):
            hashes = np.asarray(geohash_encode_batch(
                lats, lons, self.precision))
            uniq, inv = np.unique(hashes, return_inverse=True)
            counts = np.bincount(inv)
            order = np.argsort(-counts, kind="stable")[: self.shard_size]
            for i in order:
                key = str(uniq[i])
                sub = {}
                if self.sub:
                    bucket_mask = np.zeros_like(np.asarray(mask))
                    bucket_mask[docs[inv == i]] = True
                    sub = self.sub.collect(
                        ctx, np.asarray(mask) & bucket_mask)
                buckets[key] = Bucket(key, int(counts[i]), sub)
        return InternalTerms(self.size, 1, buckets, "_count", False)

    def empty(self) -> InternalTerms:
        return InternalTerms(self.size, 1, {}, "_count", False)


@register_agg("geohash_grid")
def _parse_geohash_grid(name, body, sub):
    field = body.get("field")
    if field is None:
        raise IllegalArgumentException("[geohash_grid] requires a field")
    precision = int(body.get("precision", 5))
    if not 1 <= precision <= 12:
        raise IllegalArgumentException(
            f"[geohash_grid] precision must be in [1, 12], got "
            f"{precision}")
    size = int(body.get("size", 10000))
    shard_size = int(body.get("shard_size", max(size, 10) * 3 // 2 + 10))
    return GeoHashGridAggregator(name, field, precision, size,
                                 max(size, shard_size), sub)
