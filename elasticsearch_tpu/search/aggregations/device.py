"""Device-side aggregation kernels (SURVEY.md §7.2.8, §2.1#38).

The reference's largest subsystem spends its time in per-segment leaf
collectors walking docs; here the big three collectors — terms,
histogram/date_histogram, numeric stats — are MASKED SEGMENT REDUCTIONS
over the pack's doc-value columns, so they run as XLA scatter-add /
reduce ops over the same dense mask the query planner produced:

    terms:     counts[ord]   += mask        (scatter-add, drop-mode)
    histogram: counts[floor((v-off)/w)] += mask
    stats:     (count, sum, min, max) via masked reductions

Shapes are bucketed to powers of two so the jit cache stays small, and
each pack view caches its device-resident columns (first agg query per
segment pays the transfer, steady state reads HBM). Calendar intervals
run on device via host-precomputed bucket boundaries (bucket.py), and
one-level sub-aggregations run as per-bucket masked reductions here;
aggregators fall back to the host numpy path only when the device
can't express the request (multi-valued extras, deeper sub-agg
nesting)."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np


def _pow2(n: int, floor: int = 16) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


# bounded global budget for device-resident agg columns: columns are a
# derived cache, so LRU eviction just re-transfers on the next agg query.
# Tracked here (not per-pack) so many segments × many fields can't
# accumulate unaccounted HBM behind the circuit breaker's back; entries
# hold only weakrefs to the per-pack caches, so a merged-away pack frees
# its columns with the pack itself.
DEV_COL_BUDGET_BYTES = 1 << 30
_dev_registry: "OrderedDict[int, Tuple[Any, Any, int]]" = None  # type: ignore
_dev_lock = None
_dev_total = 0
_dev_seq = 0


def _dev_col(pack, kind: str, field: str):
    """Device-resident copy of a pack dv column, cached on the pack and
    accounted against DEV_COL_BUDGET_BYTES (LRU across all packs)."""
    global _dev_registry, _dev_lock, _dev_total, _dev_seq
    import threading
    import weakref
    from collections import OrderedDict

    import jax
    if _dev_lock is None:
        _dev_lock = threading.Lock()
        _dev_registry = OrderedDict()
    cache = getattr(pack, "_dev_cols", None)
    if cache is None:
        cache = {}
        pack._dev_cols = cache
    key = (kind, field)
    arr = cache.get(key)
    if arr is not None:
        return arr
    host = {"ord": pack.dv_ord, "i64": pack.dv_i64,
            "f64": pack.dv_f64}[kind][field]
    arr = jax.device_put(host)
    nbytes = int(host.nbytes)
    with _dev_lock:
        if key in cache:  # racing transfer of the same column
            return cache[key]
        cache[key] = arr
        _dev_seq += 1
        _dev_registry[_dev_seq] = (weakref.ref(pack), key, nbytes)
        _dev_total += nbytes
        while _dev_total > DEV_COL_BUDGET_BYTES and _dev_registry:
            _, (pref, pkey, pbytes) = _dev_registry.popitem(last=False)
            _dev_total -= pbytes
            p = pref()
            if p is not None:
                getattr(p, "_dev_cols", {}).pop(pkey, None)
    return arr


@functools.lru_cache(maxsize=64)
def _terms_counts_fn(n_out: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(ords, mask):
        idx = jnp.where(mask & (ords >= 0), ords, n_out)
        return jnp.zeros(n_out, dtype=jnp.int64).at[idx].add(
            1, mode="drop")

    return f


def terms_counts(pack, field: str, mask) -> Optional[np.ndarray]:
    """Per-ordinal doc counts for a keyword terms agg, on device.
    Returns None when the column isn't device-servable."""
    col = pack.dv_ord.get(field)
    terms = pack.dv_ord_terms.get(field)
    if col is None or not terms:
        return None
    import jax.numpy as jnp
    n_out = _pow2(len(terms))
    counts = _terms_counts_fn(n_out)(_dev_col(pack, "ord", field),
                                     jnp.asarray(mask))
    return np.asarray(counts)[: len(terms)]


@functools.lru_cache(maxsize=64)
def _histo_counts_fn(n_out: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(col, valid, base, interval):
        # f64 bucket math for BOTH column kinds: intervals/offsets are
        # doubles in the request (interval 2.5 on a long field is
        # valid); i64 values ≤ 2^53 convert exactly
        ids = jnp.floor((col.astype(jnp.float64) - base)
                        / interval).astype(jnp.int64)
        idx = jnp.where(valid & (ids >= 0) & (ids < n_out), ids, n_out)
        return jnp.zeros(n_out, dtype=jnp.int64).at[idx].add(
            1, mode="drop")

    return f


def histogram_counts(pack, field: str, mask, offset, interval,
                     lo_bucket: int, n_buckets: int
                     ) -> Optional[np.ndarray]:
    """Fixed-interval histogram counts on device: bucket i counts docs in
    [offset + (lo_bucket+i)·interval, ...+interval). Returns i64 counts
    [n_buckets] or None when no device column exists."""
    import jax.numpy as jnp
    from elasticsearch_tpu.index.segment import MISSING_I64
    m = jnp.asarray(mask)
    if field in pack.dv_i64:
        col = _dev_col(pack, "i64", field)
        valid = m & (col != MISSING_I64)
    elif field in pack.dv_f64:
        col = _dev_col(pack, "f64", field)
        valid = m & ~jnp.isnan(col)
    else:
        return None
    n_out = _pow2(n_buckets)
    base = float(offset) + float(lo_bucket) * float(interval)
    counts = _histo_counts_fn(n_out)(
        col, valid, jnp.float64(base), jnp.float64(interval))
    return np.asarray(counts)[: n_buckets]


@functools.lru_cache(maxsize=8)
def _stats_fn(is_float: bool):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(col, valid):
        colf = col.astype(jnp.float64)
        cnt = jnp.sum(valid)
        s = jnp.sum(jnp.where(valid, colf, 0.0))
        mn = jnp.min(jnp.where(valid, colf, jnp.inf))
        mx = jnp.max(jnp.where(valid, colf, -jnp.inf))
        return cnt, s, mn, mx

    return f


def numeric_stats(pack, field: str, mask
                  ) -> Optional[Tuple[int, float, float, float]]:
    """(count, sum, min, max) of a numeric column under the mask, on
    device. None when no device column exists."""
    import jax.numpy as jnp
    from elasticsearch_tpu.index.segment import MISSING_I64
    m = jnp.asarray(mask)
    if field in pack.dv_i64:
        col = _dev_col(pack, "i64", field)
        valid = m & (col != MISSING_I64)
        cnt, s, mn, mx = _stats_fn(False)(col, valid)
    elif field in pack.dv_f64:
        col = _dev_col(pack, "f64", field)
        valid = m & ~jnp.isnan(col)
        cnt, s, mn, mx = _stats_fn(True)(col, valid)
    else:
        return None
    return int(cnt), float(s), float(mn), float(mx)


@functools.lru_cache(maxsize=64)
def _ord_presence_fn(n_out: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(ords, mask):
        idx = jnp.where(mask & (ords >= 0), ords, n_out)
        return jnp.zeros(n_out, dtype=jnp.int32).at[idx].max(
            1, mode="drop")

    return f


def ord_presence(pack, field: str, mask) -> Optional[np.ndarray]:
    """bool[n_terms]: which keyword ordinals appear under the mask —
    the device half of an exact-per-segment cardinality collect (the
    host then feeds only the DISTINCT terms into the HLL sketch that
    merges across shards, instead of hashing every doc)."""
    col = pack.dv_ord.get(field)
    terms = pack.dv_ord_terms.get(field)
    if col is None or not terms:
        return None
    import jax.numpy as jnp
    n_out = _pow2(len(terms))
    present = _ord_presence_fn(n_out)(_dev_col(pack, "ord", field),
                                      jnp.asarray(mask))
    return np.asarray(present)[: len(terms)] > 0


@functools.lru_cache(maxsize=64)
def _bounded_bucket_fn(n_bounds: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(col, valid, bounds):
        # bucket of v = index of the last boundary ≤ v (searchsorted
        # right − 1); out-of-range and invalid docs drop. The f64 cast
        # stays INSIDE the jit so it fuses (no per-query HBM copy)
        ids = jnp.searchsorted(bounds, col.astype(jnp.float64),
                               side="right") - 1
        idx = jnp.where(valid & (ids >= 0), ids, n_bounds)
        return jnp.zeros(n_bounds, dtype=jnp.int64).at[idx].add(
            1, mode="drop")

    return f


def bounded_bucket_counts(pack, field: str, mask,
                          boundaries: np.ndarray
                          ) -> Optional[np.ndarray]:
    """Counts per variable-width bucket [boundaries[i], boundaries[i+1])
    — calendar intervals (month/quarter/year) become one device
    searchsorted + scatter-add over host-precomputed month starts
    (SURVEY.md §7.2.8; VERDICT r4 item 8: calendar intervals fell off
    the device path)."""
    import jax.numpy as jnp
    from elasticsearch_tpu.index.segment import MISSING_I64
    m = jnp.asarray(mask)
    if field in pack.dv_i64:
        col = _dev_col(pack, "i64", field)
        valid = m & (col != MISSING_I64)
    elif field in pack.dv_f64:
        col = _dev_col(pack, "f64", field)
        valid = m & ~jnp.isnan(col)
    else:
        return None
    n = _pow2(len(boundaries))
    bounds = np.full(n, np.iinfo(np.int64).max, dtype=np.float64)
    bounds[: len(boundaries)] = boundaries
    counts = _bounded_bucket_fn(n)(col, valid, jnp.asarray(bounds))
    return np.asarray(counts)[: len(boundaries)]


@functools.lru_cache(maxsize=64)
def _terms_metric_fn(n_out: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(ords, vals, ok):
        idx = jnp.where(ok, ords, n_out)
        z = lambda fill: jnp.full(n_out, fill, dtype=jnp.float64)
        cnt = jnp.zeros(n_out, dtype=jnp.int64).at[idx].add(
            1, mode="drop")
        v = vals.astype(jnp.float64)
        s = z(0.0).at[idx].add(jnp.where(ok, v, 0.0), mode="drop")
        mn = z(jnp.inf).at[idx].min(jnp.where(ok, v, jnp.inf),
                                    mode="drop")
        mx = z(-jnp.inf).at[idx].max(jnp.where(ok, v, -jnp.inf),
                                     mode="drop")
        return cnt, s, mn, mx

    return f


def terms_numeric_stats(pack, key_field: str, val_field: str, mask
                        ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                            np.ndarray, np.ndarray]]:
    """One-level sub-agg on device (VERDICT r4 item 8): per keyword
    ordinal of `key_field`, the (count, sum, min, max) of `val_field`
    — a numeric metric nested under a terms agg runs as FOUR
    scatter-reductions instead of per-bucket host masks."""
    import jax.numpy as jnp
    from elasticsearch_tpu.index.segment import MISSING_I64
    ord_col = pack.dv_ord.get(key_field)
    terms = pack.dv_ord_terms.get(key_field)
    if ord_col is None or not terms:
        return None
    m = jnp.asarray(mask)
    if val_field in pack.dv_i64:
        vals = _dev_col(pack, "i64", val_field)
        valid = m & (vals != MISSING_I64)
    elif val_field in pack.dv_f64:
        vals = _dev_col(pack, "f64", val_field)
        valid = m & ~jnp.isnan(vals)
    else:
        return None
    ords = _dev_col(pack, "ord", key_field)
    ok = valid & (ords >= 0)
    n_out = _pow2(len(terms))
    cnt, s, mn, mx = _terms_metric_fn(n_out)(ords, vals, ok)
    n = len(terms)
    return (np.asarray(cnt)[:n], np.asarray(s)[:n],
            np.asarray(mn)[:n], np.asarray(mx)[:n])
