"""Pipeline aggregations — computed over REDUCED results at
response-build time, never per shard.

Reference: `search/aggregations/pipeline/**` (SURVEY.md §2.1#38):
sibling pipelines (avg_bucket, sum_bucket, min_bucket, max_bucket,
stats_bucket) read a metric across a sibling multi-bucket agg via
`buckets_path` ("histo>metric" / "histo>_count"); parent pipelines
(derivative, cumulative_sum) run inside a histogram and add a value to
each bucket. `build_response` is the reduce-phase entry point the
coordinator calls instead of the raw `to_response`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.search.aggregations.base import (AggregatorFactories,
                                                        register_pipeline)

SIBLING = "sibling"
PARENT = "parent"


@dataclasses.dataclass
class Pipeline:
    name: str
    kind: str           # "avg_bucket" | ... | "derivative" | ...
    mode: str           # SIBLING | PARENT
    buckets_path: str
    gap_policy: str = "skip"      # "skip" | "insert_zeros"

    # ---------------- path resolution ----------------

    def _metric_from_bucket(self, bucket: Dict[str, Any],
                            segments: List[str]) -> Optional[float]:
        if segments == ["_count"]:
            return float(bucket.get("doc_count", 0))
        node: Any = bucket
        for seg in segments:
            if not isinstance(node, dict) or seg not in node:
                return None
            node = node[seg]
        if isinstance(node, dict):
            node = node.get("value")
        return None if node is None else float(node)

    def _bucket_values(self, host: Dict[str, Any]
                       ) -> List[Optional[float]]:
        """Sibling mode: resolve `agg>metric` against `host` (the dict
        holding the sibling agg's response)."""
        first, _, rest = self.buckets_path.partition(">")
        sibling = host.get(first)
        if not isinstance(sibling, dict) or "buckets" not in sibling:
            raise IllegalArgumentException(
                f"[{self.name}] buckets_path [{self.buckets_path}] must "
                f"point at a multi-bucket aggregation")
        segments = rest.split(">") if rest else ["_count"]
        buckets = sibling["buckets"]
        if isinstance(buckets, dict):  # keyed filters
            buckets = list(buckets.values())
        return [self._metric_from_bucket(b, segments) for b in buckets]

    def _values(self, host: Dict[str, Any]) -> List[float]:
        vals = self._bucket_values(host)
        if self.gap_policy == "insert_zeros":
            return [0.0 if v is None else v for v in vals]
        return [v for v in vals if v is not None]

    # ---------------- sibling computation ----------------

    def _bucket_keys(self, host: Dict[str, Any]) -> List[Any]:
        first, _, _ = self.buckets_path.partition(">")
        buckets = host.get(first, {}).get("buckets", [])
        if isinstance(buckets, dict):
            return list(buckets.keys())
        return [b.get("key") for b in buckets]

    def compute_sibling(self, host: Dict[str, Any]) -> Dict[str, Any]:
        vals = self._values(host)
        if self.kind == "avg_bucket":
            return {"value": sum(vals) / len(vals) if vals else None}
        if self.kind == "sum_bucket":
            return {"value": sum(vals) if vals else 0.0}
        if self.kind in ("min_bucket", "max_bucket"):
            # the response carries WHICH bucket(s) won (reference:
            # InternalBucketMetricValue#keys)
            if not vals:
                return {"value": None, "keys": []}
            best = min(vals) if self.kind == "min_bucket" else max(vals)
            all_vals = self._bucket_values(host)
            if self.gap_policy == "insert_zeros":
                all_vals = [0.0 if v is None else v for v in all_vals]
            keys = [str(k) for k, v in zip(self._bucket_keys(host),
                                           all_vals) if v == best]
            return {"value": best, "keys": keys}
        if self.kind == "stats_bucket":
            if not vals:
                return {"count": 0, "min": None, "max": None,
                        "avg": None, "sum": 0.0}
            return {"count": len(vals), "min": min(vals),
                    "max": max(vals), "avg": sum(vals) / len(vals),
                    "sum": sum(vals)}
        raise IllegalArgumentException(
            f"unknown sibling pipeline [{self.kind}]")

    # ---------------- parent computation ----------------

    def compute_parent(self, buckets: List[Dict[str, Any]]) -> None:
        segments = (self.buckets_path.split(">")
                    if self.buckets_path != "_count" else ["_count"])
        prev: Optional[float] = None
        running = 0.0
        for b in buckets:
            v = self._metric_from_bucket(b, segments)
            if v is None and self.gap_policy == "insert_zeros":
                v = 0.0
            if self.kind == "cumulative_sum":
                running += 0.0 if v is None else v
                b[self.name] = {"value": running}
            elif self.kind == "derivative":
                # first bucket (prev None) has no derivative; under
                # gap_policy=skip a gap bucket emits none and doesn't
                # advance prev (the next derivative spans the gap)
                if v is not None and prev is not None:
                    b[self.name] = {"value": v - prev}
                if v is not None:
                    prev = v


@dataclasses.dataclass
class ScriptPipeline(Pipeline):
    """bucket_script / bucket_selector (reference:
    BucketScriptPipelineAggregationBuilder): `buckets_path` is a MAP of
    script variable → metric path; the expression script computes one
    value per bucket (bucket_script adds it, bucket_selector keeps the
    bucket iff truthy). SURVEY.md §2.1#42 — one of the four subsystems
    the restricted expression engine unlocks."""

    paths: Dict[str, str] = dataclasses.field(default_factory=dict)
    script: Any = None  # CompiledScript

    def _bucket_vars(self, bucket: Dict[str, Any]
                     ) -> Optional[Dict[str, float]]:
        out: Dict[str, float] = {}
        for var, path in self.paths.items():
            segments = (path.split(">") if path != "_count"
                        else ["_count"])
            v = self._metric_from_bucket(bucket, segments)
            if v is None:
                if self.gap_policy == "insert_zeros":
                    v = 0.0
                else:
                    return None  # skip: bucket lacks an input
            out[var] = v
        return out

    def compute_parent(self, buckets: List[Dict[str, Any]]) -> None:
        from elasticsearch_tpu.script import ScriptException
        keep: List[Dict[str, Any]] = []
        for b in buckets:
            vars_in = self._bucket_vars(b)
            if vars_in is None:
                if self.kind == "bucket_script":
                    continue            # no value emitted for the gap
                keep.append(b)          # selector: gaps are kept
                continue
            try:
                result = self.script.execute(
                    {"params": {**self.script.params, **vars_in},
                     **vars_in})
            except ScriptException as e:
                raise IllegalArgumentException(
                    f"[{self.kind}] [{self.name}] script failed: "
                    f"{e.args[0] if e.args else e}") from None
            if self.kind == "bucket_script":
                if result is not None and not isinstance(
                        result, (int, float)):
                    raise IllegalArgumentException(
                        f"[bucket_script] [{self.name}] must return a "
                        f"number, got [{type(result).__name__}]")
                if result is not None:
                    b[self.name] = {"value": float(result)}
            else:  # bucket_selector
                if bool(result):
                    keep.append(b)
        if self.kind == "bucket_selector":
            buckets[:] = keep


def apply_pipelines(factories: AggregatorFactories,
                    node: Dict[str, Any]) -> None:
    """Walk the response tree alongside the parsed agg tree, recursing
    into buckets, then materialize this level's pipelines."""
    for name, agg in factories.aggregators.items():
        sub = getattr(agg, "sub", None)
        if sub is None or not sub:
            continue
        entry = node.get(name)
        if not isinstance(entry, dict):
            continue
        buckets = entry.get("buckets")
        if isinstance(buckets, list):
            for b in buckets:
                apply_pipelines(sub, b)
            for pname, pipe in sub.pipelines.items():
                if pipe.mode == PARENT:
                    pipe.compute_parent(buckets)
        else:
            # keyed filters (dict buckets) and single-bucket parents
            # cannot host a sequential parent pipeline — reject, never
            # silently drop (reference: 400 on invalid placement)
            for pname, pipe in sub.pipelines.items():
                if pipe.mode == PARENT:
                    raise IllegalArgumentException(
                        f"[{pipe.kind}] aggregation [{pname}] must be "
                        f"declared inside an ordered multi-bucket "
                        f"aggregation (histogram)")
            if isinstance(buckets, dict):
                for b in buckets.values():
                    apply_pipelines(sub, b)
            else:
                # single-bucket agg: sub responses flattened in place
                apply_pipelines(sub, entry)
    for pname, pipe in factories.pipelines.items():
        # PARENT pipelines at this level were computed by the enclosing
        # multi-bucket agg above (build_response rejects top-level ones)
        if pipe.mode == SIBLING:
            node[pname] = pipe.compute_sibling(node)


def build_response(factories: AggregatorFactories,
                   reduced: Dict[str, Any]) -> Dict[str, Any]:
    """Reduced internal aggs → response JSON with pipelines applied
    (the coordinator's final-reduce hook)."""
    out = AggregatorFactories.to_response(reduced)
    # top-level parent pipelines are invalid (no enclosing buckets)
    for pname, pipe in factories.pipelines.items():
        if pipe.mode == PARENT:
            raise IllegalArgumentException(
                f"[{pipe.kind}] aggregation [{pname}] must be declared "
                f"inside a multi-bucket aggregation")
    apply_pipelines(factories, out)
    return out


def _parse(kind: str, mode: str):
    def parser(name, body) -> Pipeline:
        path = (body or {}).get("buckets_path")
        if not path:
            raise IllegalArgumentException(
                f"[{kind}] requires [buckets_path]")
        gap = str((body or {}).get("gap_policy", "skip"))
        if gap not in ("skip", "insert_zeros"):
            raise IllegalArgumentException(
                f"[{kind}] unknown gap_policy [{gap}]")
        return Pipeline(name, kind, mode, str(path), gap)
    return parser


for _kind in ("avg_bucket", "sum_bucket", "min_bucket", "max_bucket",
              "stats_bucket"):
    register_pipeline(_kind)(_parse(_kind, SIBLING))
for _kind in ("derivative", "cumulative_sum"):
    register_pipeline(_kind)(_parse(_kind, PARENT))


def _parse_script_pipeline(kind: str):
    def parser(name, body) -> ScriptPipeline:
        body = body or {}
        paths = body.get("buckets_path")
        if not isinstance(paths, dict) or not paths:
            raise IllegalArgumentException(
                f"[{kind}] requires [buckets_path] as an object of "
                f"script variable → metric path")
        if "script" not in body:
            raise IllegalArgumentException(f"[{kind}] requires [script]")
        from elasticsearch_tpu.script import (ScriptException,
                                              compile_script)
        try:
            script = compile_script(body["script"])
        except ScriptException as e:
            raise IllegalArgumentException(
                f"[{kind}] {e.args[0] if e.args else e}") from None
        gap = str(body.get("gap_policy", "skip"))
        if gap not in ("skip", "insert_zeros"):
            raise IllegalArgumentException(
                f"[{kind}] unknown gap_policy [{gap}]")
        return ScriptPipeline(
            name, kind, PARENT, "", gap,
            paths={str(k): str(v) for k, v in paths.items()},
            script=script)
    return parser


for _kind in ("bucket_script", "bucket_selector"):
    register_pipeline(_kind)(_parse_script_pipeline(_kind))
