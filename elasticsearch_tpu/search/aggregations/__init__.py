"""Aggregations (reference: search/aggregations/**, SURVEY.md §2.1#38).

Import order matters: metrics/bucket modules self-register parsers with
base's registry on import."""

from elasticsearch_tpu.search.aggregations.base import (  # noqa: F401
    Aggregator,
    AggregatorFactories,
    InternalAggregation,
    SegmentAggContext,
    parse_aggregations,
)
from elasticsearch_tpu.search.aggregations import bucket as _bucket  # noqa: F401,E402
from elasticsearch_tpu.search.aggregations import metrics as _metrics  # noqa: F401,E402
from elasticsearch_tpu.search.aggregations.pipeline import (  # noqa: F401,E402
    build_response,
)
