"""Highlighting — the plain highlighter.

Reference: `search/fetch/subphase/highlight/**` (PlainHighlighter,
HighlightBuilder — SURVEY.md §2.1#50). Kept contracts: the request
grammar ({"fields": {name: {...}}, pre_tags/post_tags/fragment_size/
number_of_fragments/require_field_match), per-hit {"highlight":
{field: [fragments]}} in the response, fields with no match are
omitted, number_of_fragments=0 highlights the whole value.

The token scanner re-analyzes the stored source the way the plain
highlighter re-analyzes with the index analyzer: word tokens are
matched case-insensitively against the query's term predicates (exact
terms, prefix, wildcard, fuzzy), each match wrapped in the tags, and
fragments are match-scored windows over the raw text.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.search import dsl

DEFAULT_FRAGMENT_SIZE = 100
DEFAULT_NUM_FRAGMENTS = 5
_TOKEN = re.compile(r"\w+", re.UNICODE)

Matcher = Callable[[str], bool]


class HighlightSpec:
    def __init__(self, body: Dict[str, Any]):
        if not isinstance(body, dict) or not isinstance(
                body.get("fields"), dict):
            raise IllegalArgumentException(
                "[highlight] requires a [fields] object")
        self.pre = (body.get("pre_tags") or ["<em>"])[0]
        self.post = (body.get("post_tags") or ["</em>"])[0]
        self.require_field_match = bool(
            body.get("require_field_match", True))
        self.fields: Dict[str, Dict[str, Any]] = {}
        for name, opts in body["fields"].items():
            opts = opts or {}
            self.fields[name] = {
                "fragment_size": int(opts.get(
                    "fragment_size",
                    body.get("fragment_size", DEFAULT_FRAGMENT_SIZE))),
                "number_of_fragments": int(opts.get(
                    "number_of_fragments",
                    body.get("number_of_fragments",
                             DEFAULT_NUM_FRAGMENTS))),
                "pre": (opts.get("pre_tags") or [self.pre])[0],
                "post": (opts.get("post_tags") or [self.post])[0],
            }


# ----------------------------------------------------------------------
# query term extraction → token matchers per field
# ----------------------------------------------------------------------

def _split_terms(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN.findall(str(text))]


def collect_matchers(query: dsl.QueryNode, field: str,
                     require_field_match: bool) -> List[Matcher]:
    """Token predicates this query implies for `field` (reference:
    the highlighter extracts terms from the rewritten query)."""
    out: List[Matcher] = []

    def field_ok(f: str) -> bool:
        return (not require_field_match) or f == field

    def exact(terms: List[str]) -> Matcher:
        tset = set(terms)
        return lambda tok: tok in tset

    def walk(node: dsl.QueryNode) -> None:
        if isinstance(node, dsl.MatchQuery) and field_ok(node.field):
            out.append(exact(_split_terms(node.query)))
        elif isinstance(node, dsl.MatchPhraseQuery) \
                and field_ok(node.field):
            out.append(exact(_split_terms(node.query)))
        elif isinstance(node, dsl.TermQuery) and field_ok(node.field):
            out.append(exact(_split_terms(node.value)))
        elif isinstance(node, dsl.TermsQuery) and field_ok(node.field):
            terms: List[str] = []
            for v in node.values:
                terms.extend(_split_terms(v))
            out.append(exact(terms))
        elif isinstance(node, dsl.MultiMatchQuery):
            if any(field_ok(f) for f, _ in node.fields):
                out.append(exact(_split_terms(node.query)))
        elif isinstance(node, dsl.PrefixQuery) and field_ok(node.field):
            prefix = node.value.lower()
            out.append(lambda tok: tok.startswith(prefix))
        elif isinstance(node, dsl.WildcardQuery) \
                and field_ok(node.field):
            import fnmatch
            pattern = node.value.lower().replace("[", "[[]")
            out.append(lambda tok: fnmatch.fnmatchcase(tok, pattern))
        elif isinstance(node, dsl.FuzzyQuery) and field_ok(node.field):
            from elasticsearch_tpu.search.planner import \
                _edit_distance_lte
            value = node.value.lower()
            n = len(value)
            max_d = (0 if n < 3 else (1 if n < 6 else 2)) \
                if not isinstance(node.fuzziness, int) \
                else node.fuzziness
            out.append(
                lambda tok: _edit_distance_lte(value, tok, max_d))
        elif isinstance(node, dsl.BoolQuery):
            # must_not never highlights (excluded docs' terms)
            for child in node.must + node.should + node.filter:
                walk(child)
        elif isinstance(node, dsl.ConstantScoreQuery):
            walk(node.filter_query)
        elif isinstance(node, dsl.FunctionScoreQuery):
            walk(node.query)
        elif isinstance(node, dsl.ScriptScoreQuery):
            walk(node.query)

    walk(query)
    return out


# ----------------------------------------------------------------------
# fragment building
# ----------------------------------------------------------------------

def _match_spans(text: str, matchers: List[Matcher]
                 ) -> List[Tuple[int, int]]:
    spans = []
    for m in _TOKEN.finditer(text):
        tok = m.group(0).lower()
        if any(fn(tok) for fn in matchers):
            spans.append((m.start(), m.end()))
    return spans


def _wrap(text: str, spans: List[Tuple[int, int]], pre: str,
          post: str) -> str:
    out = []
    last = 0
    for s, e in spans:
        out.append(text[last:s])
        out.append(pre)
        out.append(text[s:e])
        out.append(post)
        last = e
    out.append(text[last:])
    return "".join(out)


def highlight_value(text: str, matchers: List[Matcher], *,
                    fragment_size: int, number_of_fragments: int,
                    pre: str, post: str) -> Optional[List[str]]:
    """→ highlighted fragments, or None when nothing matched."""
    spans = _match_spans(text, matchers)
    if not spans:
        return None
    if number_of_fragments == 0:
        # the whole field value as one fragment (reference semantics)
        return [_wrap(text, spans, pre, post)]
    # greedy windows: walk the matches in order, open a window at the
    # first uncovered match, extend to fragment_size on word boundaries
    fragments: List[Tuple[int, List[Tuple[int, int]], int, int]] = []
    i = 0
    while i < len(spans) and len(fragments) < number_of_fragments:
        start = max(0, spans[i][0] - fragment_size // 4)
        # snap to a word boundary leftward
        while start > 0 and text[start - 1].isalnum():
            start -= 1
        end = min(len(text), start + fragment_size)
        while end < len(text) and text[end - 1].isalnum() \
                and text[end:end + 1].isalnum():
            end += 1
        inside = []
        while i < len(spans) and spans[i][1] <= end:
            inside.append(spans[i])
            i += 1
        if not inside:  # the match itself is longer than the window
            inside = [spans[i]]
            end = spans[i][1]
            i += 1
        fragments.append((len(inside), inside, start, end))
    return [
        _wrap(text[start:end],
              [(s - start, e - start) for s, e in inside], pre, post)
        for _count, inside, start, end in fragments]


def build_highlights(query: dsl.QueryNode, source: Optional[dict],
                     spec: HighlightSpec,
                     available_fields: Optional[List[str]] = None
                     ) -> Dict[str, List[str]]:
    """Per-hit highlight map; fields without matches are omitted."""
    import fnmatch
    out: Dict[str, List[str]] = {}
    if not isinstance(source, dict):
        return out
    for pattern, opts in spec.fields.items():
        if "*" in pattern or "?" in pattern:
            names = [f for f in source
                     if fnmatch.fnmatchcase(f, pattern)]
        else:
            names = [pattern]
        for name in names:
            value = source.get(name)
            if not isinstance(value, str):
                continue
            matchers = collect_matchers(query, name,
                                        spec.require_field_match)
            if not matchers:
                continue
            frags = highlight_value(
                value, matchers,
                fragment_size=opts["fragment_size"],
                number_of_fragments=opts["number_of_fragments"],
                pre=opts["pre"], post=opts["post"])
            if frags:
                out[name] = frags
    return out
