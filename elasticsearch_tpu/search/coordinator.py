"""Search coordinator — query_then_fetch across shards.

Reference: `action/search/TransportSearchAction` +
`SearchPhaseController` (SURVEY.md §2.1#35, §3.3): resolve indices →
query phase on every shard → merge top-k (score desc, tie toward lower
shard ordinal then doc order) → fetch phase only on shards owning
winners → reduce aggs → one response. This module is the LOCAL-node
coordinator (all shards in-process); the mesh-distributed BM25 fast path
lives in parallel/distributed.py and federation over hosts arrives with
the transport layer.
"""

from __future__ import annotations

import fnmatch
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from elasticsearch_tpu.common.errors import (IllegalArgumentException,
                                             IndexNotFoundException)
from elasticsearch_tpu.indices.service import IndicesService
from elasticsearch_tpu.search import dsl
from elasticsearch_tpu.search.aggregations import (AggregatorFactories,
                                                   parse_aggregations)
from elasticsearch_tpu.search.query_phase import (ShardHit, execute_fetch,
                                                  execute_query)


def resolve_indices(indices: IndicesService,
                    expression: Optional[str]) -> List[str]:
    """Wildcard/CSV index resolution (reference:
    IndexNameExpressionResolver — no date math yet)."""
    names = sorted(indices.indices.keys())
    if expression in (None, "", "_all", "*"):
        return names
    out: List[str] = []
    for part in expression.split(","):
        part = part.strip()
        if not part:
            continue
        if "*" in part or "?" in part:
            matched = fnmatch.filter(names, part)
            out.extend(m for m in matched if m not in out)
        else:
            if part not in names:
                raise IndexNotFoundException(f"no such index [{part}]")
            if part not in out:
                out.append(part)
    return out


def parse_search_body(body: Optional[Dict[str, Any]]):
    body = body or {}
    unknown = set(body) - {"query", "aggs", "aggregations", "size", "from",
                           "_source", "min_score", "track_total_hits",
                           "sort", "search_after", "highlight", "suggest",
                           "version", "seq_no_primary_term"}
    if unknown:
        raise IllegalArgumentException(
            f"unknown search body keys {sorted(unknown)}")
    query = dsl.parse_query(body.get("query") or {"match_all": {}})
    aggs_spec = body.get("aggs") or body.get("aggregations")
    aggs = parse_aggregations(aggs_spec) if aggs_spec else None
    return query, aggs, body


def search(indices: IndicesService, index_expr: Optional[str],
           body: Optional[Dict[str, Any]],
           params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    t0 = time.perf_counter()
    params = params or {}
    names = resolve_indices(indices, index_expr)
    query, aggs, body = parse_search_body(body)
    size = int(params.get("size", body.get("size", 10)))
    from_ = int(params.get("from", body.get("from", 0)))
    min_score = body.get("min_score")
    source = body.get("_source", True)

    # ---- query phase: every shard of every target index ----
    shard_results = []   # (index_name, shard_num, QuerySearchResult)
    total = 0
    for name in names:
        svc = indices.index(name)
        for shard_num, shard in sorted(svc.shards.items()):
            reader = shard.acquire_searcher()
            res = execute_query(reader, query, size=size + from_, from_=0,
                                min_score=min_score, aggs=aggs)
            shard_results.append((name, shard_num, shard, res))
            total += res.total_hits

    # ---- merge top-k (score desc, then index/shard order, then rank) ----
    merged: List[Tuple[float, int, int, ShardHit]] = []
    for si, (name, shard_num, shard, res) in enumerate(shard_results):
        for rank, hit in enumerate(res.hits):
            merged.append((hit.score, si, rank, hit))
    merged.sort(key=lambda t: (-t[0], t[1], t[2]))
    window = merged[from_: from_ + size]

    # ---- fetch phase: group winners by shard ----
    by_shard: Dict[int, List[ShardHit]] = {}
    for _, si, _, hit in window:
        by_shard.setdefault(si, []).append(hit)
    fetched: Dict[Tuple[int, str], Dict[str, Any]] = {}
    for si, hits in by_shard.items():
        name, shard_num, shard, _ = shard_results[si]
        reader = shard.acquire_searcher()
        for hit, doc in zip(hits, execute_fetch(reader, hits, source)):
            doc["_index"] = name
            fetched[(si, hit.doc_id)] = doc
    hits_json = []
    for score, si, _, hit in window:
        doc = fetched.get((si, hit.doc_id), {"_id": hit.doc_id})
        doc["_score"] = score
        hits_json.append(doc)

    max_score = merged[0][0] if merged else None
    out: Dict[str, Any] = {
        "took": int((time.perf_counter() - t0) * 1000),
        "timed_out": False,
        "_shards": {"total": len(shard_results),
                    "successful": len(shard_results), "skipped": 0,
                    "failed": 0},
        "hits": {"total": {"value": total, "relation": "eq"},
                 "max_score": max_score,
                 "hits": hits_json},
    }

    # ---- agg reduce across shards ----
    if aggs:
        parts = [res.aggregations for _, _, _, res in shard_results
                 if res.aggregations is not None]
        reduced = AggregatorFactories.reduce(parts) if parts else aggs.empty()
        out["aggregations"] = AggregatorFactories.to_response(reduced)
    return out


def count(indices: IndicesService, index_expr: Optional[str],
          body: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    names = resolve_indices(indices, index_expr)
    query = dsl.parse_query((body or {}).get("query") or {"match_all": {}})
    total = 0
    n_shards = 0
    for name in names:
        svc = indices.index(name)
        for shard_num, shard in sorted(svc.shards.items()):
            reader = shard.acquire_searcher()
            res = execute_query(reader, query, size=0)
            total += res.total_hits
            n_shards += 1
    return {"count": total,
            "_shards": {"total": n_shards, "successful": n_shards,
                        "skipped": 0, "failed": 0}}
