"""Search coordinator — query_then_fetch across shards.

Reference: `action/search/TransportSearchAction` +
`SearchPhaseController` (SURVEY.md §2.1#35, §3.3): resolve indices →
query phase on every shard → merge top-k (score desc, tie toward lower
shard ordinal then doc order) → fetch phase only on shards owning
winners → reduce aggs → one response. This module is the LOCAL-node
coordinator (all shards in-process); the mesh-distributed BM25 fast path
lives in parallel/distributed.py and federation over hosts arrives with
the transport layer.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import logging

from elasticsearch_tpu.common import tracing
from elasticsearch_tpu.common.errors import (CircuitBreakingException,
                                             EsRejectedExecutionException,
                                             IllegalArgumentException,
                                             IndexNotFoundException,
                                             SearchPhaseExecutionException,
                                             TaskCancelledException,
                                             shard_failure_entry)
from elasticsearch_tpu.indices.service import IndicesService
from elasticsearch_tpu.search import dsl
from elasticsearch_tpu.search.aggregations import (AggregatorFactories,
                                                   parse_aggregations)
from elasticsearch_tpu.search.query_phase import (ShardHit, execute_fetch,
                                                  execute_query, fault_check)

logger = logging.getLogger("elasticsearch_tpu.search.coordinator")

#: failures that must abort the whole request rather than degrade to a
#: per-shard failure: cancellation is the caller's decision, and breaker
#: / executor rejections must surface as 429s (reference: the breaker
#: trips BEFORE work is admitted, it is not a shard fault)
_NON_DEGRADABLE = (TaskCancelledException, CircuitBreakingException,
                   EsRejectedExecutionException)


def allow_partial_results(params: Optional[Dict[str, str]]) -> bool:
    """`allow_partial_search_results` query param (reference default:
    true — a search survives individual shard failures and reports them
    in `_shards.failures`)."""
    raw = (params or {}).get("allow_partial_search_results", "true")
    return str(raw).lower() not in ("false", "0", "no")


def check_shard_failures(failures: List[Dict[str, Any]], successful: int,
                         allow_partial: bool, phase: str = "query") -> None:
    """Reference AbstractSearchAsyncAction#onPhaseFailure semantics:
    every shard failing — or any shard failing when partial results are
    disallowed — raises SearchPhaseExecutionException (503) instead of
    returning a degraded 200."""
    if not failures:
        return
    if successful == 0:
        raise SearchPhaseExecutionException(phase, "all shards failed",
                                            failures)
    if not allow_partial:
        raise SearchPhaseExecutionException(
            phase, "Search rejected due to failed shards "
            "[allow_partial_search_results=false]", failures)


def _is_closed(entry) -> bool:
    """Closed-index check over both registry kinds: a local IndexService
    (`closed` flag) or cluster IndexMeta (`state` field)."""
    return (getattr(entry, "closed", False)
            or getattr(entry, "state", "open") == "close")


def resolve_targets(indices: IndicesService, expression: Optional[str]
                    ) -> Tuple[List[str], Dict[str, List[dict]]]:
    """Wildcard/CSV resolution over index AND alias names (reference:
    IndexNameExpressionResolver — no date math yet).

    → (index names, {index: [alias filter json, ...]}). An index reached
    directly (or through an unfiltered alias) in the same expression is
    unfiltered; multiple filtered aliases OR together. Closed indices:
    wildcard/_all expansion skips them (expand_wildcards=open default);
    naming one directly raises IndexClosedException (reference:
    IndicesOptions.strictExpandOpen)."""
    from elasticsearch_tpu.common.errors import IndexClosedException
    idx_names = sorted(indices.indices.keys())
    alias_map = getattr(indices, "aliases", {})
    alias_names = sorted(alias_map.keys())
    out: List[str] = []
    filters: Dict[str, List[dict]] = {}
    unfiltered: set = set()

    def closed(name: str) -> bool:
        return _is_closed(indices.indices.get(name))

    def add_index(name: str, filt: Optional[dict]) -> None:
        if name not in out:
            out.append(name)
        if filt is None:
            unfiltered.add(name)
            filters.pop(name, None)
        elif name not in unfiltered:
            filters.setdefault(name, []).append(filt)

    def add_part(part: str) -> None:
        if part in idx_names:
            if closed(part):
                raise IndexClosedException(f"closed index [{part}]")
            add_index(part, None)
            return
        if part in alias_names:
            for idx, props in sorted(alias_map[part].items()):
                if idx in indices.indices and not closed(idx):
                    add_index(idx, props.get("filter"))
            return
        raise IndexNotFoundException(f"no such index [{part}]")

    if expression in (None, "", "_all", "*"):
        for n in idx_names:
            if not closed(n):
                add_index(n, None)
        return out, filters
    for part in expression.split(","):
        part = part.strip()
        if not part:
            continue
        if "*" in part or "?" in part:
            for m in fnmatch.filter(idx_names, part):
                if not closed(m):
                    add_index(m, None)
            for m in fnmatch.filter(alias_names, part):
                add_part(m)
        else:
            add_part(part)
    return out, filters


def resolve_indices(indices: IndicesService,
                    expression: Optional[str]) -> List[str]:
    """Index-name resolution ignoring alias filters (admin APIs)."""
    return resolve_targets(indices, expression)[0]


def resolve_concrete_indices(indices: IndicesService,
                             expression: Optional[str]) -> List[str]:
    """Destructive admin APIs (delete index) must name CONCRETE indices
    — addressing one through an alias is rejected, never silently
    expanded onto the backing index (reference: DestructiveOperations +
    IndexNameExpressionResolver concrete-only resolution)."""
    alias_map = getattr(indices, "aliases", {})
    if expression:
        for part in expression.split(","):
            part = part.strip()
            if part in alias_map:
                raise IllegalArgumentException(
                    f"The provided expression [{part}] matches an alias; "
                    f"this operation requires concrete index names")
    names = sorted(indices.indices.keys())
    if expression in (None, "", "_all", "*"):
        return names
    out: List[str] = []
    for part in expression.split(","):
        part = part.strip()
        if not part:
            continue
        if "*" in part or "?" in part:
            out.extend(m for m in fnmatch.filter(names, part)
                       if m not in out)
        elif part not in names:
            raise IndexNotFoundException(f"no such index [{part}]")
        elif part not in out:
            out.append(part)
    return out


def with_alias_filters(query: dsl.QueryNode,
                       filts: Optional[List[dict]]) -> dsl.QueryNode:
    """Wrap the request query with the matched aliases' filters
    (reference: the alias filter joins the shard-level query as a
    FILTER clause; several filtered aliases OR together)."""
    if not filts:
        return query
    parsed = [dsl.parse_query(f) for f in filts]
    if len(parsed) == 1:
        filt: dsl.QueryNode = parsed[0]
    else:
        filt = dsl.BoolQuery(should=parsed, minimum_should_match=1)
    return dsl.BoolQuery(must=[query], filter=[filt])


def parse_search_body(body: Optional[Dict[str, Any]]):
    body = body or {}
    # unimplemented keys get a 400, never silently ignored (VERDICT r1
    # weak #1): a sorted/highlighted query must not return wrong results
    # with a 200
    unsupported = set(body) & {"script_fields"}
    if unsupported:
        raise IllegalArgumentException(
            f"search body keys {sorted(unsupported)} are not supported "
            f"yet by this engine")
    unknown = set(body) - {"query", "aggs", "aggregations", "size", "from",
                           "_source", "min_score", "track_total_hits",
                           "sort", "search_after", "timeout", "pit",
                           "profile", "highlight", "suggest",
                           "version", "seq_no_primary_term",
                           "rescore", "collapse", "knn", "_knn_docs"}
    if unknown:
        raise IllegalArgumentException(
            f"unknown search body keys {sorted(unknown)}")
    if body.get("knn") is not None:
        from elasticsearch_tpu.search.knn import parse_knn
        parse_knn(body["knn"])  # validate at parse time (400s)
        if body.get("sort") is not None or body.get("collapse"):
            raise IllegalArgumentException(
                "[knn] cannot be combined with [sort]/[collapse]: knn "
                "results are relevance-ranked")
    query = dsl.parse_query(body.get("query") or {"match_all": {}})
    aggs_spec = body.get("aggs") or body.get("aggregations")
    aggs = parse_aggregations(aggs_spec) if aggs_spec else None
    if body.get("rescore") is not None:
        from elasticsearch_tpu.search.rescore import parse_rescore
        parse_rescore(body["rescore"])  # validate at parse time (400s)
    if body.get("collapse") is not None:
        spec = body["collapse"]
        if not isinstance(spec, dict) or not spec.get("field"):
            raise IllegalArgumentException("[collapse] requires [field]")
        if spec.get("inner_hits") is not None:
            raise IllegalArgumentException(
                "[collapse] inner_hits is not supported yet")
        if body.get("sort") is not None or body.get("rescore") is not None:
            # keep the supported surface honest: collapse composes with
            # relevance ranking only for now
            raise IllegalArgumentException(
                "[collapse] cannot be combined with [sort]/[rescore] yet")
    return query, aggs, body


def encode_knn_docs(knn_wrap: Dict[Tuple[str, int], List[Tuple[Any, float]]]
                    ) -> Dict[str, Any]:
    """Per-shard knn winners → JSON-serializable `_knn_docs` body key
    (the wire form route_search ships to shard groups; reference: the
    coordinator's per-shard ScoreDoc lists after the knn phase)."""
    out: Dict[str, Any] = {}
    for (name, shard_num), sets in knn_wrap.items():
        entry = []
        for seg_map, boost in sets:
            entry.append({
                "boost": boost,
                "segments": {seg: [list(map(int, ords)),
                                   list(map(float, scores))]
                             for seg, (ords, scores) in seg_map.items()}})
        out[f"{name}#{shard_num}"] = entry
    return out


def decode_knn_docs(encoded: Dict[str, Any]
                    ) -> Dict[Tuple[str, int], List[Tuple[Any, float]]]:
    import numpy as np
    out: Dict[Tuple[str, int], List[Tuple[Any, float]]] = {}
    for key, sets in encoded.items():
        name, _, shard_s = key.rpartition("#")
        decoded = []
        for entry in sets:
            seg_map = {
                seg: (np.asarray(ords, dtype=np.int64),
                      np.asarray(scores, dtype=np.float32))
                for seg, (ords, scores) in entry["segments"].items()}
            decoded.append((seg_map, float(entry["boost"])))
        out[(name, int(shard_s))] = decoded
    return out


def parse_timeout_s(body: Dict[str, Any],
                    params: Dict[str, str]) -> Optional[float]:
    """`timeout` body key / query param → seconds (reference: TimeValue
    grammar; a search past its timeout returns partial results with
    "timed_out": true)."""
    raw = params.get("timeout", body.get("timeout"))
    if raw is None:
        return None
    from elasticsearch_tpu.common.units import TimeValue
    seconds = TimeValue.parse(raw).seconds
    if seconds < 0:
        return None  # -1 is the reference's "no timeout" sentinel
    return seconds


def search(indices: IndicesService, index_expr: Optional[str],
           body: Optional[Dict[str, Any]],
           params: Optional[Dict[str, str]] = None,
           tpu_search=None, task=None,
           pinned: Optional[Dict[Tuple[str, int], Any]] = None,
           names_override: Optional[List[str]] = None) -> Dict[str, Any]:
    """pinned: (index, shard) → ShardReader snapshot (scroll/PIT
    contexts); when set the kernel fast path is skipped — resident packs
    track the LIVE readers, not the snapshot."""
    from elasticsearch_tpu.search.query_phase import SearchContext
    t0 = time.perf_counter()
    params = params or {}
    if names_override is not None:
        names, alias_filters = list(names_override), {}
    else:
        names, alias_filters = resolve_targets(indices, index_expr)
    # partial-mesh shed check: an index whose resident pack was shed
    # for N-1 HBM headroom answers a TYPED 503 + Retry-After (load
    # shedding, not failure) until a fuller mesh readmits the pack
    if tpu_search is not None:
        shed_info = getattr(tpu_search, "shed_info", None)
        if callable(shed_info):
            for name in names:
                info = shed_info(name)
                if info:
                    from elasticsearch_tpu.common.errors import \
                        PackShedException
                    raise PackShedException(
                        f"index [{name}] shed from device residency "
                        f"during partial-mesh recovery; retry after "
                        f"capacity returns", index=name,
                        retry_after_s=float(
                            info.get("retry_after_s", 5.0)))
    query, aggs, body = parse_search_body(body)
    ctx = SearchContext(parse_timeout_s(body, params), task)
    size = int(params.get("size", body.get("size", 10)))
    from_ = int(params.get("from", body.get("from", 0)))
    min_score = body.get("min_score")
    source = body.get("_source", True)
    from elasticsearch_tpu.search import sort as sort_mod
    sort_specs = sort_mod.parse_sort(body.get("sort"))
    search_after = body.get("search_after")
    if search_after is not None and not sort_specs:
        raise IllegalArgumentException(
            "[search_after] requires a [sort] specification")
    highlight_spec = None
    fetch_source = source
    if body.get("highlight") is not None:
        from elasticsearch_tpu.search.highlight import HighlightSpec
        highlight_spec = HighlightSpec(body["highlight"])
        # the highlighter reads stored fields even when the response
        # suppresses _source
        fetch_source = True if source is False else source

    rescore_specs = None
    if body.get("rescore") is not None:
        from elasticsearch_tpu.search.rescore import parse_rescore
        rescore_specs = parse_rescore(body["rescore"])
    collapse_field = (body.get("collapse") or {}).get("field") \
        if body.get("collapse") else None

    # ---- knn candidate phase (reference: DfsQueryPhase for knn) ----
    # Resolve each knn clause to its GLOBAL top-k winners up front,
    # pinning one reader per shard so the query phase scores the same
    # point-in-time view the candidates came from.
    knn_wrap: Optional[Dict[Tuple[str, int], List[Tuple[Any, float]]]] = None
    knn_only = False
    if body.get("_knn_docs") is not None:
        # pre-resolved by a cluster-level coordinator (route_search)
        knn_wrap = decode_knn_docs(body["_knn_docs"])
        knn_only = "query" not in body
    elif body.get("knn") is not None:
        from elasticsearch_tpu.search import knn as knn_mod
        knn_specs = knn_mod.parse_knn(body["knn"])
        knn_only = "query" not in body
        if pinned is None:
            pinned = {}
            for name in names:
                svc = indices.index(name)
                for shard_num, shard in sorted(svc.shards.items()):
                    pinned[(name, shard_num)] = shard.acquire_searcher()
        knn_wrap = {}
        for spec in knn_specs:
            per_shard = {}
            for (name, shard_num), reader in pinned.items():
                if name not in names:
                    continue
                eff_spec = spec
                afilts = alias_filters.get(name)
                if afilts:
                    base_filt = spec.filter_query or dsl.MatchAllQuery()
                    eff_spec = dataclasses.replace(
                        spec, filter_query=with_alias_filters(
                            base_filt, afilts))
                per_shard[(name, shard_num)] = knn_mod.shard_candidates(
                    reader, eff_spec)
            grouped = knn_mod.global_topk(per_shard, spec.k)
            for shard_key, seg_map in grouped.items():
                knn_wrap.setdefault(shard_key, []).append(
                    (seg_map, spec.boost))

    # ---- TPU fast path: micro-batched kernel over resident packs ----
    # (VERDICT r1 #1: the batched pipeline IS the serving path for the
    # queries it can express; everything else falls through to the
    # planner below, unchanged.)
    profile = bool(body.get("profile"))
    if (tpu_search is not None and aggs is None and pinned is None
            and knn_wrap is None  # knn runs the two-phase planner path
            and not alias_filters  # filtered aliases run the planner
            and not any(k in body for k in ("sort", "search_after",
                                            "highlight", "suggest",
                                            "rescore", "collapse"))):
        # `profile: true` stays ON the kernel path (it used to force the
        # reference scorer — profiling a path we never serve with): the
        # response gains a TPU section next to the usual shard tree.
        try:
            fast = _search_fast(indices, names, query, tpu_search,
                                size=size, from_=from_,
                                min_score=min_score,
                                source=source, t0=t0,
                                version=bool(body.get("version")),
                                seq_no_primary_term=bool(
                                    body.get("seq_no_primary_term")),
                                ctx=ctx, profile=profile)
        except _NON_DEGRADABLE:
            raise
        except Exception:  # noqa: BLE001 — degrade to the planner path
            # a kernel-path fault must not kill the request: the planner
            # below re-runs it with per-shard failure capture
            logger.warning("kernel fast path failed; falling back to "
                           "the planner", exc_info=True)
            fast = None
        if fast is not None:
            # N-1 serving: even kernel-served answers carry the
            # structured degraded reason while the mesh is partial
            _stamp_degraded(fast, tpu_search, names)
            return fast

    # ---- query phase: every shard of every target index ----
    # each shard executes under failure capture (reference:
    # AbstractSearchAsyncAction#onShardFailure) — one copy throwing
    # degrades to a `_shards.failures[]` entry, never a lost request
    shard_results = []   # (index_name, shard_num, reader, QuerySearchResult)
    failures: List[Dict[str, Any]] = []
    allow_partial = allow_partial_results(params)
    total = 0
    timed_out = False
    skipped = 0
    if pinned is not None:
        # scroll/PIT accounting is over the SNAPSHOT's shards: copies
        # that left the registry since the context opened are not
        # "expected", copies missing from the snapshot are failures
        name_set = set(names)
        n_shards_expected = sum(1 for (n, _s) in pinned if n in name_set)
    else:
        n_shards_expected = sum(len(indices.index(n).shards)
                                for n in names)
    query_nanos: Dict[Tuple[str, int], int] = {}
    from elasticsearch_tpu.search.can_match import can_match
    for name in names:
        svc = indices.index(name)
        eff_query = with_alias_filters(query, alias_filters.get(name))
        for shard_num, shard in sorted(svc.shards.items()):
            if ctx.should_stop():
                timed_out = True
                break
            if pinned is not None:
                reader = pinned.get((name, shard_num))
                if reader is None:
                    continue  # shard not part of the pinned snapshot
            try:
                fault_check(name, shard_num, "query")
                if pinned is None:
                    reader = shard.acquire_searcher()
                if knn_wrap is not None:
                    # union the shard's pinned knn winners with the text
                    # query (None base when the request had knn only)
                    sets = knn_wrap.get((name, shard_num), [])
                    if knn_only and not sets:
                        skipped += 1  # nothing can match on this shard
                        continue
                    from elasticsearch_tpu.search.knn import wrap_query
                    shard_query = wrap_query(
                        None if knn_only else eff_query, sets)
                else:
                    shard_query = eff_query
                    if not can_match(reader, eff_query, svc.mapper):
                        skipped += 1  # disjoint range stats: skip
                        continue
                q0 = time.perf_counter()
                # the rescore window may exceed the response window
                k_shard = size + from_
                if rescore_specs:
                    k_shard = max(k_shard,
                                  max(s.window_size
                                      for s in rescore_specs))
                if collapse_field:
                    # exact grouped top-N per shard (no candidate-depth
                    # cap; a dominating key can't starve later groups)
                    from elasticsearch_tpu.search.collapse import \
                        collapse_top_groups
                    from elasticsearch_tpu.search.query_phase import \
                        QuerySearchResult
                    pairs, total_sh = collapse_top_groups(
                        reader, shard_query, collapse_field, size + from_)
                    res = QuerySearchResult(
                        [h for h, _ in pairs], total_sh,
                        pairs[0][0].score if pairs else None)
                    if aggs is not None:
                        res.aggregations = execute_query(
                            reader, shard_query, size=0, aggs=aggs,
                            ctx=ctx).aggregations
                else:
                    res = execute_query(reader, shard_query, size=k_shard,
                                        from_=0,
                                        min_score=min_score, aggs=aggs,
                                        sort_specs=sort_specs or None,
                                        search_after=search_after,
                                        ctx=ctx)
                if rescore_specs:
                    from elasticsearch_tpu.search.rescore import \
                        rescore_shard_hits
                    res.hits = rescore_shard_hits(reader, res.hits,
                                                  rescore_specs)
            except _NON_DEGRADABLE:
                raise
            except Exception as e:  # noqa: BLE001 — per-shard capture
                logger.debug("shard [%s][%d] query phase failed",
                             name, shard_num, exc_info=True)
                indices.count_search_failure(name, shard_num)
                tracing.add_event("shard.query_failed", index=name,
                                  shard=shard_num,
                                  error=f"{type(e).__name__}: {e}")
                failures.append(shard_failure_entry(name, shard_num, e))
                continue
            elapsed = time.perf_counter() - q0
            query_nanos[(name, shard_num)] = int(elapsed * 1e9)
            tracing.record_stage("shard.query", elapsed, index=name,
                                 shard=shard_num)
            if svc.search_slowlog.enabled:
                svc.search_slowlog.maybe_log(elapsed, shard_num,
                                             source=body,
                                             total_hits=res.total_hits)
            timed_out = timed_out or res.timed_out
            shard_results.append((name, shard_num, reader, res))
            total += res.total_hits
        if timed_out:
            break
    check_shard_failures(failures, len(shard_results) + skipped,
                         allow_partial, "query")

    # ---- merge top-k: by sort key when sorting, else score desc; ties
    # toward lower index/shard order then rank (reference merge order) ----
    merged: List[Tuple[Any, int, int, ShardHit]] = []
    for si, (name, shard_num, _reader, res) in enumerate(shard_results):
        for rank, hit in enumerate(res.hits):
            if sort_specs:
                key = sort_mod.sort_key(sort_specs, hit.sort_values or [])
            else:
                key = -hit.score
            merged.append((key, si, rank, hit))
    merged.sort(key=lambda t: (t[0], t[1], t[2]))
    if collapse_field:
        # field collapsing (reference: CollapseBuilder): keep the best
        # hit per key walking the merged ranking; missing-key docs are
        # not collapsed together
        seen_keys = set()
        collapsed = []
        hit_keys: Dict[int, Any] = {}
        for entry in merged:
            _, si, _, hit = entry
            reader = shard_results[si][2]
            key = _collapse_key(reader, hit, collapse_field)
            if key is not None:
                if key in seen_keys:
                    continue
                seen_keys.add(key)
            hit_keys[id(hit)] = key
            collapsed.append(entry)
            if len(collapsed) >= from_ + size:
                break
        window = collapsed[from_: from_ + size]
    else:
        window = merged[from_: from_ + size]

    # ---- fetch phase: group winners by shard ----
    by_shard: Dict[int, List[ShardHit]] = {}
    for _, si, _, hit in window:
        by_shard.setdefault(si, []).append(hit)
    fetched: Dict[Tuple[int, str], Dict[str, Any]] = {}
    want_version = bool(body.get("version"))
    want_seqno = bool(body.get("seq_no_primary_term"))
    fetch_nanos: Dict[Tuple[str, int], int] = {}
    fetch_failed: set = set()
    for si, hits in by_shard.items():
        # fetch against the SAME reader the query phase scored on —
        # a refresh in between must not remap doc ordinals
        name, shard_num, reader, _ = shard_results[si]
        f0 = time.perf_counter()
        try:
            fault_check(name, shard_num, "fetch")
            for hit, doc in zip(hits, execute_fetch(
                    reader, hits, fetch_source, version=want_version,
                    seq_no_primary_term=want_seqno)):
                doc["_index"] = name
                if highlight_spec is not None:
                    from elasticsearch_tpu.search.highlight import \
                        build_highlights
                    # highlight the REQUEST query only — alias filters
                    # select docs, they are not something the user
                    # searched
                    hl = build_highlights(query, doc.get("_source"),
                                          highlight_spec)
                    if hl:
                        doc["highlight"] = hl
                    if source is False:
                        doc.pop("_source", None)
                fetched[(si, hit.doc_id)] = doc
        except _NON_DEGRADABLE:
            raise
        except Exception as e:  # noqa: BLE001 — per-shard capture
            logger.debug("shard [%s][%d] fetch phase failed",
                         name, shard_num, exc_info=True)
            indices.count_search_failure(name, shard_num)
            tracing.add_event("shard.fetch_failed", index=name,
                              shard=shard_num,
                              error=f"{type(e).__name__}: {e}")
            failures.append(shard_failure_entry(name, shard_num, e))
            fetch_failed.add(si)
            fetched = {k: v for k, v in fetched.items() if k[0] != si}
            continue
        f_elapsed = time.perf_counter() - f0
        fetch_nanos[(name, shard_num)] = int(f_elapsed * 1e9)
        tracing.record_stage("shard.fetch", f_elapsed, index=name,
                             shard=shard_num)
    if fetch_failed:
        # a shard that lost its fetch phase contributes NO hits and
        # counts failed, even though its query phase ran
        window = [e for e in window if e[1] not in fetch_failed]
        check_shard_failures(
            failures, len(shard_results) - len(fetch_failed) + skipped,
            allow_partial, "fetch")
    hits_json = []
    for _key, si, _, hit in window:
        doc = fetched.get((si, hit.doc_id), {"_id": hit.doc_id})
        doc["_score"] = None if (sort_specs and hit.sort_values) else hit.score
        if hit.sort_values is not None:
            doc["sort"] = hit.sort_values
        if collapse_field:
            key = hit_keys.get(id(hit))
            if key is not None:
                doc["fields"] = {collapse_field: [key]}
        hits_json.append(doc)

    if sort_specs:
        # max_score is null under field sort (reference behavior)
        only_score = all(s.field == "_score" for s in sort_specs)
        max_score = (max((h.score for _, _, _, h in merged), default=None)
                     if only_score else None)
        if only_score:
            for doc, (_, _, _, hit) in zip(hits_json, window):
                doc["_score"] = hit.score
    else:
        max_score = -merged[0][0] if merged else None
    shards_json: Dict[str, Any] = {
        "total": n_shards_expected,
        "successful": len(shard_results) - len(fetch_failed) + skipped,
        "skipped": skipped,
        "failed": len(failures)}
    if failures:
        shards_json["failures"] = failures
    out: Dict[str, Any] = {
        "took": int((time.perf_counter() - t0) * 1000),
        "timed_out": timed_out,
        # total reflects every targeted shard even when the deadline
        # stopped the scan early (successful = actually visited; skipped
        # shards count as successful, reference can_match semantics)
        "_shards": shards_json,
        "hits": {"total": {"value": total,
                           "relation": "gte" if timed_out else "eq"},
                 "max_score": max_score,
                 "hits": hits_json},
    }

    # ---- agg reduce across shards (+ pipeline aggs on the final
    # reduced tree) ----
    if aggs:
        from elasticsearch_tpu.search.aggregations import build_response
        parts = [res.aggregations for _, _, _, res in shard_results
                 if res.aggregations is not None]
        reduced = AggregatorFactories.reduce(parts) if parts else aggs.empty()
        out["aggregations"] = build_response(aggs, reduced)

    if profile:
        out["profile"] = {"shards": build_profile(
            query, shard_results, query_nanos, fetch_nanos)}
    if body.get("suggest") is not None:
        from elasticsearch_tpu.search.suggest import run_suggest
        out["suggest"] = run_suggest(indices, names, body["suggest"])
    _stamp_degraded(out, tpu_search, names)
    return out


def _stamp_degraded(out: Dict[str, Any], tpu_search,
                    names: Optional[List[str]] = None) -> None:
    """Mark answers produced while the kernel path is degraded —
    batcher down/recovering (planner served this) or serving on a
    partial mesh (N-1 capacity) — with a structured reason clients
    can type against (reference: a yellow cluster keeps answering,
    and says so). A target index whose pack is being served by a
    surviving placement replica group carries the more specific
    `failed_over` reason — degraded but ANSWERED, the opposite of
    `shed` (which never reaches here: shed indexes 503 up front)."""
    if tpu_search is None:
        return
    info = None
    if names:
        failover_info = getattr(tpu_search, "failover_info", None)
        if callable(failover_info):
            for name in names:
                fo = failover_info(name)
                if fo:
                    info = {"reason": "failed_over",
                            "index": fo.get("index"),
                            "from_group": fo.get("from_group"),
                            "to_group": fo.get("to_group")}
                    break
    if info is None:
        info = getattr(tpu_search, "degraded_info", None)
    if info is None and getattr(tpu_search, "degraded_active", False):
        info = {"reason": "recovering"}
    if info:
        out["degraded"] = True
        out["degraded_reason"] = dict(info)


def _collapse_key(reader, hit, field: str):
    """The collapse key of one hit: first doc value of `field` (None =
    missing → the hit is not collapsed with anything)."""
    for v in reader.views:
        if v.segment.name == hit.ref.segment:
            col = v.segment.doc_values.get(field)
            if col is None:
                return None
            raw = col.values[hit.ref.ord]
            if col.kind == "ord":
                return None if raw < 0 else col.ord_terms[int(raw)]
            from elasticsearch_tpu.index.segment import MISSING_I64
            if col.kind == "i64":
                return None if raw == MISSING_I64 else int(raw)
            import math
            return None if math.isnan(raw) else float(raw)
    return None


def build_profile(query, shard_results, query_nanos, fetch_nanos
                  ) -> List[Dict[str, Any]]:
    """Reference-shaped per-shard profile section (search/profile/**):
    one entry per shard with the query tree timing and the fetch phase.
    The dense-mask engine runs the whole query as one kernel program per
    segment, so the breakdown reports that single executed node."""
    shards = []
    for name, shard_num, _reader, res in shard_results:
        qn = query_nanos.get((name, shard_num), 0)
        shards.append({
            "id": f"[{name}][{shard_num}]",
            "searches": [{
                "query": [{
                    "type": type(query).__name__,
                    "description": query.query_name(),
                    "time_in_nanos": qn,
                    "breakdown": {
                        "score": qn, "build_scorer": 0,
                        "create_weight": 0, "next_doc": 0, "advance": 0,
                        "match": 0,
                    },
                }],
                "rewrite_time": 0,
                "collector": [{
                    "name": "DenseMaskTopK",
                    "reason": "search_top_hits",
                    "time_in_nanos": qn,
                }],
            }],
            "aggregations": [],
            "fetch": {
                "type": "fetch",
                "description": "",
                "time_in_nanos": fetch_nanos.get((name, shard_num), 0),
            },
        })
    return shards


def _tpu_profile_section(tpu_search, sink: Dict[str, Any]
                         ) -> Dict[str, Any]:
    """The kernel-side profile story for one (index, query): what
    try_search measured for THIS query (variant, plan-cache outcome,
    host stage millis incl. the batch_wait split) reconciled with the
    service-wide device-stage distributions from StageTimes (per-query
    device time is not separable inside a shared train — the recent
    ring percentiles are the honest view)."""
    out = dict(sink)
    stages = getattr(tpu_search, "stages", None)
    if stages is not None:
        snap = stages.snapshot()
        out["device_stages"] = {
            name: st for name, st in snap.items()
            if "device_wait" in name or name == "batch_decode"}
    return out


def build_kernel_profile_shard(query, name: str, elapsed_s: float,
                               tpu: Dict[str, Any]) -> Dict[str, Any]:
    """One profile-tree shard entry for the kernel fast path, shaped
    like the planner's `build_profile` entries so tooling that walks
    `profile.shards` keeps working, plus the TPU section under "tpu"."""
    qn = int(elapsed_s * 1e9)
    return {
        "id": f"[{name}][kernel]",
        "searches": [{
            "query": [{
                "type": type(query).__name__,
                "description": query.query_name(),
                "time_in_nanos": qn,
                "breakdown": {"score": qn, "build_scorer": 0,
                              "next_doc": 0},
            }],
            "rewrite_time": 0,
            "collector": [{
                "name": "TpuKernelTopK",
                "reason": "search_top_hits",
                "time_in_nanos": qn,
            }],
        }],
        "aggregations": [],
        "fetch": {"type": "fetch", "description": "", "time_in_nanos": 0},
        "tpu": tpu,
    }


def _search_fast(indices: IndicesService, names: List[str],
                 query: dsl.QueryNode, tpu_search, *, size: int, from_: int,
                 min_score, source, t0: float,
                 version: bool = False,
                 seq_no_primary_term: bool = False,
                 ctx=None, profile: bool = False
                 ) -> Optional[Dict[str, Any]]:
    """Kernel-path query phase + columnar response assembly. Returns None
    when any target index's query can't lower (the whole request then
    runs on the planner so merge semantics stay uniform).

    Hit assembly is vectorized (VERDICT r3 #1b): external ids resolve via
    one fancy-index over the pack's id table, stored fields read straight
    off the pinned segments — no per-hit ShardHit/fetch-phase objects on
    the hot path."""
    import numpy as np

    k = from_ + size
    if k <= 0:
        return None
    if min_score is not None:
        # the kernel path counts totals before min_score filtering; the
        # planner applies it to the match set — decline so hits.total is
        # consistent across paths (ADVICE r2 low #3)
        return None
    per_index = []
    profile_entries: List[Dict[str, Any]] = []
    n_shards_total = 0
    for name in names:
        svc = indices.index(name)
        n_shards_total += len(svc.shards)
        q0 = time.perf_counter()
        sink: Optional[Dict[str, Any]] = {} if profile else None
        res = tpu_search.try_search(
            svc, query, k=k,
            timeout_s=ctx.remaining_s() if ctx is not None else None,
            profile_sink=sink)
        if res is None:
            return None
        q_elapsed = time.perf_counter() - q0
        tracing.record_stage("kernel.search", q_elapsed, index=name)
        if svc.search_slowlog.enabled:
            svc.search_slowlog.maybe_log(
                q_elapsed, "kernel",
                source={"query": query.query_name()},
                total_hits=res.total_hits)
        if profile:
            profile_entries.append(build_kernel_profile_shard(
                query, name, q_elapsed, _tpu_profile_section(
                    tpu_search, sink or {})))
        per_index.append((name, svc, res))

    t_asm = time.perf_counter()
    total = sum(r.total_hits for _, _, r in per_index)
    relation = ("gte" if any(r.total_relation == "gte"
                             for _, _, r in per_index) else "eq")
    if len(per_index) == 1:
        # single-index (the dominant case): the kernel result is already
        # merged best-first — the response window is a pair of array
        # slices, no merge pass at all. The hits block stays COLUMNAR
        # (a lazy ColumnarHits view): the REST layer serializes it
        # straight from the arrays, and no per-hit dict exists unless an
        # in-process consumer actually indexes into it.
        from elasticsearch_tpu.search.serializer import ColumnarHits
        name, svc, res = per_index[0]
        scores = res.scores[from_: from_ + size]
        rows = res.rows[from_: from_ + size]
        ords = res.ords[from_: from_ + size]
        if res.resident is None or len(scores) == 0:
            hits_json: Any = []
        else:
            hits_json = ColumnarHits(name, res.resident, scores, rows,
                                     ords, source, version,
                                     seq_no_primary_term)
        max_score = float(res.scores[0]) if len(res.scores) else None
    else:
        # cross-index merge: (score desc, index order, kernel rank) — the
        # same tie order as the planner path's merge, one lexsort
        all_scores = np.concatenate([r.scores for _, _, r in per_index]) \
            if per_index else np.empty(0, dtype=np.float32)
        tags = np.concatenate([np.full(len(r.scores), ii, dtype=np.int32)
                               for ii, (_, _, r) in enumerate(per_index)])
        ranks = np.concatenate([np.arange(len(r.scores), dtype=np.int32)
                                for _, _, r in per_index])
        order = np.lexsort((ranks, tags, -all_scores))
        window = order[from_: from_ + size]
        # assemble per index in one batched call each, then restore the
        # merged order (per-hit 1-element assembly re-creates the python
        # overhead this path removes)
        win_tags = tags[window]
        win_ranks = ranks[window]
        assembled: Dict[int, List[Dict[str, Any]]] = {}
        for ii, (name, svc, res) in enumerate(per_index):
            sel = win_ranks[win_tags == ii]
            if len(sel):
                assembled[ii] = _assemble_hits(
                    name, res.resident, res.scores[sel], res.rows[sel],
                    res.ords[sel], source, version, seq_no_primary_term)
        cursors = {ii: 0 for ii in assembled}
        merged: List[Dict[str, Any]] = []
        for ii in win_tags.tolist():
            merged.append(assembled[ii][cursors[ii]])
            cursors[ii] += 1
        # merged hits are materialized dicts, but their serialization
        # still batches through the response splicer (SplicedHits wraps,
        # dumps_response splices)
        from elasticsearch_tpu.search.serializer import SplicedHits
        hits_json = SplicedHits(merged)
        max_score = float(all_scores[order[0]]) if len(order) else None
    stages = getattr(tpu_search, "stages", None)
    if stages is not None:
        stages.add("assemble", time.perf_counter() - t_asm)
    out = {
        "took": int((time.perf_counter() - t0) * 1000),
        "timed_out": False,
        "_shards": {"total": n_shards_total, "successful": n_shards_total,
                    "skipped": 0, "failed": 0},
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": max_score,
                 "hits": hits_json},
    }
    if profile:
        out["profile"] = {
            "shards": profile_entries,
            "tpu": [e["tpu"] for e in profile_entries],
        }
    return out


def _assemble_hits(name: str, resident, scores, rows, ords, source,
                   version: bool, seq_no_primary_term: bool
                   ) -> List[Dict[str, Any]]:
    """Columnar window → response hit dicts. ids via one fancy-index;
    stored fields (when requested) read directly from the pinned
    segments the pack was scored against (same snapshot contract as the
    fetch phase). Materialized form — callers that mutate hits (the
    shard-group path tags `__shard`) or ship them over transport use
    this; the local REST fast path uses the lazy ColumnarHits view."""
    from elasticsearch_tpu.search.serializer import assemble_hits_list
    return assemble_hits_list(name, resident, scores, rows, ords, source,
                              version, seq_no_primary_term)


# ----------------------------------------------------------------------
# cross-node query_then_fetch (reference: the shard-level
# SearchTransportService hops — query + fetch executed on the node that
# owns each shard, merged by the coordinating node, SURVEY.md §3.3)
# ----------------------------------------------------------------------

def search_shard_group(indices: IndicesService,
                       targets: List[Tuple[str, int]],
                       body: Optional[Dict[str, Any]],
                       params: Optional[Dict[str, str]] = None,
                       tpu_search=None,
                       index_filters: Optional[Dict[str, List[dict]]]
                       = None) -> Dict[str, Any]:
    """Execute the query phase (+ eager fetch of the local window) over
    an explicit list of LOCAL (index, shard) targets, returning a
    JSON-serializable partial result the coordinating node merges with
    `merge_group_responses`. Aggregation partials travel as a pickled
    blob — inter-node RPC is a trusted channel exactly like the
    reference's native transport serialization."""
    from elasticsearch_tpu.search.query_phase import SearchContext
    params = params or {}
    query, aggs, body = parse_search_body(body or {})
    # the timeout travels with the body; each node enforces it locally
    # (coordinator-side cancellation bans are not propagated yet)
    ctx = SearchContext(parse_timeout_s(body, params))
    size = int(params.get("size", body.get("size", 10)))
    from_ = int(params.get("from", body.get("from", 0)))
    k = size + from_
    min_score = body.get("min_score")
    source = body.get("_source", True)
    from elasticsearch_tpu.search import sort as sort_mod
    sort_specs = sort_mod.parse_sort(body.get("sort"))
    search_after = body.get("search_after")
    want_version = bool(body.get("version"))
    want_seqno = bool(body.get("seq_no_primary_term"))
    highlight_spec = None
    fetch_source = source
    if body.get("highlight") is not None:
        from elasticsearch_tpu.search.highlight import HighlightSpec
        highlight_spec = HighlightSpec(body["highlight"])
        fetch_source = True if source is False else source

    by_index: Dict[str, List[int]] = {}
    for name, shard_num in targets:
        by_index.setdefault(name, []).append(shard_num)

    # knn winners resolved by route_search's candidate phase arrive as
    # the _knn_docs body key; wrap per shard exactly like search()
    group_knn: Optional[Dict[Tuple[str, int], List[Tuple[Any, float]]]] = None
    group_knn_only = False
    if body.get("_knn_docs") is not None:
        group_knn = decode_knn_docs(body["_knn_docs"])
        group_knn_only = "query" not in body

    # TPU fast path per index when the group covers every local shard of
    # that index (cluster allocation puts whole local shard sets in one
    # group, so this is the common case)
    shard_results = []
    agg_parts = []   # one partial per executed shard, hits or not
    group_failures: List[Dict[str, Any]] = []
    group_skipped = 0
    group_query_nanos: Dict[Tuple[str, int], int] = {}
    group_fetch_nanos: Dict[Tuple[str, int], int] = {}
    group_profile_entries: List[Tuple] = []
    fast_profile_entries: List[Dict[str, Any]] = []
    total = 0
    relation = "eq"
    for name, shard_nums in sorted(by_index.items()):
        svc = indices.index(name)
        eff_query = with_alias_filters(
            query, (index_filters or {}).get(name))
        used_fast = False
        if (tpu_search is not None and aggs is None and not sort_specs
                and search_after is None and k > 0 and min_score is None
                and group_knn is None
                and not body.get("rescore") and not body.get("collapse")
                and not (index_filters or {}).get(name)
                and set(shard_nums) == set(svc.shards.keys())):
            group_profile = bool(body.get("profile"))
            sink: Optional[Dict[str, Any]] = {} if group_profile else None
            q_fast0 = time.perf_counter()
            try:
                res = tpu_search.try_search(svc, query, k=k,
                                            timeout_s=ctx.remaining_s(),
                                            profile_sink=sink)
            except _NON_DEGRADABLE:
                raise
            except Exception:  # noqa: BLE001 — degrade to planner
                logger.warning("group kernel path failed; falling back "
                               "to the planner", exc_info=True)
                res = None
            if res is not None:
                used_fast = True
                if group_profile:
                    fast_profile_entries.append(build_kernel_profile_shard(
                        query, name, time.perf_counter() - q_fast0,
                        _tpu_profile_section(tpu_search, sink or {})))
                total += res.total_hits
                if getattr(res, "total_relation", "eq") == "gte":
                    relation = "gte"
                docs = _assemble_hits(name, res.resident, res.scores,
                                      res.rows, res.ords, source,
                                      want_version, want_seqno)
                shard_nums = (res.resident.row_shard[res.rows].tolist()
                              if docs else [])
                for rank, (doc, sn) in enumerate(zip(docs, shard_nums)):
                    doc["__shard"] = sn
                    shard_results.append(("__fast__", name, sn, rank, doc))
        if not used_fast:
            from elasticsearch_tpu.search.can_match import can_match
            group_rescore = None
            if body.get("rescore") is not None:
                from elasticsearch_tpu.search.rescore import parse_rescore
                group_rescore = parse_rescore(body["rescore"])
            group_collapse = (body.get("collapse") or {}).get("field") \
                if body.get("collapse") else None
            for shard_num in sorted(shard_nums):
                try:
                    fault_check(name, shard_num, "query")
                    shard = svc.shard(shard_num)
                    reader = shard.acquire_searcher()
                    if group_knn is not None:
                        sets = group_knn.get((name, shard_num), [])
                        if group_knn_only and not sets:
                            group_skipped += 1
                            continue
                        from elasticsearch_tpu.search.knn import \
                            wrap_query
                        shard_query = wrap_query(
                            None if group_knn_only else eff_query, sets)
                    else:
                        shard_query = eff_query
                        if not can_match(reader, eff_query, svc.mapper):
                            group_skipped += 1
                            continue
                    q0 = time.perf_counter()
                    k_shard = k
                    if group_rescore:
                        k_shard = max(k_shard, max(s.window_size
                                                   for s in group_rescore))
                    if group_collapse:
                        from elasticsearch_tpu.search.collapse import \
                            collapse_top_groups
                        from elasticsearch_tpu.search.query_phase import \
                            QuerySearchResult
                        pairs, total_sh = collapse_top_groups(
                            reader, shard_query, group_collapse, k)
                        res = QuerySearchResult(
                            [h for h, _ in pairs], total_sh,
                            pairs[0][0].score if pairs else None)
                        if aggs is not None:
                            res.aggregations = execute_query(
                                reader, shard_query, size=0, aggs=aggs,
                                ctx=ctx).aggregations
                    else:
                        res = execute_query(reader, shard_query,
                                            size=k_shard, from_=0,
                                            min_score=min_score,
                                            aggs=aggs,
                                            sort_specs=sort_specs or None,
                                            search_after=search_after,
                                            ctx=ctx)
                    if group_rescore:
                        from elasticsearch_tpu.search.rescore import \
                            rescore_shard_hits
                        res.hits = rescore_shard_hits(reader, res.hits,
                                                      group_rescore)
                    elapsed = time.perf_counter() - q0
                    fault_check(name, shard_num, "fetch")
                    f0 = time.perf_counter()
                    fetched = execute_fetch(reader, res.hits,
                                            fetch_source,
                                            version=want_version,
                                            seq_no_primary_term=want_seqno)
                except _NON_DEGRADABLE:
                    raise
                except Exception as e:  # noqa: BLE001 — captured per shard
                    logger.debug("group shard [%s][%d] failed",
                                 name, shard_num, exc_info=True)
                    indices.count_search_failure(name, shard_num)
                    tracing.add_event("shard.query_failed", index=name,
                                      shard=shard_num,
                                      error=f"{type(e).__name__}: {e}")
                    group_failures.append(
                        shard_failure_entry(name, shard_num, e))
                    continue
                group_query_nanos[(name, shard_num)] = int(elapsed * 1e9)
                tracing.record_stage("shard.query", elapsed, index=name,
                                     shard=shard_num)
                group_profile_entries.append((name, shard_num, None, res))
                if svc.search_slowlog.enabled:
                    svc.search_slowlog.maybe_log(
                        elapsed, shard_num, source=body,
                        total_hits=res.total_hits)
                total += res.total_hits
                if aggs is not None and res.aggregations is not None:
                    agg_parts.append(res.aggregations)
                group_fetch_nanos[(name, shard_num)] = int(
                    (time.perf_counter() - f0) * 1e9)
                for rank, (hit, doc) in enumerate(zip(res.hits, fetched)):
                    doc["_index"] = name
                    doc["_score"] = hit.score
                    if hit.sort_values is not None:
                        doc["sort"] = hit.sort_values
                    if group_collapse:
                        ck = _collapse_key(reader, hit, group_collapse)
                        if ck is not None:
                            doc["fields"] = {group_collapse: [ck]}
                    if highlight_spec is not None:
                        from elasticsearch_tpu.search.highlight import \
                            build_highlights
                        hl = build_highlights(query,
                                              doc.get("_source"),
                                              highlight_spec)
                        if hl:
                            doc["highlight"] = hl
                        if source is False:
                            doc.pop("_source", None)
                    doc["__shard"] = shard_num
                    shard_results.append((res, name, shard_num, rank, doc))

    # local pre-merge: keep only the node-level top-k (the coordinator
    # re-merges, so shipping more than k per node is pure waste)
    entries = []
    for res, name, shard_num, rank, doc in shard_results:
        if sort_specs:
            key = sort_mod.sort_key(sort_specs, doc.get("sort") or [])
        else:
            key = -(doc.get("_score") or 0.0)
        entries.append((key, name, shard_num, rank, doc))
    entries.sort(key=lambda t: t[:4])
    # under collapse, each shipped hit is already its shard's best per
    # key (collapse_top_groups), so k per node suffices
    hits = []
    for key, name, shard_num, rank, doc in entries[:k]:
        hits.append(doc)

    out: Dict[str, Any] = {
        "hits": hits, "total": total, "relation": relation,
        "timed_out": ctx.timed_out,
        "skipped": group_skipped,
        # shards counts only the copies that EXECUTED; failed copies
        # travel in "failures" so the coordinator can retry them on
        # another copy before counting them failed
        "shards": (len({(n, s) for n, s in targets})
                   - len(group_failures)),
        "max_score": (max((d.get("_score") or float("-inf")
                           for d in hits), default=None)
                      if not sort_specs and hits else None),
    }
    if group_failures:
        out["failures"] = group_failures
    if aggs:
        import base64
        import pickle
        out["aggs_blob"] = base64.b64encode(
            pickle.dumps(agg_parts)).decode("ascii")
    if body.get("profile"):
        out["profile_shards"] = build_profile(
            query, group_profile_entries, group_query_nanos,
            group_fetch_nanos) + fast_profile_entries
    if body.get("suggest") is not None:
        from elasticsearch_tpu.search.suggest import run_suggest
        # restrict to the group's ASSIGNED shards: unselected local
        # copies must not double-count in the cross-node merge
        out["suggest"] = run_suggest(
            indices, sorted(by_index.keys()), body["suggest"],
            shard_filter=by_index)
    return out


def merge_group_responses(groups: List[Dict[str, Any]],
                          body: Optional[Dict[str, Any]],
                          params: Optional[Dict[str, str]],
                          t0: float,
                          failed_shards: int = 0,
                          failures: Optional[List[Dict[str, Any]]] = None
                          ) -> Dict[str, Any]:
    """Coordinator-side reduce of `search_shard_group` partials into one
    reference-shaped _search response.

    `failures`: consolidated `_shards.failures[]` entries for copies
    that stayed failed AFTER the coordinator's failover attempts (the
    caller owns retry; this function only reports). `failed_shards`
    additionally counts failures with no entry (legacy callers)."""
    params = params or {}
    body = body or {}
    failures = list(failures or [])
    n_failed = failed_shards + len(failures)
    size = int(params.get("size", body.get("size", 10)))
    from_ = int(params.get("from", body.get("from", 0)))
    from elasticsearch_tpu.search import sort as sort_mod
    sort_specs = sort_mod.parse_sort(body.get("sort"))

    merged = []
    total = 0
    relation = "eq"
    n_shards = n_failed
    n_skipped = 0
    timed_out = False
    for gi, g in enumerate(groups):
        total += g["total"]
        n_shards += g.get("shards", 0)
        n_skipped += g.get("skipped", 0)
        if g.get("timed_out"):
            timed_out = True
        if g.get("relation") == "gte":
            relation = "gte"
        for rank, doc in enumerate(g["hits"]):
            if sort_specs:
                key = sort_mod.sort_key(sort_specs, doc.get("sort") or [])
            else:
                key = -(doc.get("_score") or 0.0)
            merged.append((key, doc.get("_index", ""),
                           doc.pop("__shard", 0), rank, doc))
    merged.sort(key=lambda t: t[:4])
    collapse_field = (body.get("collapse") or {}).get("field") \
        if body.get("collapse") else None
    if collapse_field:
        seen_keys = set()
        picked = []
        for entry in merged:
            doc = entry[4]
            key_vals = (doc.get("fields") or {}).get(collapse_field)
            if key_vals:
                if key_vals[0] in seen_keys:
                    continue
                seen_keys.add(key_vals[0])
            picked.append(doc)
            if len(picked) >= from_ + size:
                break
        window = picked[from_: from_ + size]
    else:
        window = [doc for _, _, _, _, doc in merged[from_: from_ + size]]

    if sort_specs:
        only_score = all(s.field == "_score" for s in sort_specs)
        max_score = None
        if only_score and merged:
            max_score = max((d.get("_score") or float("-inf")
                             for *_id, d in merged), default=None)
        if not only_score:
            for doc in window:
                doc["_score"] = None
    else:
        max_score = max((g.get("max_score") for g in groups
                         if g.get("max_score") is not None),
                        default=None)

    shards_json: Dict[str, Any] = {"total": n_shards,
                                   "successful": n_shards - n_failed,
                                   "skipped": n_skipped,
                                   "failed": n_failed}
    if failures:
        shards_json["failures"] = failures
    out: Dict[str, Any] = {
        "took": int((time.perf_counter() - t0) * 1000),
        "timed_out": timed_out,
        "_shards": shards_json,
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": max_score,
                 "hits": window},
    }

    if body.get("suggest") is not None:
        from elasticsearch_tpu.search.suggest import (merge_suggest,
                                                      parse_suggest)
        specs = parse_suggest(body["suggest"])
        out["suggest"] = merge_suggest(
            specs, [g.get("suggest") for g in groups
                    if g.get("suggest") is not None])

    aggs_spec = body.get("aggs") or body.get("aggregations")
    if aggs_spec:
        import base64
        import pickle

        from elasticsearch_tpu.search.aggregations import build_response
        aggs = parse_aggregations(aggs_spec)
        parts = []
        for g in groups:
            blob = g.get("aggs_blob")
            if blob:
                parts.extend(pickle.loads(base64.b64decode(blob)))
        reduced = (AggregatorFactories.reduce(parts) if parts
                   else aggs.empty())
        out["aggregations"] = build_response(aggs, reduced)
    if body.get("profile"):
        shards = [s for g in groups for s in g.get("profile_shards", [])]
        out["profile"] = {"shards": shards}
        tpu = [s["tpu"] for s in shards if "tpu" in s]
        if tpu:
            out["profile"]["tpu"] = tpu
    return out


def count(indices: IndicesService, index_expr: Optional[str],
          body: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    names, alias_filters = resolve_targets(indices, index_expr)
    query = dsl.parse_query((body or {}).get("query") or {"match_all": {}})
    total = 0
    n_shards = 0
    for name in names:
        svc = indices.index(name)
        eff_query = with_alias_filters(query, alias_filters.get(name))
        for shard_num, shard in sorted(svc.shards.items()):
            reader = shard.acquire_searcher()
            res = execute_query(reader, eff_query, size=0)
            total += res.total_hits
            n_shards += 1
    return {"count": total,
            "_shards": {"total": n_shards, "successful": n_shards,
                        "skipped": 0, "failed": 0}}
