"""Wire-level plan signatures for the multi-process serving front.

The serving fronts (``serving/front.py``) parse and canonicalize search
bodies on their own cores, then hand the batcher a signature alongside
the raw bytes; the batcher memoizes signature → parsed body so repeated
query shapes never pay ``json.loads`` on the device-owning process, and
the signature doubles as the stable half of the lowered-plan cache key
(``tpu_service.plan_key`` adds the mapping generation).

Deliberately import-light: front processes must never pull in JAX, so
this module depends on nothing but the stdlib. ``planner.py`` re-exports
it for batcher-side callers.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any

__all__ = ["canonical_body", "wire_plan_signature"]


def canonical_body(body: Any) -> str:
    """Key-order-insensitive canonical encoding of a query body: two
    requests that differ only in JSON key order or whitespace sign the
    same."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str)


def wire_plan_signature(index: str, body: Any) -> str:
    """Stable signature of (target index, canonical body) — the unit the
    front hands off and the batcher memoizes on."""
    h = hashlib.blake2b(digest_size=16)
    h.update(index.encode("utf-8", "replace"))
    h.update(b"\x00")
    h.update(canonical_body(body).encode("utf-8", "replace"))
    return h.hexdigest()
