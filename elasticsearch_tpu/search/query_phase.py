"""Query + fetch phases for one shard.

Reference: search/query/QueryPhase#executeInternal and
search/fetch/FetchPhase#execute (SURVEY.md §2.1#36, §3.3). The query phase
returns doc refs + scores only (no _source); the fetch phase resolves the
winners' stored fields — same two-phase contract as the reference so the
coordinator can fan out fetch to winning shards only.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.reader import ShardReader
from elasticsearch_tpu.ops import bm25
from elasticsearch_tpu.search import dsl
from elasticsearch_tpu.search.planner import SegmentQueryExecutor


@dataclasses.dataclass
class ShardDocRef:
    segment: str
    ord: int


@dataclasses.dataclass
class ShardHit:
    doc_id: str
    score: float
    ref: ShardDocRef


@dataclasses.dataclass
class QuerySearchResult:
    """Per-shard query-phase result (the QuerySearchResult analog):
    top-k (doc ref, score), total hits, per-shard agg partials — no
    _source yet."""
    hits: List[ShardHit]
    total_hits: int
    max_score: Optional[float]
    aggregations: Optional[Dict[str, Any]] = None  # name → InternalAggregation


def execute_query(reader: ShardReader, query: dsl.QueryNode, *,
                  size: int = 10, from_: int = 0,
                  min_score: Optional[float] = None,
                  aggs: Optional[Any] = None) -> QuerySearchResult:
    """aggs: an AggregatorFactories (see search/aggregations) collected
    under the query's match mask per segment, reduced across segments to
    one shard-level partial (reference: QueryPhase runs the collector
    chain once for topk + aggs, SURVEY.md §3.3)."""
    from elasticsearch_tpu.search.aggregations import (AggregatorFactories,
                                                       SegmentAggContext)

    k = size + from_
    per_segment: List[Tuple[int, np.ndarray, np.ndarray]] = []
    agg_parts: List[Dict[str, Any]] = []
    total = 0
    for idx, view in enumerate(reader.views):
        executor = SegmentQueryExecutor(reader, idx)
        mask, score = executor.execute(query)
        live = jnp.asarray(view.live_mask)
        final = bm25.mask_scores(score[None, :], mask[None, :], live)[0]
        total += int(jnp.sum(mask & live))
        if aggs:
            ctx = SegmentAggContext(reader, idx)
            agg_parts.append(aggs.collect(
                ctx, np.asarray(mask & live)))
        if k > 0:
            vals, idxs = bm25.topk(final[None, :], k=min(k, view.pack.d_pad))
            per_segment.append((idx, np.asarray(vals[0]), np.asarray(idxs[0])))
    # merge across segments: (score desc, segment ord asc, doc ord asc) —
    # the reference's tie-break order across leaf readers
    merged: List[Tuple[float, int, int]] = []
    for seg_idx, vals, idxs in per_segment:
        for v, d in zip(vals, idxs):
            if v == float("-inf"):
                continue
            if min_score is not None and v < min_score:
                continue
            merged.append((float(v), seg_idx, int(d)))
    merged.sort(key=lambda t: (-t[0], t[1], t[2]))
    window = merged[from_: from_ + size] if size > 0 else []
    hits = []
    for score, seg_idx, ord_ in window:
        seg = reader.views[seg_idx].segment
        hits.append(ShardHit(seg.doc_ids[ord_], score, ShardDocRef(seg.name, ord_)))
    max_score = merged[0][0] if merged else None
    shard_aggs = None
    if aggs:
        from elasticsearch_tpu.search.aggregations import AggregatorFactories
        shard_aggs = (AggregatorFactories.reduce(agg_parts)
                      if agg_parts else aggs.empty())
    return QuerySearchResult(hits, total, max_score, shard_aggs)


def execute_fetch(reader: ShardReader, hits: List[ShardHit],
                  source: Any = True) -> List[Dict[str, Any]]:
    """Fetch phase: resolve _source for winning docs.

    `source`: True | False | list of field-name prefixes (the _source
    filtering contract of the reference's fetch sub-phases)."""
    by_name = {v.segment.name: v.segment for v in reader.views}
    out = []
    for hit in hits:
        seg = by_name.get(hit.ref.segment)
        doc: Dict[str, Any] = {"_id": hit.doc_id, "_score": hit.score}
        if seg is not None and source is not False:
            src = seg.stored_source[hit.ref.ord]
            if isinstance(source, (list, tuple)):
                src = _filter_source(src or {}, list(source))
            doc["_source"] = src
        out.append(doc)
    return out


def _filter_source(src: Dict[str, Any], includes: List[str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, value in src.items():
        for inc in includes:
            if key == inc or inc.startswith(key + ".") or key.startswith(inc + "."):
                if isinstance(value, dict) and inc.startswith(key + "."):
                    sub = _filter_source(value, [inc[len(key) + 1:]])
                    if sub:
                        out[key] = sub
                else:
                    out[key] = value
                break
    return out
