"""Query + fetch phases for one shard.

Reference: search/query/QueryPhase#executeInternal and
search/fetch/FetchPhase#execute (SURVEY.md §2.1#36, §3.3). The query phase
returns doc refs + scores only (no _source); the fetch phase resolves the
winners' stored fields — same two-phase contract as the reference so the
coordinator can fan out fetch to winning shards only.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common import profiler
from elasticsearch_tpu.index.reader import ShardReader
from elasticsearch_tpu.ops import bm25
from elasticsearch_tpu.search import dsl
from elasticsearch_tpu.search.planner import SegmentQueryExecutor


# ---- fault-injection seam (testing/disruption.py) -------------------
# Hooks run at the top of each shard-level phase with (index, shard,
# phase) and raise to simulate that copy failing mid-search. Empty in
# production — the list is only populated by disruption schemes, so the
# hot-path cost is one falsy check.
_FAULT_HOOKS: List[Any] = []


def fault_check(index: str, shard: int, phase: str) -> None:
    """Give installed disruption schemes a chance to fail this shard's
    `phase` ("query" | "fetch"). Called by the coordinator right before
    it executes the phase, i.e. at the same point a real copy would
    throw (reference: the fault points MockTransportService exercises)."""
    if _FAULT_HOOKS:
        for hook in list(_FAULT_HOOKS):
            hook(index, shard, phase)


@dataclasses.dataclass
class ShardDocRef:
    segment: str
    ord: int


@dataclasses.dataclass
class ShardHit:
    doc_id: str
    score: float
    ref: ShardDocRef
    sort_values: Optional[List] = None  # set when sorting by fields


@dataclasses.dataclass
class QuerySearchResult:
    """Per-shard query-phase result (the QuerySearchResult analog):
    top-k (doc ref, score), total hits, per-shard agg partials — no
    _source yet."""
    hits: List[ShardHit]
    total_hits: int
    max_score: Optional[float]
    aggregations: Optional[Dict[str, Any]] = None  # name → InternalAggregation
    timed_out: bool = False


class SearchContext:
    """Deadline + cancellation carrier for one search request
    (reference: ContextIndexSearcher's timeout/cancellation runnables +
    CancellableTask, SURVEY.md §2.1#37). Checked cooperatively between
    per-segment kernel launches — the unit of work the engine schedules.

    Semantics match the reference: a passed DEADLINE degrades to partial
    results with "timed_out": true; a CANCELLED task raises
    TaskCancelledException out of the request."""

    def __init__(self, timeout_s: Optional[float] = None, task=None):
        import time as _time
        self.deadline = (_time.monotonic() + timeout_s
                         if timeout_s is not None else None)
        self.task = task
        self.timed_out = False

    def remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        import time as _time
        return max(0.0, self.deadline - _time.monotonic())

    def should_stop(self) -> bool:
        """True ⇒ stop collecting and return partial results."""
        if self.task is not None:
            self.task.ensure_not_cancelled()  # raises when cancelled
        if self.deadline is not None:
            import time as _time
            if _time.monotonic() >= self.deadline:
                self.timed_out = True
                return True
        return False


def execute_query(reader: ShardReader, query: dsl.QueryNode, *,
                  size: int = 10, from_: int = 0,
                  min_score: Optional[float] = None,
                  aggs: Optional[Any] = None,
                  sort_specs: Optional[List] = None,
                  search_after: Optional[List] = None,
                  ctx: Optional[SearchContext] = None) -> QuerySearchResult:
    """aggs: an AggregatorFactories (see search/aggregations) collected
    under the query's match mask per segment, reduced across segments to
    one shard-level partial (reference: QueryPhase runs the collector
    chain once for topk + aggs, SURVEY.md §3.3).
    sort_specs: parsed sort.SortSpec list → field-sorted results with
    per-hit sort values (reference: FieldSortBuilder, §2.1#50).
    ctx: deadline/cancellation checked between segments — a timeout
    returns the partial result with timed_out=True."""
    from elasticsearch_tpu.search.aggregations import (AggregatorFactories,
                                                       SegmentAggContext)

    # tag the thread's trace stage for the sampling profiler (no-op
    # set-emptiness check while the sampler is off)
    profiler.tag_stage("query_phase")
    if sort_specs:
        return _execute_sorted_query(reader, query, size=size, from_=from_,
                                     min_score=min_score, aggs=aggs,
                                     sort_specs=sort_specs,
                                     search_after=search_after, ctx=ctx)
    k = size + from_
    per_segment: List[Tuple[int, np.ndarray, np.ndarray]] = []
    agg_parts: List[Dict[str, Any]] = []
    total = 0
    timed_out = False
    for idx, view in enumerate(reader.views):
        if ctx is not None and ctx.should_stop():
            timed_out = True
            break
        executor = SegmentQueryExecutor(reader, idx)
        mask, score = executor.execute(query)
        live = jnp.asarray(view.live_mask)
        final = bm25.mask_scores(score[None, :], mask[None, :], live)[0]
        match = mask & live
        if min_score is not None:
            # min_score filters the MATCH SET — totals and aggs must
            # agree with the sorted path (the reference applies it
            # before counting; ADVICE r2: all paths report one total)
            match = match & (final >= min_score)
        total += int(jnp.sum(match))
        if aggs:
            agg_ctx = SegmentAggContext(reader, idx)
            agg_parts.append(aggs.collect(agg_ctx, np.asarray(match)))
        if k > 0:
            # bm25.topk runs the hierarchical per-block reduction over
            # the dense padded doc axis (round 8) — identical selection
            # and tie-breaks to full-width lax.top_k, cheaper at the
            # multi-million-doc segment widths this loop sees
            vals, idxs = bm25.topk(final[None, :], k=min(k, view.pack.d_pad))
            per_segment.append((idx, np.asarray(vals[0]), np.asarray(idxs[0])))
    # merge across segments: (score desc, segment ord asc, doc ord asc) —
    # the reference's tie-break order across leaf readers
    merged: List[Tuple[float, int, int]] = []
    for seg_idx, vals, idxs in per_segment:
        for v, d in zip(vals, idxs):
            if v == float("-inf"):
                continue
            if min_score is not None and v < min_score:
                continue
            merged.append((float(v), seg_idx, int(d)))
    merged.sort(key=lambda t: (-t[0], t[1], t[2]))
    window = merged[from_: from_ + size] if size > 0 else []
    hits = []
    for score, seg_idx, ord_ in window:
        seg = reader.views[seg_idx].segment
        hits.append(ShardHit(seg.doc_ids[ord_], score, ShardDocRef(seg.name, ord_)))
    max_score = merged[0][0] if merged else None
    shard_aggs = None
    if aggs:
        from elasticsearch_tpu.search.aggregations import AggregatorFactories
        shard_aggs = (AggregatorFactories.reduce(agg_parts)
                      if agg_parts else aggs.empty())
    return QuerySearchResult(hits, total, max_score, shard_aggs,
                             timed_out=timed_out)


def _execute_sorted_query(reader: ShardReader, query: dsl.QueryNode, *,
                          size: int, from_: int, min_score, aggs,
                          sort_specs: List, search_after,
                          ctx: Optional[SearchContext] = None
                          ) -> QuerySearchResult:
    """Field-sorted query phase: per segment, vectorized lexsort over the
    matching docs' sort keys (numeric values / keyword ordinals), then a
    cross-segment merge on python value tuples."""
    from elasticsearch_tpu.search import sort as sort_mod
    from elasticsearch_tpu.search.aggregations import (AggregatorFactories,
                                                       SegmentAggContext)

    k = size + from_
    agg_parts: List[Dict[str, Any]] = []
    total = 0
    timed_out = False
    merged: List[Tuple[Tuple, int, int, float, List]] = []
    for idx, view in enumerate(reader.views):
        if ctx is not None and ctx.should_stop():
            timed_out = True
            break
        executor = SegmentQueryExecutor(reader, idx)
        mask, score = executor.execute(query)
        live = jnp.asarray(view.live_mask)
        final_mask = np.asarray(mask & live)[: view.segment.num_docs]
        scores_np = np.asarray(
            bm25.mask_scores(score[None, :], mask[None, :], live)[0]
        )[: view.segment.num_docs]
        if min_score is not None:
            final_mask = final_mask & (scores_np >= min_score)
        total += int(final_mask.sum())
        if aggs:
            agg_ctx = SegmentAggContext(reader, idx)
            pad = np.zeros(view.pack.d_pad, dtype=bool)
            pad[: len(final_mask)] = final_mask
            agg_parts.append(aggs.collect(agg_ctx, pad))
        columns = sort_mod.segment_sort_values(reader, idx, sort_specs,
                                               scores_np)
        # one O(n) rank/adjust pass per column, shared by the cursor
        # mask and the lexsort keys
        ranks = [sort_mod.column_ranks(spec, col)
                 for spec, col in zip(sort_specs, columns)]
        if search_after is not None:
            final_mask = final_mask & sort_mod.after_mask(
                sort_specs, columns, search_after, ranks=ranks)
        ords = np.nonzero(final_mask)[0]
        if len(ords) == 0:
            continue
        # per-segment vectorized top-k (lexsort; strings via ordinals)
        keys = _lexsort_keys(ranks, ords)
        # np.lexsort: LAST key is primary → (tiebreak ord, ..., spec0)
        order = np.lexsort((ords,) + tuple(reversed(keys)))
        top_ords = ords[order[: k]] if k > 0 else ords[:0]
        # resolve values (keyword ordinals → terms) only for the winners
        for o in top_ords:
            vals = [col.resolve(int(o)) for col in columns]
            merged.append((sort_mod.sort_key(sort_specs, vals), idx, int(o),
                           float(scores_np[o]), vals))
    merged.sort(key=lambda t: (t[0], t[1], t[2]))
    window = merged[from_: from_ + size] if size > 0 else []
    hits = []
    for key, seg_idx, ord_, score_v, vals in window:
        seg = reader.views[seg_idx].segment
        hits.append(ShardHit(
            seg.doc_ids[ord_], score_v, ShardDocRef(seg.name, ord_),
            sort_values=[sort_mod.plain_value(v) for v in vals]))
    shard_aggs = None
    if aggs:
        shard_aggs = (AggregatorFactories.reduce(agg_parts)
                      if agg_parts else aggs.empty())
    # max_score is null under field sort (reference behavior without
    # track_scores)
    only_score = all(s.field == "_score" for s in sort_specs)
    max_score = (max((h.score for h in hits), default=None)
                 if only_score else None)
    return QuerySearchResult(hits, total, max_score, shard_aggs,
                             timed_out=timed_out)


def _lexsort_keys(ranks, ords):
    """Per-spec (missing_rank, adjusted_value) numeric key arrays over
    `ords`, direction-adjusted for np.lexsort (ascending) — sliced from
    the precomputed column_ranks arrays."""
    keys = []
    for rank, adj in ranks:
        keys.append(rank[ords])
        keys.append(adj[ords])
    return keys


def execute_fetch(reader: ShardReader, hits: List[ShardHit],
                  source: Any = True, *, version: bool = False,
                  seq_no_primary_term: bool = False) -> List[Dict[str, Any]]:
    """Fetch phase: resolve _source (and optionally _version /
    _seq_no+_primary_term from the per-doc metadata columns) for winners.

    `source`: True | False | list of field-name prefixes (the _source
    filtering contract of the reference's fetch sub-phases)."""
    profiler.tag_stage("fetch_phase")
    by_name = {v.segment.name: v.segment for v in reader.views}
    out = []
    for hit in hits:
        seg = by_name.get(hit.ref.segment)
        doc: Dict[str, Any] = {"_id": hit.doc_id, "_score": hit.score}
        if seg is not None and source is not False:
            src = seg.stored_source[hit.ref.ord]
            if isinstance(source, (list, tuple)):
                src = _filter_source(src or {}, list(source))
            doc["_source"] = src
        if seg is not None and version:
            doc["_version"] = int(seg.doc_versions[hit.ref.ord])
        if seg is not None and seq_no_primary_term:
            doc["_seq_no"] = int(seg.seq_nos[hit.ref.ord])
            doc["_primary_term"] = int(seg.primary_terms[hit.ref.ord])
        out.append(doc)
    return out


def filter_source(src: Dict[str, Any],
                  includes: List[str]) -> Dict[str, Any]:
    """Project a stored _source onto an includes list (dotted paths
    descend into objects). Shared by the planner fetch phase and the
    TPU columnar serializer."""
    out: Dict[str, Any] = {}
    for key, value in src.items():
        for inc in includes:
            if key == inc or inc.startswith(key + ".") or key.startswith(inc + "."):
                if isinstance(value, dict) and inc.startswith(key + "."):
                    sub = filter_source(value, [inc[len(key) + 1:]])
                    if sub:
                        out[key] = sub
                else:
                    out[key] = value
                break
    return out


#: back-compat alias (pre-existing callers import the underscored name)
_filter_source = filter_source
