"""Node — the composition root + HTTP server.

Reference: `node/Node` + `http/` (SURVEY.md §2.1#2/9, §3.1): constructs
every service, wires the REST controller, serves JSON over HTTP. The
reference's Netty pipeline becomes a stdlib ThreadingHTTPServer — the
data path's heavy work is on-device, so the host HTTP layer only needs to
parse/route (SURVEY.md §7.1: host is control plane).

Run: python -m elasticsearch_tpu.node --port 9200 --data-path /tmp/data
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.indices.service import IndexService, IndicesService
from elasticsearch_tpu.rest.controller import RestController


class Node:
    def __init__(self, data_path: str, *,
                 node_name: str = "node-1",
                 cluster_name: str = "elasticsearch-tpu",
                 settings: Optional[Settings] = None):
        # private copy — dynamic cluster settings mutate node.settings
        # and must never write through to the caller's object or the
        # shared EMPTY singleton
        self.settings = Settings((settings or Settings.EMPTY)
                                 .get_as_dict())
        # the node-config baseline dynamic settings recompute against:
        # clearing a cluster setting (null) reverts to this, not to
        # whatever value happened to be live
        self._base_settings = dict(self.settings.get_as_dict())
        # logging is part of node construction, not the CLI: embedded
        # users (bench, tests, Python API) get the same handlers/levels.
        # Owner-scoped so two embedded nodes don't reset each other.
        from elasticsearch_tpu.common.logging import configure
        configure(self.settings, owner=id(self))
        # plugins load BEFORE any service that consults their
        # registries (queries, processors, analyzers, engine factory)
        from elasticsearch_tpu.plugins import REGISTRY as _plugins
        _plugins.load_from_settings(self.settings)
        self.plugins = _plugins
        self.node_name = node_name
        self.node_id = _load_or_create_node_id(data_path, node_name)
        self.cluster_name = cluster_name
        self.cluster_uuid = uuid.uuid4().hex[:20]
        self.http_port = 0
        # cluster mode (multi-node over the transport layer); None ⇒ the
        # single-node paths in the REST actions
        self.cluster = None
        self.indices = IndicesService(data_path)
        from elasticsearch_tpu.tasks import TaskManager
        self.task_manager = TaskManager(self.node_id)
        from elasticsearch_tpu.search.contexts import SearchContextManager
        self.search_contexts = SearchContextManager()
        from elasticsearch_tpu.ingest import IngestService
        self.ingest = IngestService()
        self._load_ingest_pipelines(data_path)
        import os as _os

        from elasticsearch_tpu.snapshots import RepositoriesService
        self.repositories = RepositoriesService(
            _os.path.join(data_path, "_state", "repositories.json"))
        from elasticsearch_tpu.templates import TemplateService
        self.templates = TemplateService(
            _os.path.join(data_path, "_state", "index_templates.json"))
        # single-node dynamic cluster settings (cluster mode keeps them
        # in the published ClusterState instead); persistent ones
        # survive restart via the gateway file
        self.transient_settings: Dict[str, Any] = {}
        self.persistent_settings: Dict[str, Any] = \
            self._load_persistent_settings(data_path)
        if self.persistent_settings:
            # full recompute so persisted logger.* overrides are applied
            # to the logging config too, not just the settings map
            self.recompute_settings()
        # the TPU serving path: resident packs + micro-batched kernel
        # (disable with search.tpu_serving.enabled=false — the planner
        # path then serves everything)
        self.tpu_search = None
        if self.settings.get_bool("search.tpu_serving.enabled", True):
            from elasticsearch_tpu.common.breaker import \
                HierarchyCircuitBreakerService
            from elasticsearch_tpu.search.tpu_service import TpuSearchService
            self.breakers = HierarchyCircuitBreakerService(
                total_limit_bytes=self.settings.get_int(
                    "indices.breaker.total.limit_bytes", 8 << 30))
            self.tpu_search = TpuSearchService(
                breaker=self.breakers.breakers["hbm"],
                window_s=self.settings.get_float(
                    "search.tpu_serving.batch_window_seconds", 0.01),
                max_batch=self.settings.get_int(
                    "search.tpu_serving.max_batch", 128),
                batch_timeout_s=self.settings.get_float(
                    "search.tpu_serving.batch_timeout_seconds", 30.0),
                plan_cache_size=self.settings.get_int(
                    "search.tpu_serving.plan_cache_size", 2048),
                prewarm_concurrency=self.settings.get_int(
                    "search.tpu_serving.prewarm_concurrency", 4),
                # persistent XLA compile cache colocated with the node's
                # data (restart = cache replay, not recompilation);
                # ES_TPU_JAX_CACHE_DIR still overrides
                compile_cache_dir=self.settings.get(
                    "search.tpu_serving.compile_cache_dir",
                    _os.path.join(data_path, "jax_compile_cache")),
                # packed-key device kernels (PERF.md round 8): single
                # uint32 sort key + hierarchical top-k, with automatic
                # per-launch exact-f32 fallback when the pack/batch
                # overflows the packed layout
                packed_sort=self.settings.get_bool(
                    "search.tpu_serving.kernel.packed_sort", True),
                # compressed resident packs (PERF.md round 11): 16-bit
                # impact/doc/rank streams + residual tables + block-max
                # metadata + delta doc stream; ~3x fewer HBM bytes/doc
                # at identical result bits. Default ON since PR 15 (see
                # README "kernel variants" for the real-chip soak
                # status); incompressible packs fall back to raw
                # residency
                compressed_pack=self.settings.get_bool(
                    "search.tpu_serving.kernel.compressed_pack", True),
                # fused Pallas merge kernel (PR 15): the whole compressed
                # hot loop as one kernel — off by default until the
                # Mosaic soak on real chips lands; bit-identical and
                # typed-fallback-gated wherever it is enabled
                pallas=self.settings.get_bool(
                    "search.tpu_serving.kernel.pallas", False),
                # supervision: dispatches overdue past this deadline are
                # failed typed and trip batcher recovery (0 disables)
                launch_deadline_ms=self.settings.get_float(
                    "search.tpu_serving.launch_deadline_ms", 120_000.0),
                # device fault domains: wedge attribution → micro-probe
                # quarantine → partial-mesh N-1 serving → flap-damped
                # reintroduction
                device_health={
                    "enabled": self.settings.get_bool(
                        "search.tpu_serving.device_health.enabled", True),
                    "suspect_after": self.settings.get_int(
                        "search.tpu_serving.device_health.suspect_after",
                        2),
                    "probe_deadline_ms": self.settings.get_float(
                        "search.tpu_serving.device_health"
                        ".probe_deadline_ms", 5_000.0),
                    "reprobe_interval_seconds": self.settings.get_float(
                        "search.tpu_serving.device_health"
                        ".reprobe_interval_seconds", 30.0),
                    "hold_down_seconds": self.settings.get_float(
                        "search.tpu_serving.device_health"
                        ".hold_down_seconds", 60.0),
                    "reintroduce_after": self.settings.get_int(
                        "search.tpu_serving.device_health"
                        ".reintroduce_after", 3),
                    "drain_window_seconds": self.settings.get_float(
                        "search.tpu_serving.device_health"
                        ".drain_window_seconds", 2.0),
                    "shed_retry_after_seconds": self.settings.get_float(
                        "search.tpu_serving.device_health"
                        ".shed_retry_after_seconds", 5.0),
                },
                # pack-replica placement across device fault domains:
                # groups=1 (the default) keeps today's whole-mesh serving
                # byte-identical; groups>1 partitions the mesh and places
                # each resident pack on `replicas` distinct groups so a
                # chip loss fails over instead of shedding
                placement={
                    "groups": self.settings.get_int(
                        "search.tpu_serving.placement.groups", 1),
                    "replicas": self.settings.get_int(
                        "search.tpu_serving.placement.replicas", 1),
                },
                # streaming delta packs: append-only refreshes ride as
                # small device-resident deltas unioned into results; a
                # background compactor folds chains back into the
                # compressed base (disabled automatically under
                # placement — replica groups must stay byte-identical)
                delta={
                    "enabled": self.settings.get_bool(
                        "search.tpu_serving.delta.enabled", True),
                    "max_packs": self.settings.get_int(
                        "search.tpu_serving.delta.max_packs", 4),
                    "max_docs": self.settings.get_int(
                        "search.tpu_serving.delta.max_docs", 50_000),
                })
            # recovery's eager re-residency resolves index names through
            # the live indices service
            self.tpu_search.index_resolver = \
                lambda name: self.indices.indices.get(name)
        from elasticsearch_tpu.common.threadpool import ThreadPools
        self.thread_pools = ThreadPools(self.settings)
        # overload protection: memory-accounted write admission shared
        # by every replication stage, plus coordinator-side search load
        # shedding (reference: IndexingPressure + search backpressure)
        from elasticsearch_tpu.common.pressure import (
            IndexingPressure, SearchBackpressureService)
        self.indexing_pressure = IndexingPressure(self.settings)
        self.search_backpressure = SearchBackpressureService(
            self.settings, pressure=self.indexing_pressure,
            thread_pools=self.thread_pools,
            task_manager=self.task_manager)
        # per-tenant QoS: weighted shares carved from the SAME budgets
        # the node-level guards enforce. The default search budget is a
        # multiple of the search pool, so an unconfigured node (every
        # request the default tenant, share 1.0) behaves exactly as
        # before the carve existed.
        from elasticsearch_tpu.common.tenancy import TenantQuotaService
        search_pool = self.thread_pools.get("search")
        self.tenants = TenantQuotaService(
            self.settings,
            write_limit_bytes=self.indexing_pressure.limit,
            search_slots=max(
                32, 4 * (search_pool.size if search_pool is not None
                         else 8)))
        self.indexing_pressure.tenants = self.tenants
        self.search_backpressure.tenants = self.tenants
        if self.tpu_search is not None:
            self.tpu_search.batcher.tenants = self.tenants
        self.controller = RestController()
        self.controller.thread_pools = self.thread_pools
        # tracing: per-request root spans + propagation through the
        # coordinator fan-out and the TPU batch pipeline (sample_rate=0,
        # the default, keeps the hostpath allocation-free)
        from elasticsearch_tpu.common.tracing import Tracer
        self.tracer = Tracer(
            sample_rate=self.settings.get_float(
                "search.tracing.sample_rate", 0.0),
            max_spans=self.settings.get_int(
                "search.tracing.max_spans", 4096),
            slow_threshold_ms=self.settings.get_float(
                "search.tracing.slow_threshold_ms", 3000.0),
            node_name=node_name)
        self.controller.tracer = self.tracer
        # host/device profiling: continuous low-overhead flamegraph
        # sampler + bounded device trace sessions (ISSUE 6). Constructed
        # unconditionally so endpoints/metrics keep their shape; the
        # sampler thread only spawns when search.profiler.enabled.
        import os as _os

        from elasticsearch_tpu.common.profiler import Profiler
        self.profiler = Profiler(
            enabled=self.settings.get_bool("search.profiler.enabled",
                                           False),
            hz=self.settings.get_float("search.profiler.hz", 20.0),
            retention_s=self.settings.get_float(
                "search.profiler.retention_s", 300.0),
            device_dir=_os.path.join(data_path, "profile_sessions"))
        if self.tpu_search is not None:
            # read through the service each tick: supervision may swap
            # the batcher object on recovery
            self.profiler.sampler.timeline_source = \
                lambda: self.tpu_search.batcher.queue_depths()
        self.profiler.start()
        # flight recorder: process-wide causal event journal + incident
        # snapshots (ISSUE 18). Installed as the module-level recorder so
        # every subsystem's events.emit() lands here; off ⇒ near-free.
        from elasticsearch_tpu.common import events as _events
        self.flight_recorder = None
        if self.settings.get_bool("search.flight_recorder.enabled", True):
            self.flight_recorder = _events.FlightRecorder(
                _os.path.join(data_path, "flight"),
                max_events=self.settings.get_int(
                    "search.flight_recorder.max_events", 4096),
                disk_retention=self.settings.get_int(
                    "search.flight_recorder.disk_retention", 4),
                incident_dir=self.settings.get(
                    "search.flight_recorder.incident_dir",
                    _os.path.join(data_path, "flight", "incidents")),
                snapshot_events=self.settings.get_int(
                    "search.flight_recorder.snapshot_events", 256))
            _events.set_recorder(self.flight_recorder)
            self._wire_snapshot_sources()
            _events.emit("node.start", node=node_name,
                         node_id=self.node_id)
        # the multi-process serving front (started explicitly via
        # start_serving_fronts(); None ⇒ single-process serving)
        self.serving_front = None
        # off-interpreter coordinator merge: deferred k-way merges run
        # on the serving fronts when they exist, else on this node-local
        # worker pool; merge_pool_size=0 (the default) keeps the merge
        # inline on the dispatch thread
        from elasticsearch_tpu.search import merge as _merge
        self.merge_stats = _merge.MergeStats()
        self.merge_pool = None
        _pool_size = self.settings.get_int(
            "search.tpu_serving.merge_pool_size", 0)
        if _pool_size > 0:
            self.merge_pool = _merge.MergePool(_pool_size,
                                               stats=self.merge_stats)
        from elasticsearch_tpu.common.metrics import MetricsRegistry
        self.metrics = MetricsRegistry()
        self._register_metrics()
        self._register_actions()
        self._refresh_interval = self.settings.get_float(
            "index.refresh_interval_seconds", 1.0)
        self._sync_interval = self.settings.get_float(
            "index.translog.sync_interval_seconds", 5.0)
        self._refresher: Optional[threading.Timer] = None
        self._syncer: Optional[threading.Timer] = None
        self._closed = False

    def _wire_snapshot_sources(self) -> None:
        """Attach bounded context captures to the flight recorder:
        incident snapshots embed serving stats, degraded-mesh info and
        (when the sampler is live) the hottest folded stacks."""
        rec = self.flight_recorder

        def _tpu_stats():
            if self.tpu_search is None:
                return None
            return self.tpu_search.stats()

        def _degraded():
            if self.tpu_search is None:
                return None
            return self.tpu_search.degraded_info()

        def _stacks():
            s = self.profiler.sampler
            if not s.running:
                return None
            return [{"stack": stack, "count": count}
                    for stack, count in s.folded(top=15)]

        def _merge_pool():
            # merge-pool state rides every incident snapshot (a batcher
            # death with a backed-up merge queue is a different story
            # than one with an idle pool)
            pool = getattr(self, "merge_pool", None)
            if pool is not None:
                return pool.status()
            stats = getattr(self, "merge_stats", None)
            return stats.to_dict() if stats is not None else None

        rec.add_snapshot_source("tpu_stats", _tpu_stats)
        rec.add_snapshot_source("degraded_info", _degraded)
        rec.add_snapshot_source("profile_stacks", _stacks)
        rec.add_snapshot_source("merge_pool", _merge_pool)

    def _ingest_state_path(self) -> str:
        import os
        return os.path.join(self.indices.data_path, "_state",
                            "ingest_pipelines.json")

    def _cluster_settings_path(self) -> str:
        import os
        return os.path.join(self.indices.data_path, "_state",
                            "cluster_settings.json")

    def _load_ingest_pipelines(self, data_path: str) -> None:
        import logging
        try:
            with open(self._ingest_state_path(), "rb") as f:
                bodies = json.loads(f.read().decode("utf-8"))
        except FileNotFoundError:
            return
        except (OSError, json.JSONDecodeError) as e:
            logging.getLogger("elasticsearch_tpu.ingest").error(
                "could not read persisted ingest pipelines: %s", e)
            return
        if not isinstance(bodies, dict):
            logging.getLogger("elasticsearch_tpu.ingest").error(
                "persisted ingest pipelines file is not an object; "
                "ignoring it")
            return
        # lenient per pipeline: a bad entry quarantines itself (persist
        # keeps its body), never prevents startup or drops siblings
        self.ingest.sync(bodies)

    def persist_ingest_pipelines(self) -> None:
        import os

        from elasticsearch_tpu.index.translog import write_atomic
        p = self._ingest_state_path()
        os.makedirs(os.path.dirname(p), exist_ok=True)
        write_atomic(p, json.dumps(self.ingest.bodies(),
                                   sort_keys=True).encode("utf-8"))

    def _load_persistent_settings(self, data_path: str
                                  ) -> Dict[str, Any]:
        try:
            with open(self._cluster_settings_path(), "rb") as f:
                return json.loads(f.read().decode("utf-8"))
        except (OSError, json.JSONDecodeError):
            return {}

    def recompute_settings(self, persistent: Optional[dict] = None,
                           transient: Optional[dict] = None) -> None:
        """node.settings := base config + persistent + transient
        (reference precedence). Called on every dynamic change —
        including removals, which thereby revert to the base value."""
        if persistent is None:
            persistent = self.persistent_settings
        if transient is None:
            transient = self.transient_settings
        target = dict(self._base_settings)
        target.update(persistent)
        target.update(transient)
        self.settings.replace_all(target)
        from elasticsearch_tpu.common.logging import configure
        configure(self.settings, owner=id(self))

    def update_cluster_settings_local(self, persistent: dict,
                                      transient: dict) -> dict:
        """Single-node _cluster/settings PUT (reference semantics:
        validate against the dynamic registry, transient wins)."""
        import os

        from elasticsearch_tpu.cluster.service import (
            DYNAMIC_CLUSTER_PREFIXES, DYNAMIC_CLUSTER_SETTINGS)
        from elasticsearch_tpu.common.errors import IllegalArgumentException
        from elasticsearch_tpu.index.translog import write_atomic
        flat_p = Settings._flatten(persistent)
        flat_t = Settings._flatten(transient)
        for key in list(flat_p) + list(flat_t):
            if key in DYNAMIC_CLUSTER_SETTINGS or any(
                    key.startswith(p) for p in DYNAMIC_CLUSTER_PREFIXES):
                continue
            raise IllegalArgumentException(
                f"setting [{key}] is not dynamically updateable")
        for store, changes in ((self.persistent_settings, flat_p),
                               (self.transient_settings, flat_t)):
            for k, v in changes.items():
                if v is None:
                    store.pop(k, None)
                else:
                    store[k] = v
        self.recompute_settings()
        p = self._cluster_settings_path()
        os.makedirs(os.path.dirname(p), exist_ok=True)
        write_atomic(p, json.dumps(self.persistent_settings,
                                   sort_keys=True).encode("utf-8"))
        return {"acknowledged": True,
                "persistent": dict(self.persistent_settings),
                "transient": dict(self.transient_settings)}

    def start_cluster(self, *, host: str = "127.0.0.1",
                      transport_port: int = 0,
                      seed_hosts=None, initial_master_nodes=None) -> None:
        """Join/bootstrap a multi-node cluster (reference: discovery +
        coordination startup in Node#start)."""
        from elasticsearch_tpu.cluster.service import ClusterService
        # the gateway eagerly reopened every local shard as a primary;
        # in cluster mode the routing table decides which copies live
        # here and with which role — drop the objects (files stay) and
        # let the state applier recreate the right ones
        for svc in self.indices.indices.values():
            for shard in list(svc.shards.values()):
                shard.close()
            svc.shards.clear()
        self.cluster = ClusterService(
            self, host=host, transport_port=transport_port,
            seed_hosts=seed_hosts,
            initial_master_names=initial_master_nodes)
        self.cluster.start()

    def start_serving_fronts(self, *, host: str = "127.0.0.1",
                             count: Optional[int] = None) -> list:
        """Spawn the multi-process serving front: N HTTP front processes
        handing plan-signed requests to this (batcher) process over
        shared memory (serving/front.py). Returns the front HTTP ports;
        [] when search.tpu_serving.front_processes is 0 (the default —
        single-process serving via serve())."""
        if self.serving_front is not None:
            return self.serving_front.ports
        n = count if count is not None else self.settings.get_int(
            "search.tpu_serving.front_processes", 0)
        if n <= 0:
            return []
        profile_hz = 0.0
        if self.settings.get_bool("search.profiler.enabled", False):
            profile_hz = self.settings.get_float(
                "search.profiler.hz", 20.0)
        from elasticsearch_tpu.serving.front import FrontSupervisor
        self.serving_front = FrontSupervisor(
            self, n, host=host,
            slots=self.settings.get_int(
                "search.tpu_serving.front_slots", 64),
            slot_bytes=self.settings.get_int(
                "search.tpu_serving.front_slot_bytes", 256 << 10),
            timeout_s=self.settings.get_float(
                "search.tpu_serving.front_timeout_seconds", 45.0),
            wedge_timeout_s=self.settings.get_float(
                "search.tpu_serving.front_wedge_timeout_seconds", 30.0),
            profile_hz=profile_hz,
            memo_size=self.settings.get_int(
                "search.tpu_serving.plan_memo_size", 4096),
            hb_interval_s=self.settings.get_float(
                "search.tpu_serving.batcher_heartbeat_seconds", 1.0),
            batcher_stale_s=self.settings.get_float(
                "search.tpu_serving.batcher_stale_seconds", 5.0),
            orphan_grace_s=self.settings.get_float(
                "search.tpu_serving.front_orphan_grace_seconds", 10.0))
        return self.serving_front.ports

    def replicate(self, op: str, index: str, shard_num: int, doc_id: str,
                  source, result) -> None:
        """Primary→replica fan-out seam; no-op single-node (the write
        executors call this after every primary-phase apply)."""
        if self.cluster is not None:
            self.cluster.replicate_op(op, index, shard_num, doc_id,
                                      source, result)

    def _register_metrics(self) -> None:
        """Register every subsystem's metrics with the node-wide
        registry (scraped by GET /_prometheus/metrics). Dynamic families
        — per-pool, per-breaker, per-stage, per-shard — go through
        collectors so members created later still show up."""
        reg = self.metrics
        reg.set_help("threadpool.active",
                     "Requests currently executing in the pool")
        reg.set_help("threadpool.queue", "Requests waiting for a slot")
        reg.set_help("search.plan_cache.hits",
                     "Lowered-plan cache lookups served from cache")
        reg.set_help("transport.retries",
                     "Transport sends retried after a retryable failure")
        reg.set_help("kernel.variant",
                     "Device-kernel launches by (kernel, variant)")
        reg.set_help("pack.hbm_bytes",
                     "Resident-pack HBM bytes by (index, field, "
                     "component)")
        reg.set_help("pack.compression_ratio",
                     "Resident bytes / uncompressed-format bytes per "
                     "(index, field) pack")

        def _threadpools():
            for name, pool in self.thread_pools.pools.items():
                st = pool.stats()
                lb = {"pool": name}
                yield ("threadpool.threads", lb, st["threads"], "gauge")
                yield ("threadpool.queue_capacity", lb,
                       st["queue_size"], "gauge")
                yield ("threadpool.active", lb, st["active"], "gauge")
                yield ("threadpool.queue", lb, st["queue"], "gauge")
                yield ("threadpool.rejected", lb, st["rejected"],
                       "counter")
                yield ("threadpool.completed", lb, st["completed"],
                       "counter")
        reg.add_collector(_threadpools)

        def _breakers():
            svc = getattr(self, "breakers", None)
            if svc is None:
                return
            for name, st in svc.stats().items():
                lb = {"breaker": name}
                yield ("breaker.limit_bytes", lb,
                       st["limit_size_in_bytes"], "gauge")
                yield ("breaker.estimated_bytes", lb,
                       st["estimated_size_in_bytes"], "gauge")
                yield ("breaker.tripped", lb, st["tripped"], "counter")
        reg.add_collector(_breakers)

        def _tpu():
            svc = self.tpu_search
            if svc is None:
                return
            nl = {}
            yield ("search.tpu.served", nl, svc.served, "counter")
            yield ("search.tpu.fallback", nl, svc.fallback, "counter")
            yield ("search.tpu.timeouts", nl, svc.timeouts, "counter")
            yield ("search.tpu.kernel_breaker_open", nl,
                   1 if svc._tripped else 0, "gauge")
            yield ("search.tpu.batches_executed", nl,
                   svc.batcher.batches_executed, "counter")
            yield ("search.tpu.batched_queries", nl,
                   svc.batcher.queries_executed, "counter")
            plans = svc.plans.stats()
            yield ("search.plan_cache.size", nl, plans["size"], "gauge")
            for key in ("hits", "misses", "evictions", "invalidations"):
                yield (f"search.plan_cache.{key}", nl, plans[key],
                       "counter")
            packs = svc.packs.stats()
            yield ("search.pack_cache.resident", nl, packs["resident"],
                   "gauge")
            for key in ("hits", "misses", "stale_served"):
                yield (f"search.pack_cache.{key}", nl, packs[key],
                       "counter")
            # per-(index,field) resident-pack HBM breakdown: the
            # compressed-pack capacity win, scrapeable. `component`
            # splits the charge (resident = what the breaker holds,
            # raw = the uncompressed-format equivalent, block_meta /
            # residual = the pruning + exact-decode overheads).
            for pk, det in packs.get("packs", {}).items():
                index, _, field = pk.partition("/")
                lb = {"index": index, "field": field}
                for comp, key in (("resident", "hbm_bytes"),
                                  ("raw", "raw_bytes"),
                                  ("block_meta", "block_meta_bytes"),
                                  ("residual", "residual_bytes"),
                                  ("doc_base", "doc_base_bytes")):
                    yield ("pack.hbm_bytes", {**lb, "component": comp},
                           det.get(key, 0), "gauge")
                yield ("pack.compression_ratio", lb,
                       det.get("compression_ratio", 1.0), "gauge")
                # the bytes-war scoreboard (PR 15 acceptance: compressed
                # + delta packs sit at ≤ 6 B/posting)
                yield ("pack.hbm_bytes_per_posting", lb,
                       det.get("hbm_bytes_per_posting", 0.0), "gauge")
                yield ("pack.doc_delta", lb,
                       1 if det.get("doc_delta") else 0, "gauge")
            with svc._prewarm_lock:
                warm = dict(svc._prewarm_progress)
            yield ("search.tpu.prewarm_total", nl, warm["total"], "gauge")
            yield ("search.tpu.prewarm_done", nl, warm["done"], "gauge")
            depths = svc.batcher.queue_depths()
            yield ("search.tpu.queue_pending", nl, depths["pending"],
                   "gauge")
            yield ("search.tpu.queue_inflight", nl, depths["inflight"],
                   "gauge")
            yield ("search.tpu.pack_queues", nl, depths["queues"],
                   "gauge")
            from elasticsearch_tpu.search.tpu_service import (
                KERNEL_CONFIG, KERNEL_VARIANT_COUNTS)
            yield ("search.tpu.kernel_packed_sort", nl,
                   1 if KERNEL_CONFIG["packed_sort"] else 0, "gauge")
            yield ("search.tpu.kernel_compressed_pack", nl,
                   1 if KERNEL_CONFIG["compressed_pack"] else 0, "gauge")
            yield ("search.tpu.kernel_pallas", nl,
                   1 if KERNEL_CONFIG["pallas"] else 0, "gauge")
            # per-(kernel, variant) launch counts:
            # es_tpu_kernel_variant_total{kernel=...,variant=...}
            for labels, counter in KERNEL_VARIANT_COUNTS.items():
                yield ("kernel.variant", labels, counter)
            for stage, seconds, count, ring in svc.stages.metrics_view():
                lb = {"stage": stage}
                yield ("search.tpu.stage_seconds", lb, seconds, "counter")
                yield ("search.tpu.stage_operations", lb, count,
                       "counter")
                if ring is not None:
                    yield ("search.tpu.stage_latency_seconds", lb, ring,
                           "summary")
            # batcher supervision: launch watchdog + wedge/crash
            # recovery (metric OBJECTS yield so the completeness
            # traversal sees them as registered)
            wd = svc.watchdog
            yield ("watchdog.launches", nl, wd.c_launches, "counter")
            yield ("watchdog.wedges", nl, wd.c_wedges, "counter")
            yield ("watchdog.inflight", nl, wd.inflight(), "gauge")
            yield ("watchdog.deadline_ms", nl,
                   round(wd.deadline_s * 1e3, 1), "gauge")
            sup = svc.supervisor
            from elasticsearch_tpu.search.tpu_service import \
                _SUPERVISION_STATES
            yield ("recovery.recoveries", nl, sup.c_recoveries, "counter")
            yield ("recovery.degraded_served", nl, sup.c_degraded_served,
                   "counter")
            yield ("recovery.state", nl,
                   _SUPERVISION_STATES.get(sup.state, -1), "gauge")
            yield ("recovery.last_duration_seconds", nl,
                   sup.last_duration_s, "gauge")
            # device fault domains: per-device health state plus the
            # quarantine/probe/remesh lifecycle (metric OBJECTS yield
            # for the completeness traversal, same as the watchdog's)
            yield ("device.mesh_active", nl, sup.mesh_device_count,
                   "gauge")
            yield ("device.mesh_total", nl, sup.full_device_count,
                   "gauge")
            yield ("device.remeshes", nl, sup.c_remeshes, "counter")
            yield ("device.remesh_duration_seconds", nl,
                   sup.last_remesh_duration_s, "gauge")
            yield ("device.shed_packs", nl, len(svc.shed_keys()),
                   "gauge")
            health = svc.health
            if health is not None:
                yield ("device.probes", nl, health.c_probes, "counter")
                yield ("device.probe_failures", nl,
                       health.c_probe_failures, "counter")
                yield ("device.quarantines", nl, health.c_quarantines,
                       "counter")
                yield ("device.reintroductions", nl,
                       health.c_reintroductions, "counter")
                # es_tpu_device_health_state{device=} 0=healthy,
                # 1=suspect, 2=quarantined
                for dev_id, code in health.state_codes().items():
                    yield ("device.health_state",
                           {"device": str(dev_id)}, code, "gauge")
                # es_tpu_device_wedges_total{device=}: attributable
                # wedge counts per chip
                for labels, counter in health.c_device_wedges.items():
                    yield ("device.wedges", labels, counter)
            pl = svc.placement
            if pl is not None:
                # es_tpu_placement_*: fault-domain placement — group
                # inventory, replica failovers vs. shed (the drill's
                # zero-shed proof reads these two counters)
                yield ("placement.groups", nl, pl.num_groups, "gauge")
                yield ("placement.replicas", nl, pl.replicas, "gauge")
                yield ("placement.devices_active", nl,
                       pl.devices_active(), "gauge")
                yield ("placement.failovers", nl, pl.c_failovers,
                       "counter")
                yield ("placement.replacements", nl, pl.c_replacements,
                       "counter")
                yield ("placement.packs_shed", nl, pl.c_shed, "counter")
                for g in pl.groups():
                    gl = {"group": str(g.gid)}
                    yield ("placement.group_devices", gl,
                           len(g.active_ids), "gauge")
                    cache = svc.group_caches.get(g.gid)
                    yield ("placement.group_packs", gl,
                           len(cache.resident_keys())
                           if cache is not None else 0, "gauge")
                    yield ("placement.group_hbm_bytes", gl,
                           g.breaker.used, "gauge")
        reg.add_collector(_tpu)

        def _transport():
            # zeros when single-node: the family names stay stable
            # whether or not the node ever joined a cluster
            transport = getattr(self.cluster, "transport", None) \
                if self.cluster is not None else None
            nl = {}
            yield ("transport.rx", nl,
                   transport.rx_count if transport else 0, "counter")
            yield ("transport.tx", nl,
                   transport.tx_count if transport else 0, "counter")
            yield ("transport.retries", nl,
                   transport.retry_count if transport else 0, "counter")
            yield ("transport.evictions", nl,
                   transport.evict_count if transport else 0, "counter")
        reg.add_collector(_transport)

        def _search_failures():
            for (index, shard), counter in \
                    self.indices.search_failure_metrics():
                yield ("search.shard_failures",
                       {"index": index, "shard": shard}, counter)
        reg.add_collector(_search_failures)

        reg.set_help("indexing_pressure.current_bytes",
                     "In-flight write bytes held at a replication stage")
        reg.set_help("indexing_pressure.stage_bytes",
                     "Write bytes ever charged at a replication stage")
        reg.set_help("indexing_pressure.rejections",
                     "Write operations rejected by indexing pressure")
        reg.set_help("search.backpressure.shed",
                     "Stale search tasks cancelled under node duress")
        reg.set_help("search.backpressure.declined",
                     "Expensive searches declined under node duress")

        def _pressure():
            p = self.indexing_pressure
            current = p.current()
            totals = {"coordinating": (p.coordinating_total,
                                       p.coordinating_rejections),
                      "primary": (p.primary_total, p.primary_rejections),
                      "replica": (p.replica_total, p.replica_rejections)}
            for stage, (total, rejections) in totals.items():
                lb = {"stage": stage}
                yield ("indexing_pressure.current_bytes", lb,
                       current[stage], "gauge")
                yield ("indexing_pressure.stage_bytes", lb, total)
                yield ("indexing_pressure.rejections", lb, rejections)
            yield ("indexing_pressure.limit_bytes", {}, p.limit, "gauge")
            yield ("indexing_pressure.replica_limit_bytes", {},
                   p.replica_limit, "gauge")
            sb = self.search_backpressure
            yield ("search.backpressure.shed", {}, sb.shed)
            yield ("search.backpressure.declined", {}, sb.declined)
        reg.add_collector(_pressure)

        reg.set_help("tenant.search_inflight",
                     "Searches a tenant currently holds admission for")
        reg.set_help("tenant.search_admitted",
                     "Searches admitted under a tenant's share")
        reg.set_help("tenant.search_rejections",
                     "Searches 429'd by a tenant's admission share")
        reg.set_help("tenant.write_bytes_inflight",
                     "In-flight coordinating write bytes held per tenant")
        reg.set_help("tenant.write_bytes",
                     "Coordinating write bytes ever charged per tenant")
        reg.set_help("tenant.write_rejections",
                     "Writes 429'd by a tenant's indexing-pressure share")
        reg.set_help("tenant.weight", "Configured tenant admission weight")

        def _tenants():
            tq = self.tenants
            for tenant, use in tq.usage().items():
                lb = {"tenant": tenant}
                yield ("tenant.search_inflight", lb,
                       use["search_inflight"], "gauge")
                yield ("tenant.write_bytes_inflight", lb,
                       use["write_bytes"], "gauge")
                yield ("tenant.weight", lb, tq.weight(tenant), "gauge")
                yield ("tenant.search_cap", lb, tq.search_cap(tenant),
                       "gauge")
                yield ("tenant.write_cap_bytes", lb,
                       tq.write_cap_bytes(tenant), "gauge")
            for family, name in (
                    (tq.search_admitted, "tenant.search_admitted"),
                    (tq.search_rejections, "tenant.search_rejections"),
                    (tq.write_bytes_total, "tenant.write_bytes"),
                    (tq.write_rejections, "tenant.write_rejections")):
                for labels, metric in family.items():
                    yield (name, labels, metric)
        reg.add_collector(_tenants)
        reg.set_help("profiler.samples",
                     "Host sampling-profiler stack samples collected")
        reg.set_help("profiler.overhead_ratio",
                     "Fraction of wall time the sampler thread is busy")

        def _profiler():
            # plain-int/float gauges (no metric objects): the family
            # shape is stable whether or not the sampler is running
            s = self.profiler.sampler
            yield ("profiler.enabled", {}, 1 if s.running else 0, "gauge")
            yield ("profiler.samples", {}, s.samples_total, "counter")
            yield ("profiler.ticks", {}, s.ticks_total, "counter")
            yield ("profiler.retained_samples", {}, len(s._samples),
                   "gauge")
            yield ("profiler.overhead_ratio", {},
                   s.overhead_fraction(), "gauge")
            dev = self.profiler.device
            yield ("profiler.device_sessions", {}, dev.sessions_total,
                   "counter")
            yield ("profiler.device_active", {},
                   1 if dev.info()["active"] else 0, "gauge")

        reg.add_collector(_profiler)
        reg.set_help("events",
                     "Flight-recorder events emitted, by event type")
        reg.set_help("incidents",
                     "Incident snapshots captured, by trigger")
        reg.set_help("events.dropped",
                     "Flight-recorder events lost to emit failures")

        def _events():
            rec = self.flight_recorder
            if rec is None:
                return
            for labels, metric in rec.c_events.items():
                yield ("events", labels, metric, "counter")
            for labels, metric in rec.c_incidents.items():
                yield ("incidents", labels, metric, "counter")
            yield ("events.dropped", {}, rec.c_dropped, "counter")
            yield ("events.ring_size", {}, rec.ring_len(), "gauge")
        reg.add_collector(_events)
        reg.set_help("serving.fronts",
                     "Serving front processes currently alive")
        reg.set_help("serving.plan_memo.hits",
                     "Batcher body parses skipped via plan-signature memo")
        reg.set_help("serving.slots_reclaimed",
                     "Shared-memory slots reclaimed from dead fronts")

        def _serving():
            # supervisor counters + every front's shm-published registry
            # snapshot, each row tagged with its process role
            sup = self.serving_front
            if sup is None:
                return
            yield from sup.metric_rows()
        reg.add_collector(_serving)
        reg.set_help("merge.merges",
                     "Deferred k-way merges completed (pool or inline)")
        reg.set_help("merge.queue_depth",
                     "Merge-pool jobs queued and not yet picked up")
        reg.set_help("merge.latency",
                     "Merge execution seconds (k-way reduce only)")
        reg.set_help("merge.worker_restarts",
                     "Merge-pool workers respawned after dying")
        reg.set_help("merge.fallbacks",
                     "Pool merges that fell back to an inline merge")

        def _merge():
            # always present (zero-valued without a pool) so the
            # es_tpu_merge_* families never vanish from a scrape
            stats = self.merge_stats
            pool = self.merge_pool
            yield ("merge.merges", {}, stats.merges, "counter")
            yield ("merge.inline_merges", {}, stats.inline, "counter")
            yield ("merge.fallbacks", {}, stats.fallbacks, "counter")
            yield ("merge.worker_restarts", {}, stats.worker_restarts,
                   "counter")
            yield ("merge.latency", {}, stats.latency, "summary")
            yield ("merge.queue_depth", {},
                   pool.queue_depth() if pool is not None else 0, "gauge")
            yield ("merge.pool_size", {},
                   pool.size if pool is not None else 0, "gauge")
        reg.add_collector(_merge)
        reg.set_help("delta.packs",
                     "Device-resident delta packs currently chained")
        reg.set_help("delta.bytes",
                     "HBM bytes held by resident delta packs")
        reg.set_help("delta.appends",
                     "Delta packs built from append-only refreshes")
        reg.set_help("delta.compactions",
                     "Delta chains folded back into their base pack")
        reg.set_help("delta.compaction_failures",
                     "Compactions that failed (chain kept serving)")
        reg.set_help("delta.replayed_ops",
                     "Translog ops replayed for search visibility")
        reg.set_help("delta.search_visible_lag_seconds",
                     "Worst current indexed-to-searchable lag across shards")

        def _deltas():
            # always present (zero-valued with the delta path off) so
            # the es_tpu_delta_* families never vanish from a scrape
            svc = self.tpu_search
            ds = svc.delta_stats if svc is not None else None
            packs, nbytes = (svc.packs.delta_totals()
                             if svc is not None else (0, 0))
            replayed = ds.replayed_ops if ds is not None else 0
            lag = 0.0
            for index_service in self.indices.indices.values():
                for shard in index_service.shards.values():
                    replayed += shard.engine.replayed_ops
                    lag = max(lag, shard.engine.last_visible_lag_s)
            yield ("delta.packs", {}, packs, "gauge")
            yield ("delta.bytes", {}, nbytes, "gauge")
            yield ("delta.appends", {},
                   ds.appends if ds is not None else 0, "counter")
            yield ("delta.compactions", {},
                   ds.compactions if ds is not None else 0, "counter")
            yield ("delta.compaction_failures", {},
                   ds.compaction_failures if ds is not None else 0,
                   "counter")
            yield ("delta.replayed_ops", {}, replayed, "counter")
            yield ("delta.search_visible_lag_seconds", {}, lag, "gauge")
        reg.add_collector(_deltas)

    def _register_actions(self) -> None:
        from elasticsearch_tpu.rest.actions import (admin, aliases, cluster,
                                                    document, ingest,
                                                    introspect, search,
                                                    snapshots, tasks,
                                                    templates)
        for module in (document, search, admin, cluster, tasks, ingest,
                       snapshots, aliases, templates, introspect):
            module.register(self.controller, self)
        self.plugins.install_rest_handlers(self.controller, self)

    # ---------------- index helpers ----------------

    def create_index(self, name: str, settings: Settings,
                     mappings: Optional[dict]) -> IndexService:
        """Index creation applies the best-matching index template's
        defaults underneath the request (reference:
        MetadataCreateIndexService template application)."""
        from elasticsearch_tpu.templates import \
            compose_and_validate_creation
        flat, merged_mappings, aliases = compose_and_validate_creation(
            self.templates.templates, name, settings.get_as_dict(),
            mappings, self.indices.indices)
        svc = self.indices.create_index(name, Settings(flat),
                                        merged_mappings)
        for alias, props in aliases.items():
            self.indices.put_alias(name, alias, props)
        return svc

    def get_or_autocreate_index(self, name: str) -> IndexService:
        """Reference: auto-create on first doc (action.auto_create_index,
        default on) — templates apply to auto-created indices too."""
        if not self.indices.has_index(name):
            if not self.settings.get_bool("action.auto_create_index", True):
                from elasticsearch_tpu.common.errors import IndexNotFoundException
                raise IndexNotFoundException(f"no such index [{name}] and "
                                             f"auto-create is disabled")
            from elasticsearch_tpu.common.errors import \
                IndexAlreadyExistsException
            try:
                return self.create_index(name, Settings.EMPTY, None)
            except IndexAlreadyExistsException:
                # concurrent first-writes raced; the other one won
                return self.indices.index(name)
        return self.indices.index(name)

    # ---------------- background refresh (NRT cycle) ----------------

    def start_refresher(self) -> None:
        """The 1s refresh cycle (reference: IndexService#refreshTask §3.2)."""
        # refresh=wait_for blocks on the visibility checkpoint only when
        # this cycle is running (otherwise nothing would ever refresh —
        # the handler forces a refresh instead)
        self.refresher_active = True

        def tick():
            if self._closed:
                return
            for svc in list(self.indices.indices.values()):
                try:
                    svc.refresh()
                except Exception:  # noqa: BLE001 — background task
                    pass
            try:  # expire scroll/PIT contexts so idle nodes don't pin
                self.search_contexts.reap()
            except Exception:  # noqa: BLE001 — background task
                pass
            self._refresher = threading.Timer(self._refresh_interval, tick)
            self._refresher.daemon = True
            self._refresher.start()
        self._refresher = threading.Timer(self._refresh_interval, tick)
        self._refresher.daemon = True
        self._refresher.start()

        # the async-durability fsync cycle (reference: 5s translog sync
        # timer) — advances the persisted checkpoint for durability=async
        # shards and bounds the unpersisted-seqno backlog
        last_sync: Dict[str, float] = {}

        def sync_delay() -> float:
            # tick at the finest configured cadence so a per-index
            # index.translog.sync_interval_seconds SHORTER than the node
            # default is honored, not just longer ones
            delay = self._sync_interval
            for svc in list(self.indices.indices.values()):
                per = getattr(svc, "sync_interval_s", -1.0)
                if per > 0:
                    delay = min(delay, per)
            return max(0.05, delay)

        def sync_tick():
            if self._closed:
                return
            try:
                now = time.monotonic()
                for svc in list(self.indices.indices.values()):
                    per = getattr(svc, "sync_interval_s", -1.0)
                    interval = per if per > 0 else self._sync_interval
                    if now - last_sync.get(svc.name, 0.0) < interval - 1e-3:
                        continue
                    last_sync[svc.name] = now
                    for shard in list(svc.shards.values()):
                        try:
                            shard.engine.sync_translog()
                        except Exception:  # noqa: BLE001 — background task
                            pass
            finally:  # the cycle must survive any error
                self._syncer = threading.Timer(sync_delay(), sync_tick)
                self._syncer.daemon = True
                self._syncer.start()
        self._syncer = threading.Timer(sync_delay(), sync_tick)
        self._syncer.daemon = True
        self._syncer.start()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.refresher_active = False
        if self._refresher:
            self._refresher.cancel()
        if self._syncer:
            self._syncer.cancel()
        if self.serving_front is not None:
            # fronts stop accepting before the device path tears down
            self.serving_front.close()
            self.serving_front = None
        if self.merge_pool is not None:
            self.merge_pool.close()
            self.merge_pool = None
        if self.cluster is not None:
            self.cluster.close()
        if self.profiler is not None:
            self.profiler.close()
        if self.tpu_search is not None:
            self.tpu_search.close()
        if self.flight_recorder is not None:
            from elasticsearch_tpu.common import events as _events
            if _events.get_recorder() is self.flight_recorder:
                _events.set_recorder(None)
            self.flight_recorder.close()
        ccs_client = getattr(self, "_ccs_transport", None)
        if ccs_client is not None:
            ccs_client.close()
        self.indices.close()

    # ---------------- in-process dispatch (tests + http) ----------------

    def handle(self, method: str, path: str,
               params: Optional[Dict[str, str]] = None,
               body: Any = None, raw_body: bytes = b""):
        if body is None and raw_body:
            text = raw_body.decode("utf-8", errors="replace")
            if path.endswith(("/_bulk", "/_msearch")):
                body = text  # NDJSON bodies parse per line downstream
            elif text.strip():
                from elasticsearch_tpu.common.errors import ParsingException
                try:
                    body = json.loads(text)
                except json.JSONDecodeError as e:
                    return 400, {"error": {"type": "parsing_exception",
                                           "reason": str(e)}, "status": 400}
        pool = self.merge_pool
        if pool is None:
            return self.controller.dispatch(method, path, params, body,
                                            raw_body)
        # merge pool active: the dispatch may hand back a deferred
        # k-way merge descriptor; resolve it off this interpreter
        from elasticsearch_tpu.search import merge as merge_mod
        with merge_mod.deferring(True):
            status, payload = self.controller.dispatch(
                method, path, params, body, raw_body)
        if isinstance(payload, merge_mod.DeferredMerge):
            payload = pool.merge(payload.descriptor)
        return status, payload

    def merge_status(self) -> Dict[str, Any]:
        """The /_tpu/stats merge block: where deferred merges run and
        what they cost."""
        pool = self.merge_pool
        if pool is not None:
            return {"mode": "pool", **pool.status()}
        mode = "front" if self.serving_front is not None else "inline"
        return {"mode": mode, **self.merge_stats.to_dict()}


class _Handler(BaseHTTPRequestHandler):
    node: Node = None  # set by serve()
    protocol_version = "HTTP/1.1"

    def _do(self):
        parsed = urlparse(self.path)
        params = {k: v[0] if v else "" for k, v in
                  parse_qs(parsed.query, keep_blank_values=True).items()}
        # trace context arrives as an HTTP header; the controller reads
        # it from params (header wins over a query-param duplicate)
        traceparent = self.headers.get("traceparent")
        if traceparent:
            params["traceparent"] = traceparent
        # tenant identity arrives the same way (header wins; the
        # controller validates and binds it to the dispatch thread)
        tenant = self.headers.get("X-Tenant-Id")
        if tenant:
            params["tenant_id"] = tenant
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        status, payload = self.node.handle(self.command, parsed.path, params,
                                           None, raw)
        extra_headers = (payload.pop("_headers", None)
                         if isinstance(payload, dict) else None)
        if isinstance(payload, dict) and "_cat" in payload and len(payload) == 1:
            data = payload["_cat"].encode("utf-8")
            ctype = "text/plain; charset=UTF-8"
        elif isinstance(payload, str):
            # text endpoints (_nodes/hot_threads) respond as plain text
            data = payload.encode("utf-8")
            ctype = "text/plain; charset=UTF-8"
        else:
            # dumps_response renders embedded ColumnarHits blocks from
            # their device-result columns in one pass (no per-hit dicts
            # on the serving path); plain payloads serialize as before
            from elasticsearch_tpu.search.serializer import dumps_response
            data = dumps_response(payload).encode("utf-8")
            ctype = "application/json; charset=UTF-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("X-elastic-product", "Elasticsearch-TPU")
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _do

    def log_message(self, fmt, *args):  # quiet by default
        pass


def serve(node: Node, host: str = "127.0.0.1", port: int = 9200
          ) -> ThreadingHTTPServer:
    handler = type("BoundHandler", (_Handler,), {"node": node})
    server = ThreadingHTTPServer((host, port), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


def _load_or_create_node_id(data_path: str, node_name: str) -> str:
    """A node's identity must survive restarts (reference: NodeEnvironment
    node id persistence) so the cluster state keeps referring to it."""
    import os
    p = os.path.join(data_path, "_state", "node_id")
    try:
        with open(p, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        pass
    nid = uuid.uuid4().hex[:20]
    try:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "w", encoding="utf-8") as f:
            f.write(nid)
    except OSError:
        pass
    return nid


def _parse_hostport(s: str) -> tuple:
    s = s.strip()
    host, sep, port = s.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(
            f"--seed-hosts entry [{s}] must be host:port (e.g. "
            f"127.0.0.1:9300)")
    return (host or "127.0.0.1", int(port))


def main() -> None:
    parser = argparse.ArgumentParser(description="elasticsearch-tpu node")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--data-path", default="./data")
    parser.add_argument("--node-name", default="node-1")
    parser.add_argument("--transport-port", type=int, default=None,
                        help="enable cluster mode on this TCP port "
                             "(0 = ephemeral)")
    parser.add_argument("--seed-hosts", default="",
                        help="comma-separated host:port transport "
                             "addresses of seed nodes")
    parser.add_argument("--initial-master-nodes", default="",
                        help="comma-separated node NAMES forming the "
                             "bootstrap voting configuration")
    parser.add_argument("-E", action="append", default=[], metavar="K=V",
                        dest="settings", help="node setting override")
    args = parser.parse_args()
    overrides = dict(kv.split("=", 1) for kv in args.settings)
    node = Node(args.data_path, node_name=args.node_name,
                settings=Settings.of(overrides))
    node.http_port = args.port
    if args.transport_port is not None or args.seed_hosts:
        seeds = [_parse_hostport(s) for s in args.seed_hosts.split(",")
                 if s.strip()]
        masters = [m.strip() for m in args.initial_master_nodes.split(",")
                   if m.strip()] or [args.node_name]
        node.start_cluster(host=args.host,
                           transport_port=args.transport_port or 0,
                           seed_hosts=seeds, initial_master_nodes=masters)
        print(f"[{args.node_name}] transport on "
              f"{args.host}:{node.cluster.transport.port}")
    node.start_refresher()
    server = serve(node, args.host, args.port)
    print(f"[{args.node_name}] listening on http://{args.host}:{args.port}")
    front_ports = node.start_serving_fronts(host=args.host)
    if front_ports:
        print(f"[{args.node_name}] serving fronts on "
              + ", ".join(f"http://{args.host}:{p}" for p in front_ports))
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        node.close()


if __name__ == "__main__":
    main()
