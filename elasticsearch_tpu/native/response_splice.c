/* response_splice — assemble the hits-array JSON bytes from pre-encoded
 * columns without re-entering Python per hit.
 *
 * The serializer pre-encodes each column with ONE C-level json.dumps call
 * (ids as a string array, scores as a number array, index names as a
 * string array, per-hit residual fields as an object array).  This
 * splicer splits each encoded array into its top-level elements and
 * concatenates per-hit objects
 *
 *   {"_index":<name>,"_id":<id>,"_score":<score>[,<extras inner>]}
 *
 * byte-for-byte identical to json.dumps(hit_dict, separators=(",",":"))
 * of the materialized form, because every byte comes from a json.dumps
 * of the same value.  Inputs are ASCII (ensure_ascii=True is the
 * serializer's default), so no UTF-8 handling is needed.
 *
 * The element scanner is string-escape and bracket-depth aware: inside
 * an encoded JSON string a quote can only appear escaped, and commas
 * only separate top-level elements at depth 0 outside strings.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    const char *p;
    long len;
} span_t;

/* Split a compact JSON array into its top-level element spans.
 * Returns the element count, or -1 on malformed input / overflow. */
static int32_t scan_array(const char *s, span_t *elems, int32_t max_elems)
{
    const char *p = s;
    if (*p != '[')
        return -1;
    p++;
    if (*p == ']')
        return 0;
    int32_t count = 0;
    const char *start = p;
    int depth = 0, in_str = 0, esc = 0;
    for (;; p++) {
        char c = *p;
        if (!c)
            return -1; /* unterminated */
        if (in_str) {
            if (esc)
                esc = 0;
            else if (c == '\\')
                esc = 1;
            else if (c == '"')
                in_str = 0;
            continue;
        }
        if (c == '"') {
            in_str = 1;
        } else if (c == '{' || c == '[') {
            depth++;
        } else if (c == '}') {
            if (--depth < 0)
                return -1;
        } else if (c == ']') {
            if (depth == 0) {
                if (count >= max_elems)
                    return -1;
                elems[count].p = start;
                elems[count].len = p - start;
                return count + 1;
            }
            depth--;
        } else if (c == ',' && depth == 0) {
            if (count >= max_elems)
                return -1;
            elems[count].p = start;
            elems[count].len = p - start;
            count++;
            start = p + 1;
        }
    }
}

#define PUT(str, n)                                   \
    do {                                              \
        long _n = (n);                                \
        if (w + _n > cap) {                           \
            rc = -1;                                  \
            goto done;                                \
        }                                             \
        memcpy(out + w, (str), (size_t)_n);           \
        w += _n;                                      \
    } while (0)

/* Assemble the hits array.
 *   ids_json    compact JSON array of n encoded _id values
 *   scores_json compact JSON array of n encoded _score values
 *   names_json  compact JSON array of encoded _index names (deduped)
 *   name_idx    n indices into names_json's elements
 *   extras_json NULL, or compact JSON array of n objects holding each
 *               hit's residual fields ({} when none)
 * Writes the result into out (capacity cap); returns bytes written,
 * -1 when cap is too small (caller grows and retries), -2 on malformed
 * input (caller uses the Python fallback). */
long es_splice_hits(const char *ids_json, const char *scores_json,
                    const char *names_json, const int32_t *name_idx,
                    const char *extras_json, int32_t n,
                    char *out, long cap)
{
    if (n < 0)
        return -2;
    if (n == 0)
        return cap >= 2 ? (memcpy(out, "[]", 2), 2) : -1;
    long rc = -2;
    long w = 0;
    span_t *ids = malloc(sizeof(span_t) * (size_t)n);
    span_t *scores = malloc(sizeof(span_t) * (size_t)n);
    span_t *names = malloc(sizeof(span_t) * (size_t)n);
    span_t *extras = extras_json ? malloc(sizeof(span_t) * (size_t)n) : NULL;
    int32_t n_names;
    if (!ids || !scores || !names || (extras_json && !extras))
        goto done;
    if (scan_array(ids_json, ids, n) != n)
        goto done;
    if (scan_array(scores_json, scores, n) != n)
        goto done;
    n_names = scan_array(names_json, names, n);
    if (n_names <= 0)
        goto done;
    if (extras_json && scan_array(extras_json, extras, n) != n)
        goto done;
    PUT("[", 1);
    for (int32_t i = 0; i < n; i++) {
        int32_t ni = name_idx[i];
        if (ni < 0 || ni >= n_names) {
            rc = -2;
            goto done;
        }
        if (i)
            PUT(",", 1);
        PUT("{\"_index\":", 10);
        PUT(names[ni].p, names[ni].len);
        PUT(",\"_id\":", 7);
        PUT(ids[i].p, ids[i].len);
        PUT(",\"_score\":", 10);
        PUT(scores[i].p, scores[i].len);
        if (extras && extras[i].len > 2) {
            /* non-empty residual object: splice its inner bytes */
            PUT(",", 1);
            PUT(extras[i].p + 1, extras[i].len - 2);
        }
        PUT("}", 1);
    }
    PUT("]", 1);
    rc = w;
done:
    free(ids);
    free(scores);
    free(names);
    free(extras);
    return rc;
}
