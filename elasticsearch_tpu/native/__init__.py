"""Native (C) components of the runtime.

The compute path is JAX/XLA; these are the host-side hot loops where
the reference uses native code too (SURVEY.md: the runtime around the
device kernels is native). Libraries build lazily from the in-tree C
sources with the system compiler and cache next to them; every native
path has a pure-Python fallback, so a missing toolchain degrades
performance, never behavior."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger("elasticsearch_tpu.native")

_HERE = os.path.dirname(__file__)
_LOCK = threading.Lock()
_LIBS = {}


def load(name: str):
    """dlopen `<name>.so`, building it from `<name>.c` on first use.
    Returns None when the build fails (callers use their fallback)."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_HERE, f"{name}.c")
        so = os.path.join(_HERE, f"{name}.so")
        lib = None
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                tmp = so + ".tmp"
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True, timeout=60)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except Exception as exc:  # noqa: BLE001 — perf path only
            logger.warning("native [%s] unavailable (%s); using the "
                           "python fallback", name, exc)
            lib = None
        _LIBS[name] = lib
        return lib


def bind(lib_name: str, symbol: str, restype, argtypes):
    """load() + bind one symbol's ctypes signature; None when the
    native library is unavailable (callers use their Python fallback)."""
    lib = load(lib_name)
    if lib is None:
        return None
    fn = getattr(lib, symbol)
    fn.restype = restype
    fn.argtypes = argtypes
    return fn
