/* fast_tokenize — C implementation of the standard-analyzer hot loop.
 *
 * Replaces `_WORD_RE.findall(text)` + per-token str.lower() for ASCII
 * text (the overwhelming case for log/search corpora). Semantics match
 * analyzers.standard_tokenize + the lowercase filter:
 *   token := \w+([.']\w+)*  over ASCII, lowercased, '_' stripped,
 *   overlong tokens punted.
 * Non-ASCII or pathological input returns -1 and the caller falls back
 * to the Python regex path, so Unicode behavior stays byte-identical
 * with the pure Python analyzer.
 *
 * Output: tokens written into `out` separated by '\n' (which can never
 * appear inside a token), so Python materializes the token list with a
 * single C-speed decode+split. *out_len receives the byte length.
 * Returns the token count, -1 for fallback, -2 when out_cap is too
 * small (caller retries with a larger buffer).
 */

#include <stddef.h>

static int is_word(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z')
        || (c >= '0' && c <= '9') || c == '_';
}

static unsigned char lower(unsigned char c) {
    return (c >= 'A' && c <= 'Z') ? (unsigned char)(c + 32) : c;
}

long fast_tokenize_ascii(const unsigned char *text, long n,
                         long max_token_length,
                         unsigned char *out, long out_cap,
                         long *out_len) {
    long i = 0, ntok = 0, w = 0;
    for (long k = 0; k < n; k++) {
        if (text[k] >= 0x80) return -1;
    }
    while (i < n) {
        if (!is_word(text[i])) { i++; continue; }
        long start = i;
        while (i < n) {
            if (is_word(text[i])) { i++; continue; }
            /* [.'] joins only between word chars */
            if ((text[i] == '.' || text[i] == '\'')
                    && i + 1 < n && is_word(text[i + 1])) {
                i += 2;
                continue;
            }
            break;
        }
        long tok_begin = w;
        if (ntok > 0) {
            if (w >= out_cap) return -2;
            out[w++] = '\n';
            tok_begin = w;
        }
        for (long k = start; k < i; k++) {
            unsigned char c = text[k];
            if (c == '_') continue;
            if (w >= out_cap) return -2;
            out[w++] = lower(c);
        }
        if (w == tok_begin) {          /* all-underscore token: drop */
            w = (ntok > 0) ? w - 1 : w; /* and its separator */
            continue;
        }
        if (w - tok_begin > max_token_length) {
            return -1;                  /* overlong: Python splits these */
        }
        ntok++;
    }
    *out_len = w;
    return ntok;
}

/* murmur3_x86_32(seed 0) over a byte buffer — the routing hash
 * (Murmur3HashFunction over UTF-16LE code units; the Python caller
 * encodes). Returns the SIGNED i32 value, matching the pure-Python
 * implementation in indices/service.py bit for bit. */
#include <stdint.h>

int32_t murmur3_32(const unsigned char *data, long n) {
    const uint32_t c1 = 0xCC9E2D51u, c2 = 0x1B873593u;
    uint32_t h1 = 0;
    long nblocks = n & ~3L;
    for (long i = 0; i < nblocks; i += 4) {
        uint32_t k1 = (uint32_t)data[i] | ((uint32_t)data[i + 1] << 8)
            | ((uint32_t)data[i + 2] << 16) | ((uint32_t)data[i + 3] << 24);
        k1 *= c1;
        k1 = (k1 << 15) | (k1 >> 17);
        k1 *= c2;
        h1 ^= k1;
        h1 = (h1 << 13) | (h1 >> 19);
        h1 = h1 * 5u + 0xE6546B64u;
    }
    uint32_t k1 = 0;
    switch (n & 3) {
    case 3: k1 ^= (uint32_t)data[nblocks + 2] << 16; /* fall through */
    case 2: k1 ^= (uint32_t)data[nblocks + 1] << 8;  /* fall through */
    case 1:
        k1 ^= (uint32_t)data[nblocks];
        k1 *= c1;
        k1 = (k1 << 15) | (k1 >> 17);
        k1 *= c2;
        h1 ^= k1;
    }
    h1 ^= (uint32_t)n;
    h1 ^= h1 >> 16;
    h1 *= 0x85EBCA6Bu;
    h1 ^= h1 >> 13;
    h1 *= 0xC2B2AE35u;
    h1 ^= h1 >> 16;
    return (int32_t)h1;
}
