"""Impact-sorted-merge retrieval kernel — the TPU-native hot path.

Replaces the reference's per-segment postings traversal (SURVEY.md §3.3:
BulkScorer loop → BM25Scorer → TopScoreDocCollector) with a formulation
built from TPU-fast primitives only (measured on v5e: XLA scatter ≈ 10M
updates/s — unusable; sort/top_k/contiguous-slice ≈ memory-bandwidth):

  1. Eager impacts (BM25S-style, PAPERS.md): at pack-build time each
     posting stores  impact = tf / (tf + k1·(1 − b + b·dl/avgdl))  so
     query-time scoring is one multiply by the term's idf·(k1+1)·boost.
  2. Chunked slot gather: each query term's postings row is split into
     chunks of ≤ L_c (static bucket); a chunk = one (start, length, weight,
     term-id) slot. vmapped dynamic_slice → contiguous DMA, no gather.
  3. One stable sort of [R, T·L_c] by doc id — the multi-way postings merge
     (ConjunctionDISI/BooleanScorer analog) as a single sort.
  4. Windowed same-key sum: a doc appears in at most T slots, so the
     segmented sum over equal-doc runs is a T-tap shifted add — no
     associative_scan (tuple-carry scans blow up TPU compile time).
  5. run-end mask + lax.top_k over the sparse candidate axis (size T·L_c,
     NOT the doc axis) — top-1000 never touches a dense [D] array.

Semantics per row: OR-of-slots with msm support. The clause count per doc
is the equal-doc run length, which is exact because each slot holds a doc
at most once (postings rows have unique docs, and chunks of one term
partition its row). Ties break like Lucene: equal scores → smaller doc id
(sorted axis + top_k's earliest-index-wins).

Packed-key variant (variant="packed", PERF.md round 8): the merge sort
dominates kernel time and is memory-bandwidth-bound, so instead of
sorting a (docs int32, impacts f32) key+value PAIR, each lane packs
  key = doc_id << 16  |  monotone 16-bit impact code
into ONE uint32 and the sort moves half the bytes. The code is the top
16 bits of the f32 bit pattern (bf16-style truncation) — order-preserving
for non-negative floats, so run structure, run lengths (msm counts) and
totals are exact; only the impact VALUES are approximate. Top candidates
are then selected hierarchically (per-block top-k' + merge instead of one
full-width top_k over T*L_c) and re-scored in exact f32 by binary-searching
each candidate in the doc-sorted chunks — summed in the reference
variant's exact order, so returned scores, doc ids, tie-breaks and totals
are bit-identical to variant="ref". Requires packable() inputs (doc ids
< 2**16, sane non-negative weights); the serving stack checks that at
lowering time and falls back to "ref" otherwise.

Compressed-pack variants (variant="compressed"/"compressed_exact", PR 8):
the RESIDENT arrays themselves are quantized — three u16 streams
(compress_flat): doc ids, monotone VALUE codes (impact_code16 of each
impact — collisions between near-equal impacts are fine, the codes only
feed lower bounds), and per-term RANK codes (1-based index of the
posting's impact in its term's ascending distinct-impact table — 0 marks
tombstone-zeroed postings). 6 bytes/posting replaces the 16 bytes of the
doc-sorted (int32, f32) pair plus the impact-sorted copy. The exact-f32
rescore survives the f32 arrays' removal by reading each term's small
RESIDUAL TABLE (its sorted distinct positive impacts): the rank found at
a candidate's posting position indexes the bit-exact f32 impact directly.
"compressed" runs the packed single-key pipeline on the decoded
lower-bound value codes and rescores through the residual tables;
"compressed_exact" decodes every lane to exact f32 first and runs the
reference pipeline — the automatic fallback when the batch weights break
the monotone-lower-bound guarantee (packable()), exact for ANY weights.
Alongside the streams, per-128-lane BLOCK MAX codes (block-max WAND /
BM25S eager elimination) let the "compressed" kernel carry a running
top-k threshold: a 128-lane group whose maximum possible weighted
contribution (its block-max upper bound plus every other slot's window
upper bound) cannot reach the k-th best lower bound already achieved is
masked out before the sort. Skipping applies to rows with min_count ≤ 1;
totals-returning launches (a skipped doc is still a match) get their
exact TotalHits from a dedicated PRE-skip count sort — one u32 key of
(doc id << 1 | positive-code bit) — so track_total_hits queries ride
the skip path too instead of forcing full evaluation.

Delta doc stream (PR 15, the last of the bytes war): when every aligned
128-lane block of a pack's doc stream spans ≤ 255 doc ids
(delta_doc_reason), the resident u16 doc stream is replaced by a u8
DELTA stream plus one u16 per-block BASE (the block's minimum doc id),
decoded in-kernel: lane doc = base[(dlo + lane) // 128] + delta. That
takes the doc stream from 2 B to ~1.02 B per posting — resident packs
drop under 6 B/posting. The exact-rescore binary search decodes the
same way through per-slot (dbs, dlo) block cursors, so results remain
bit-identical; shards whose streams overflow the u8 span keep the plain
u16 doc format (typed per-pack gate, like compress_reason).

Pallas fused variant (variant="pallas"): the whole hot loop — phase-A
posting gather from the compressed streams, packed single-key merge,
block-max skip branch and per-block top-k — as ONE Pallas kernel
(ops/pallas_merge.py), gridded per row, carrying the running top-k
threshold inside the kernel instead of a separate masking pass. On
non-TPU backends it runs under interpret=True and is bit-identical to
variant="compressed" by construction; unsupported shapes fall back
typed through planner.choose_kernel_variant like every other gate.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = float("-inf")

#: doc-id field width of the packed sort key: doc ids (including the
#: d_pad sentinel) must be < 2**16 for the packed variant to apply
PACKED_DOC_LIMIT = 1 << 16

#: positive slot weights outside this range route to the exact-f32
#: fallback: below the floor a real match's weighted impact could
#: truncate to code 0 (dropping it from totals), above the ceiling the
#: quantized sums lose the ordering guarantees the rescore slack assumes
PACKED_WEIGHT_MIN = 1e-12
PACKED_WEIGHT_MAX = 1e30

KERNEL_VARIANTS = ("ref", "packed", "compressed", "compressed_exact",
                   "pallas")

#: variants that read the compressed resident streams (16-bit doc ids +
#: 16-bit impact codes + residual tables) instead of the raw pair;
#: "pallas" is the fused-kernel spelling of "compressed" (same operands,
#: same packable() requirement, bit-identical results)
COMPRESSED_VARIANTS = ("compressed", "compressed_exact", "pallas")

#: block-max metadata granularity: one max-impact code per this many
#: postings lanes (the TPU lane width — a group of lanes the sort would
#: load together anyway, and the future Pallas fused merge's tile unit)
COMPRESSED_BLOCK = 128

#: per-term rank codes are u16 with 0 reserved for "no impact", so a
#: term may have at most this many distinct positive impact values
COMPRESSED_RANK_LIMIT = (1 << 16) - 1


def impact_code16(x: jax.Array) -> jax.Array:
    """Monotone 16-bit code of a non-negative finite f32: the top 16
    bits of its bit pattern (bf16-style truncation). Order-preserving —
    x <= y implies code(x) <= code(y) — and decode_code16(code(x)) is a
    lower bound of x, so quantized run totals never overshoot."""
    return jax.lax.bitcast_convert_type(x, jnp.uint32) >> 16


def decode_code16(code: jax.Array) -> jax.Array:
    """Inverse of impact_code16 up to truncation: the largest f32 whose
    code equals `code` rounds down to this value (zero low bits)."""
    return jax.lax.bitcast_convert_type(
        (code << 16).astype(jnp.uint32), jnp.float32)


def impact_code16_np(x: np.ndarray) -> np.ndarray:
    """Host-side impact_code16: uint16 codes of non-negative f32s."""
    flat = np.ascontiguousarray(x, dtype=np.float32)
    return (flat.view(np.uint32) >> 16).astype(np.uint16)


def decode_code16_np(code: np.ndarray) -> np.ndarray:
    """Host-side decode_code16: lower-bound f32 of each uint16 code."""
    return (np.asarray(code).astype(np.uint32) << 16).view(np.float32)


def _posting_terms(row_starts: np.ndarray, n: int) -> np.ndarray:
    """Term id per flat posting position. Positions past the last row
    (the CHUNK_CAP slack tail) get the one-past-the-end id — they carry
    impact 0 and never produce residual entries."""
    rs = np.asarray(row_starts, dtype=np.int64)
    counts = np.diff(rs)
    terms = np.repeat(np.arange(counts.size, dtype=np.int64), counts)
    if terms.size < n:
        terms = np.concatenate(
            [terms, np.full(n - terms.size, counts.size, dtype=np.int64)])
    return terms[:n]


def compress_reason(flat_docs: np.ndarray, flat_impact: np.ndarray,
                    row_starts: np.ndarray, d_pad: int) -> Optional[str]:
    """Why this shard's flats can NOT take the compressed resident
    format — None means compressible. The gates guarantee the u16
    streams lose nothing the kernel needs: doc ids (and the d_pad
    sentinel) must fit 16 bits, every positive impact needs a nonzero
    16-bit VALUE code (else it would vanish from quantized run totals),
    and no term may exceed the 16-bit RANK space of distinct positive
    impacts (else the exact-decode rank stream would overflow)."""
    if d_pad >= PACKED_DOC_LIMIT:
        return (f"d_pad {d_pad} does not fit the 16-bit doc stream "
                f"(limit {PACKED_DOC_LIMIT})")
    imp = np.asarray(flat_impact, dtype=np.float32)
    if imp.size == 0:
        return None
    if not np.isfinite(imp).all() or bool((imp < 0).any()):
        return "impacts must be finite and non-negative"
    codes = impact_code16_np(imp)
    pos = imp > 0
    if bool((codes[pos] == 0).any()):
        return "positive impact below the 16-bit code floor"
    terms = _posting_terms(row_starts, imp.size)
    t_p, v_p = terms[pos], imp[pos]
    if t_p.size:
        order = np.lexsort((v_p, t_p))
        t_s, v_s = t_p[order], v_p[order]
        first = np.ones(t_s.size, dtype=bool)
        first[1:] = (t_s[1:] != t_s[:-1]) | (v_s[1:] != v_s[:-1])
        per_term = np.bincount(t_s[first])
        if per_term.size and int(per_term.max()) > COMPRESSED_RANK_LIMIT:
            return (f"a term has more than {COMPRESSED_RANK_LIMIT} "
                    f"distinct impacts (rank code overflow)")
    return None


def compress_flat(flat_docs: np.ndarray, flat_impact: np.ndarray,
                  row_starts: np.ndarray, d_pad: int,
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                             np.ndarray, np.ndarray, np.ndarray]:
    """Build one shard's compressed resident streams from its doc-sorted
    flats. → (docs16 u16[P], code16 u16[P], rank16 u16[P],
    block_max u16[NB+1], res_vals f32[RC], res_row_starts i64[n_rows+1]).

    docs16/code16/rank16 replace the 16 resident bytes per posting with
    6: code16 is the monotone VALUE code (lower bounds for the quantized
    sort and block-max pruning; collisions between near-equal impacts
    are harmless there), rank16 is the 1-based index of the posting's
    impact in its term's ascending distinct-impact residual table (0 =
    tombstone-zeroed posting) — injective by construction, so the exact
    rescore recovers bit-exact f32 impacts from res_vals without a
    resident f32 copy. block_max[j] is the max value code of the
    128-lane-aligned block j, plus ONE zero slack entry so a slot
    straddling the array edge can always slice n_grp+1 entries without
    dynamic_slice clamping into earlier (wrong) blocks. Raises
    ValueError when compress_reason() is non-None; callers gate first."""
    reason = compress_reason(flat_docs, flat_impact, row_starts, d_pad)
    if reason is not None:
        raise ValueError(f"flats not compressible: {reason}")
    docs = np.asarray(flat_docs)
    imp = np.asarray(flat_impact, dtype=np.float32)
    n = imp.size
    docs16 = np.minimum(docs, d_pad).astype(np.uint16)
    code16 = impact_code16_np(imp)

    nb = (n + COMPRESSED_BLOCK - 1) // COMPRESSED_BLOCK
    padded = np.zeros(nb * COMPRESSED_BLOCK, dtype=np.uint16)
    padded[:n] = code16
    block_max = np.concatenate(
        [padded.reshape(nb, COMPRESSED_BLOCK).max(axis=1),
         np.zeros(1, dtype=np.uint16)])

    terms = _posting_terms(row_starts, n)
    n_rows = np.asarray(row_starts).size - 1
    pos = imp > 0
    t_p, v_p = terms[pos], imp[pos]
    order = np.lexsort((v_p, t_p))
    t_s, v_s = t_p[order], v_p[order]
    first = np.ones(t_s.size, dtype=bool)
    if t_s.size:
        first[1:] = (t_s[1:] != t_s[:-1]) | (v_s[1:] != v_s[:-1])
    res_row_starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(np.bincount(t_s[first], minlength=n_rows),
              out=res_row_starts[1:])
    rank16 = np.zeros(n, dtype=np.uint16)
    if t_s.size:
        distinct_idx = np.cumsum(first) - 1
        rank_sorted = distinct_idx - res_row_starts[t_s] + 1
        rank_pos = np.empty(t_s.size, dtype=np.int64)
        rank_pos[order] = rank_sorted
        rank16[pos] = rank_pos.astype(np.uint16)
    return (docs16, code16, rank16, block_max,
            v_s[first].astype(np.float32), res_row_starts)


#: widest doc-id span an aligned 128-lane block may cover and still take
#: the u8 delta encoding (delta = doc − block min must fit one byte)
DELTA_DOC_SPAN = (1 << 8) - 1


def delta_doc_reason(flat_docs: np.ndarray, row_starts: np.ndarray,
                     ) -> Optional[str]:
    """Why this shard's doc stream can NOT take the per-block delta
    encoding — None means every aligned COMPRESSED_BLOCK-lane block of
    REAL postings (positions before row_starts[-1]; the slack tail is
    never decoded) spans ≤ DELTA_DOC_SPAN doc ids, so doc − block_min
    fits the u8 delta field. Blocks straddling a row boundary mix two
    terms' doc ids; the min-base covers that case (deltas are measured
    against the block minimum, not the first lane)."""
    rs = np.asarray(row_starts, dtype=np.int64)
    total = int(rs[-1]) if rs.size else 0
    if total == 0:
        return None
    docs = np.asarray(flat_docs[:total], dtype=np.int64)
    nb = (total + COMPRESSED_BLOCK - 1) // COMPRESSED_BLOCK
    pad = nb * COMPRESSED_BLOCK - total
    mx = np.concatenate([docs, np.full(pad, -1, dtype=np.int64)])
    mn = np.concatenate([docs, np.full(pad, 1 << 30, dtype=np.int64)])
    span = (mx.reshape(nb, COMPRESSED_BLOCK).max(axis=1)
            - mn.reshape(nb, COMPRESSED_BLOCK).min(axis=1))
    worst = int(span.max())
    if worst > DELTA_DOC_SPAN:
        return (f"a {COMPRESSED_BLOCK}-lane block spans {worst} doc ids "
                f"(u8 delta limit {DELTA_DOC_SPAN})")
    return None


def delta_encode_docs(flat_docs: np.ndarray, row_starts: np.ndarray,
                      n_bases: int) -> Tuple[np.ndarray, np.ndarray]:
    """Build one shard's delta doc stream: → (docs8 u8[P], bases
    u16[n_bases]). bases[j] is the minimum doc id of aligned block j
    (zero for blocks past the real postings — never decoded, see
    delta_doc_reason); docs8[p] = doc − bases[p // 128] for real
    positions, zero in the slack tail. n_bases must leave the kernel's
    slice slack past the last real block (callers size it
    ceil(P / 128) + 2). Raises ValueError when delta_doc_reason() is
    non-None; callers gate first."""
    reason = delta_doc_reason(flat_docs, row_starts)
    if reason is not None:
        raise ValueError(f"doc stream not delta-encodable: {reason}")
    docs = np.asarray(flat_docs, dtype=np.int64)
    rs = np.asarray(row_starts, dtype=np.int64)
    total = int(rs[-1]) if rs.size else 0
    nb = (total + COMPRESSED_BLOCK - 1) // COMPRESSED_BLOCK
    if n_bases < nb:
        raise ValueError(f"n_bases {n_bases} < {nb} real blocks")
    bases = np.zeros(n_bases, dtype=np.uint16)
    docs8 = np.zeros(docs.size, dtype=np.uint8)
    if total:
        pad = nb * COMPRESSED_BLOCK - total
        mn = np.concatenate(
            [docs[:total], np.full(pad, 1 << 30, dtype=np.int64)]
        ).reshape(nb, COMPRESSED_BLOCK).min(axis=1)
        bases[:nb] = mn.astype(np.uint16)
        docs8[:total] = (docs[:total]
                         - np.repeat(mn, COMPRESSED_BLOCK)[:total]
                         ).astype(np.uint8)
    return docs8, bases


def packable(d_pad: int, weights: Optional[np.ndarray] = None) -> bool:
    """Host-side lowering-time check: may the packed-key variant serve
    this (pack, batch)? False routes the batch to the exact-f32
    reference variant. Conditions: every doc id INCLUDING the d_pad
    sentinel must fit the 16-bit doc field, and every slot weight must
    be finite, non-negative and (when positive) inside
    [PACKED_WEIGHT_MIN, PACKED_WEIGHT_MAX] — negative weights break the
    monotone code, and out-of-range magnitudes could zero or saturate a
    real contribution's 16-bit code."""
    if d_pad >= PACKED_DOC_LIMIT:
        return False
    if weights is not None:
        w = np.asarray(weights)
        if w.size:
            if not np.isfinite(w).all() or bool((w < 0).any()):
                return False
            pos = w[w > 0]
            if pos.size and (float(pos.min()) < PACKED_WEIGHT_MIN
                             or float(pos.max()) > PACKED_WEIGHT_MAX):
                return False
    return True


def hierarchical_top_k(score: jax.Array, k: int, block: int = 4096,
                       split: Optional[bool] = None,
                       ) -> Tuple[jax.Array, jax.Array]:
    """top_k over [R, L] as per-block top-k' then a merge top-k — the
    full-width lax.top_k over T*L_c is the other half of the device
    floor at the 128-slot widths. Selection and tie-breaking are
    IDENTICAL to lax.top_k(score, k): with k' = min(k, block) a global
    winner is always inside its block's top-k', and equal values keep
    earliest-global-index preference because blocks merge in index
    order and each block's top_k is earliest-index-first among ties.
    Falls back to the flat top_k when the width doesn't split (L not a
    multiple of `block`, or k so large the merge wouldn't shrink).

    split=None picks per backend at trace time: the per-block reduction
    pays on sort-network backends (TPU lowers top_k to a bitonic sort
    of the FULL width, so blocking cuts real comparator work), while
    XLA:CPU's TopK custom call is already O(n) selection and the split
    only adds per-row dispatch overhead (measured ~5x slower at the
    32-slot serving width — tests/test_kernel_bench.py pins this).
    split=True forces the per-block path (parity tests exercise its
    merge logic on CPU); split=False forces flat."""
    r, length = score.shape
    kk = min(k, length)
    if split is None:
        split = jax.default_backend() == "tpu"
    if not split or length <= block or kk >= block or length % block:
        return jax.lax.top_k(score, kk)
    n_blocks = length // block
    k_b = min(kk, block)
    v, p = jax.lax.top_k(score.reshape(r, n_blocks, block), k_b)
    base = (jnp.arange(n_blocks, dtype=jnp.int32) * block)[None, :, None]
    v = v.reshape(r, n_blocks * k_b)
    p = (p + base).reshape(r, n_blocks * k_b)
    vals, pos2 = jax.lax.top_k(v, kk)
    return vals, jnp.take_along_axis(p, pos2, axis=1)


def _rank_decode(ranks: jax.Array, r_start: jax.Array, r_len: jax.Array,
                 res_vals: jax.Array) -> jax.Array:
    """Exact f32 impact of each posting from its per-term rank code:
    rank r ≥ 1 indexes the term's ascending residual value table at
    r_start + r − 1; rank 0 (padding or a tombstone-zeroed posting)
    decodes to 0.0. ranks/r_start/r_len broadcast together (int32)."""
    ok = (ranks > 0) & (ranks <= r_len)
    at = r_start + jnp.maximum(ranks, 1) - 1
    vals = jnp.take(res_vals, at, mode="fill", fill_value=0.0)
    return jnp.where(ok, vals, 0.0)


def segmented_run_sum(sk: jax.Array, sv: jax.Array,
                      t_window: int) -> jax.Array:
    """Inclusive per-run prefix sums over a key-sorted [R, L] pair via
    Hillis-Steele doubling: after ceil(log2(t_window)) steps, each
    run-end position holds its run's full sum. Replaces the old linear
    T-tap shifted-add (VERDICT r4 weak #8): work/compile now scale with
    log(T), so 32+ term queries (multi_match / fuzzy expansions) stay
    on the kernel path instead of falling off it."""
    length = sk.shape[1]
    total = sv
    step = 1
    while step < t_window:
        shifted_t = jnp.pad(total, ((0, 0), (step, 0)))[:, :length]
        shifted_k = jnp.pad(sk, ((0, 0), (step, 0)),
                            constant_values=-1)[:, :length]
        total = total + jnp.where(shifted_k == sk, shifted_t, 0.0)
        step *= 2
    return total


@partial(jax.jit, static_argnames=("max_len", "d_pad", "k", "t_window",
                                   "with_counts", "with_totals",
                                   "variant"))
def sorted_merge_topk(
    flat_docs: jax.Array,    # int32[P_flat] doc ids (u16 when compressed,
                             # u8 deltas when doc_bases is given)
    flat_impact: jax.Array,  # f32[P_flat] impacts (u16 codes when compressed)
    starts: jax.Array,       # int32[R, T] absolute offsets into flat arrays
    lengths: jax.Array,      # int32[R, T] chunk lengths (0 = empty slot)
    weights: jax.Array,      # f32[R, T] idf·(k1+1)·boost per slot
    min_count: jax.Array,    # int32[R] minimum matched clauses (msm/AND)
    *,
    max_len: int,            # static: chunk length L_c
    d_pad: int,              # static: doc-axis pad (sentinel doc id)
    k: int,                  # static: top-k
    t_window: int,           # static: T (slot count = max same-doc entries)
    with_counts: bool,       # static: evaluate min_count (msm/AND)
    with_totals: bool = False,  # static: also return matched-doc counts
    variant: str = "ref",    # static: one of KERNEL_VARIANTS (module doc)
    flat_rank: Optional[jax.Array] = None,   # u16[P_flat] per-term ranks
    res_starts: Optional[jax.Array] = None,  # int32[R,T] residual offsets
    res_lens: Optional[jax.Array] = None,    # int32[R,T] residual lengths
    res_vals: Optional[jax.Array] = None,    # f32[RC] residual exact f32s
    block_max: Optional[jax.Array] = None,   # u16[NB+1] per-block max codes
    blk_starts: Optional[jax.Array] = None,  # int32[R,T] slot block indices
    slot_terms: Optional[jax.Array] = None,  # int32[R,T] term group id/slot
    doc_bases: Optional[jax.Array] = None,   # u16[NBD] delta block bases
    dbs_starts: Optional[jax.Array] = None,  # int32[R,T] slot base indices
    dlo_starts: Optional[jax.Array] = None,  # int32[R,T] slot offset % 128
) -> Tuple[jax.Array, ...]:
    """→ (scores f32[R, k'], doc_ids int32[R, k'][, totals int32[R]]);
    empty lanes are (-inf, d_pad). k' = min(k, T·L_c). totals (when
    with_totals) is the exact per-row count of matching docs — the
    TotalHits value of the reference's query phase. variant="packed"
    computes the same outputs bit-for-bit via the single-key sort +
    hierarchical top-k + exact rescore pipeline; callers must have
    checked packable() host-side. The compressed variants read u16
    doc/code streams plus residual tables (res_* operands required) and
    are also bit-identical to "ref" on the same postings; "compressed"
    additionally needs packable() weights, "compressed_exact" does not.
    variant="pallas" runs the "compressed" pipeline as one fused Pallas
    kernel (interpret-mode off-TPU) — same operands, same bits.
    block_max/blk_starts enable the block-max skip (compressed/pallas;
    inert when k > max_len; with_totals launches get exact totals from
    the pre-skip count sort). doc_bases/dbs_starts/dlo_starts switch the
    doc stream to the u8-delta format (delta_encode_docs)."""
    if variant not in KERNEL_VARIANTS:
        raise ValueError(f"unknown kernel variant {variant!r}")
    packed = variant == "packed"
    compressed = variant in COMPRESSED_VARIANTS
    if (packed or compressed) and d_pad >= PACKED_DOC_LIMIT:
        raise ValueError(
            f"variant {variant!r} needs d_pad < {PACKED_DOC_LIMIT}, got "
            f"{d_pad} — caller must fall back to variant='ref'")
    if compressed and (flat_rank is None or res_starts is None
                       or res_lens is None or res_vals is None):
        raise ValueError(
            "compressed variants need flat_rank/res_starts/res_lens/"
            "res_vals — build them with compress_flat()")
    if doc_bases is not None and (dbs_starts is None or dlo_starts is None):
        raise ValueError(
            "delta doc stream needs dbs_starts/dlo_starts alongside "
            "doc_bases")
    kw = dict(
        max_len=max_len, d_pad=d_pad, k=k, t_window=t_window,
        with_counts=with_counts, with_totals=with_totals,
        flat_rank=flat_rank, res_starts=res_starts, res_lens=res_lens,
        res_vals=res_vals, block_max=block_max, blk_starts=blk_starts,
        slot_terms=slot_terms, doc_bases=doc_bases,
        dbs_starts=dbs_starts, dlo_starts=dlo_starts)
    if variant == "pallas":
        from elasticsearch_tpu.ops import pallas_merge
        return pallas_merge.fused_merge_topk(
            flat_docs, flat_impact, starts, lengths, weights, min_count,
            **kw)
    return _merge_topk_core(
        flat_docs, flat_impact, starts, lengths, weights, min_count,
        variant=variant, **kw)


def _merge_topk_core(
    flat_docs, flat_impact, starts, lengths, weights, min_count, *,
    max_len: int, d_pad: int, k: int, t_window: int, with_counts: bool,
    with_totals: bool, variant: str, flat_rank=None, res_starts=None,
    res_lens=None, res_vals=None, block_max=None, blk_starts=None,
    slot_terms=None, doc_bases=None, dbs_starts=None, dlo_starts=None,
) -> Tuple[jax.Array, ...]:
    """The merge pipeline proper — sorted_merge_topk after validation.
    Shared verbatim by the XLA variants and the Pallas fused kernel
    (which calls it per grid row on its block values under
    interpret=True off-TPU), so parity across dispatch styles holds by
    construction. `variant` here is one of ref/packed/compressed/
    compressed_exact; the pallas wrapper passes "compressed"."""
    packed = variant == "packed"
    compressed = variant in COMPRESSED_VARIANTS
    r, t_slots = starts.shape
    idx = jnp.arange(max_len, dtype=jnp.int32)

    def slice_one(s):
        return (jax.lax.dynamic_slice(flat_docs, (s,), (max_len,)),
                jax.lax.dynamic_slice(flat_impact, (s,), (max_len,)))

    docs, imps = jax.vmap(jax.vmap(slice_one))(starts)     # [R, T, L]
    valid = idx[None, None, :] < lengths[:, :, None]
    if compressed:
        if doc_bases is not None:
            # delta doc stream: lane doc = per-block u16 base + u8
            # delta. A slot window straddles at most max_len // 128 + 1
            # aligned blocks from its (dbs, dlo) cursor; slice one extra
            # so dynamic_slice never clamps (builders leave the slack)
            nb_slice = max_len // COMPRESSED_BLOCK + 2

            def base_slice(bs):
                return jax.lax.dynamic_slice(doc_bases, (bs,), (nb_slice,))

            bases = jax.vmap(jax.vmap(base_slice))(dbs_starts)
            lane_blk = ((dlo_starts[:, :, None] + idx[None, None, :])
                        // COMPRESSED_BLOCK)
            lane_base = jnp.take_along_axis(
                bases.astype(jnp.int32), lane_blk, axis=2)
            docs = jnp.where(
                valid, lane_base + docs.astype(jnp.int32), d_pad)
        else:
            docs = jnp.where(valid, docs.astype(jnp.int32), d_pad)
        codes = jnp.where(valid, imps.astype(jnp.uint32), 0)
        if variant == "compressed_exact":
            # decode every lane to its exact f32 through the residual
            # tables, then run the reference pipeline verbatim — exact
            # for ANY weights (the automatic fallback variant)
            def slice_rank(s):
                return jax.lax.dynamic_slice(flat_rank, (s,), (max_len,))

            ranks = jax.vmap(jax.vmap(slice_rank))(starts).astype(jnp.int32)
            ranks = jnp.where(valid, ranks, 0)
            lane_exact = _rank_decode(ranks, res_starts[:, :, None],
                                      res_lens[:, :, None], res_vals)
            imp = jnp.where(valid, weights[:, :, None] * lane_exact, 0.0)
        else:
            # lower-bound lane contributions from the decoded codes —
            # the packed pipeline's quantized values, without ever
            # materialising an f32 impact array in HBM
            imp = jnp.where(
                valid, weights[:, :, None] * decode_code16(codes), 0.0)
    else:
        docs = jnp.where(valid, docs, d_pad)
        imp = jnp.where(valid, weights[:, :, None] * imps, 0.0)

    length = t_slots * max_len
    kk = min(k, length)

    do_skip = (variant == "compressed"
               and block_max is not None and blk_starts is not None
               and k <= max_len)
    skip_totals = None
    if do_skip and with_totals:
        # exact TotalHits from the PRE-skip lanes: a skipped doc is
        # still a match, so totals cannot come from the post-skip sort.
        # One auxiliary u32 sort of (doc << 1 | positive-code bit) plus
        # the same run machinery counts exactly the docs the unskipped
        # pipeline would have counted — total > 0 there means "some
        # lane's decoded code is positive", which is precisely the
        # positive-code bit OR'd over the run
        posb = (impact_code16(imp) > 0).astype(jnp.uint32)
        ckey = jax.lax.sort(
            ((docs.astype(jnp.uint32) << 1) | posb).reshape(r, length))
        cdoc = (ckey >> 1).astype(jnp.int32)
        cpos = (ckey & 1).astype(jnp.float32)
        c_end = jnp.concatenate(
            [cdoc[:, :-1] != cdoc[:, 1:], jnp.ones((r, 1), bool)], axis=1)
        c_ok = c_end & (cdoc < d_pad) & (
            segmented_run_sum(cdoc, cpos, t_window) > 0)
        if with_counts:
            c_cnt = segmented_run_sum(cdoc, jnp.ones_like(cpos), t_window)
            c_ok = c_ok & (c_cnt >= min_count[:, None].astype(jnp.float32))
        skip_totals = jnp.sum(c_ok, axis=1, dtype=jnp.int32)
    if do_skip:
        # Block-max skip (device-side BMW/MaxScore). Threshold: within a
        # slot, lanes are DISTINCT docs, so a slot's k-th largest lane
        # value is a lower bound on the k-th best full score (each such
        # doc's full score ≥ its lane; all contributions non-negative,
        # and with min_count ≤ 1 every such doc is a real result).
        # Upper bound per 128-lane group: an unaligned group spans ≤ 2
        # aligned blocks, so max of two adjacent block codes; +1 on the
        # code is an open upper bound of any impact in the block. A group
        # is skipped only when its bound PLUS every other slot's window
        # bound stays strictly below the threshold — any doc with full
        # score ≥ thr therefore keeps all its lanes, and partially
        # skipped docs score strictly below thr even after rescore, so
        # results stay bit-identical (see module doc).
        n_grp = (max_len + COMPRESSED_BLOCK - 1) // COMPRESSED_BLOCK

        def bm_slice(bs):
            return jax.lax.dynamic_slice(block_max, (bs,), (n_grp + 1,))

        bm = jax.vmap(jax.vmap(bm_slice))(blk_starts)       # [R,T,G+1]
        grp_code = jnp.maximum(bm[..., :-1], bm[..., 1:]).astype(jnp.uint32)
        # clamp keeps the +1 from wrapping past the f32 space: anything
        # at/above the max finite code decodes to +inf (never skipped)
        ub = decode_code16(jnp.minimum(grp_code + 1, jnp.uint32(0x7F80)))
        g_base = (jnp.arange(n_grp, dtype=jnp.int32)
                  * COMPRESSED_BLOCK)[None, None, :]
        g_valid = g_base < lengths[:, :, None]
        w3 = weights[:, :, None]
        grp_ub = jnp.where(g_valid & (w3 > 0), w3 * ub, 0.0)
        slot_ub = jnp.max(grp_ub, axis=2)                    # [R,T]
        if slot_terms is not None:
            # a doc appears in at most ONE chunk of a term, so the
            # other-slots bound groups chunks by term: max over a
            # term's slots, sum over DISTINCT terms (MaxScore, not the
            # hopeless sum-over-all-slots on chunked rows)
            eq = slot_terms[:, :, None] == slot_terms[:, None, :]
            term_ub = jnp.max(
                jnp.where(eq, slot_ub[:, None, :], 0.0), axis=2)
            tri = jnp.tril(jnp.ones((t_slots, t_slots), bool), k=-1)
            first = ~jnp.any(eq & tri[None], axis=2)
            others = (jnp.sum(jnp.where(first, term_ub, 0.0),
                              axis=1, keepdims=True) - term_ub)
        else:
            others = jnp.sum(slot_ub, axis=1, keepdims=True) - slot_ub
        kth = jax.lax.top_k(imp, kk)[0][..., kk - 1]         # [R,T]
        enough = lengths >= kk
        thr = jnp.max(jnp.where(enough, kth, NEG_INF), axis=1)  # [R]
        if with_counts:
            thr = jnp.where(min_count <= 1, thr, NEG_INF)
        skip_grp = (grp_ub + others[:, :, None]) < thr[:, None, None]
        lane_skip = skip_grp[:, :, idx // COMPRESSED_BLOCK]
        docs = jnp.where(lane_skip, d_pad, docs)
        imp = jnp.where(lane_skip, 0.0, imp)

    if packed or variant == "compressed":
        # ONE uint32 sort key per lane: doc id high, impact code low —
        # half the sorted bytes of the (docs, imp) pair. Equal-doc lanes
        # stay contiguous (doc owns the high bits); padded lanes carry
        # (d_pad, code 0) and sort to the tail like the reference.
        key = ((docs.astype(jnp.uint32) << 16)
               | impact_code16(imp)).reshape(r, length)
        sk_key = jax.lax.sort(key)
        sk = (sk_key >> 16).astype(jnp.int32)
        # decoded codes are LOWER bounds of the exact lane impacts, so
        # total>0 tests and candidate ordering are conservative
        sv = decode_code16(sk_key & jnp.uint32(0xFFFF))
    else:
        sk, sv = jax.lax.sort(
            [docs.reshape(r, length), imp.reshape(r, length)], num_keys=1)

    total = segmented_run_sum(sk, sv, t_window)

    run_end = jnp.concatenate(
        [sk[:, :-1] != sk[:, 1:], jnp.ones((r, 1), bool)], axis=1)
    ok = run_end & (sk < d_pad) & (total > 0)

    cnt = None
    if with_counts or packed or variant == "compressed":
        # clause count per doc = run length (each slot holds a doc at most
        # once: postings rows have unique docs, chunks of one term
        # partition its row). Runs are ≤ t_window long by the same
        # argument, so the log-step scan sees the whole run. The packed
        # rescore needs it too: the run length is the matched-slot count.
        cnt = segmented_run_sum(sk, jnp.ones_like(sv), t_window)
    if with_counts:
        ok = ok & (cnt >= min_count[:, None].astype(jnp.float32))

    # totals BEFORE candidate selection: the count is a property of the
    # full sorted axis, and computing it here keeps every downstream
    # top-k shape (full-width or hierarchical) from being able to drop
    # or truncate it. When the block-max skip ran, the pre-skip count
    # sort already produced the exact value
    if not with_totals:
        totals = None
    elif skip_totals is not None:
        totals = skip_totals
    else:
        totals = jnp.sum(ok, axis=1, dtype=jnp.int32)

    score = jnp.where(ok, total, NEG_INF)
    if packed or variant == "compressed":
        res = None
        if variant == "compressed":
            res = (res_starts, res_lens, res_vals, flat_rank)
        delta = None
        if doc_bases is not None:
            delta = (doc_bases, dbs_starts, dlo_starts)
        vals, hit_docs = _packed_rescore_topk(
            flat_docs, flat_impact, starts, lengths, weights,
            sk, score, cnt, kk, max_len=max_len, d_pad=d_pad,
            t_window=t_window, res=res, delta=delta)
    else:
        vals, pos = jax.lax.top_k(score, kk)
        hit_docs = jnp.take_along_axis(sk, pos, axis=1)
        hit_docs = jnp.where(vals > NEG_INF, hit_docs, d_pad)
    if with_totals:
        return vals, hit_docs, totals
    return vals, hit_docs


def _packed_rescore_topk(flat_docs, flat_impact, starts, lengths, weights,
                         sk, score, cnt, kk, *, max_len: int, d_pad: int,
                         t_window: int, res=None, delta=None):
    """Candidate selection + exact-f32 rescore for the packed variant.
    With res=(res_starts, res_lens, res_vals, flat_rank) the streams are
    the compressed u16 doc/code pair and each matched position's exact
    f32 comes from its rank code into the term's residual value table
    instead of from a resident f32 array.

    Selection: hierarchical top-k over the QUANTIZED run totals, with
    slack — a packed code is a lower bound within 2**-8 relative of
    its lane, so any true top-kk doc ranks above quantized-rank kk + m
    unless m+1 other docs land inside that relative band of the
    boundary. The compressed streams quantize TWICE (posting -> stored
    code at build, then w*decode(code) -> key code at sort), doubling
    the band to ~2**-7 and with it the number of docs a dense uniform
    term can pack against the boundary (~df/128 vs ~df/256), so their
    slack is doubled too. The slack makes the sweep-tested shapes
    exact in practice while the width stays a small multiple of kk
    instead of T*L_c.

    Rescore: each candidate's exact contribution per slot comes from a
    lower_bound binary search in that slot's doc-sorted chunk, then the
    matched contributions are compacted (stable, slot order — the same
    value order the reference's stable doc sort produces) and summed by
    the SAME log-step guarded scan over the same run length, so the
    f32 rounding tree is bit-identical to segmented_run_sum's and the
    returned scores equal variant="ref" exactly, not just closely.

    With delta=(doc_bases, dbs_starts, dlo_starts) the doc stream holds
    u8 block deltas (delta_encode_docs) and every random access decodes
    through the slot's block cursor: doc(pos) = bases[dbs + (dlo + pos −
    start) // 128] + delta[pos]. Positions outside the slot's window
    decode to d_pad, which also keeps the lo == end probe conservative."""
    r, t_slots = starts.shape
    length = sk.shape[1]
    slack = max(2 * kk, 256) if res is not None else max(2 * kk, 128)
    kc = min(length, kk + slack)
    a_vals, a_pos = hierarchical_top_k(score, kc)
    cand_docs = jnp.take_along_axis(sk, a_pos, axis=1)           # [R, kc]
    cand_cnt = jnp.take_along_axis(cnt, a_pos, axis=1).astype(jnp.int32)

    # exact per-slot contribution: lower_bound of the candidate doc in
    # each chunk's [start, start+len) range of the doc-sorted postings
    lo = jnp.broadcast_to(starts[:, None, :], (r, kc, t_slots))
    ln3 = jnp.broadcast_to(lengths[:, None, :], (r, kc, t_slots))
    end = lo + ln3
    hi = end
    target = cand_docs[:, :, None]
    if delta is None:
        def doc_at(pos):
            return jnp.take(flat_docs, pos, mode="fill", fill_value=d_pad)
    else:
        d_bases, dbs, dlo = delta
        st3 = starts[:, None, :]
        dbs3 = dbs[:, None, :]
        dlo3 = dlo[:, None, :]

        def doc_at(pos):
            jrel = pos - st3
            bidx = dbs3 + (dlo3 + jrel) // COMPRESSED_BLOCK
            base = jnp.take(d_bases, bidx, mode="fill",
                            fill_value=0).astype(jnp.int32)
            dd = jnp.take(flat_docs, pos, mode="fill",
                          fill_value=0).astype(jnp.int32)
            return jnp.where((jrel >= 0) & (jrel < ln3), base + dd, d_pad)
    for _ in range(max(1, int(max_len).bit_length())):
        active = lo < hi
        mid = (lo + hi) >> 1
        v = doc_at(mid)
        go = v < target
        lo = jnp.where(active & go, mid + 1, lo)
        hi = jnp.where(active & ~go, mid, hi)
    v = doc_at(lo)
    found = (ln3 > 0) & (lo < end) & (v == target) & (target < d_pad)
    if res is None:
        imp_exact = jnp.take(flat_impact, lo, mode="fill", fill_value=0.0)
    else:
        res_st, res_ln, r_vals, f_rank = res
        rank_at = jnp.take(f_rank, lo, mode="fill",
                           fill_value=0).astype(jnp.int32)
        imp_exact = _rank_decode(rank_at, res_st[:, None, :],
                                 res_ln[:, None, :], r_vals)
    contrib = jnp.where(found, weights[:, None, :] * imp_exact, 0.0)

    # compact matched slots to the front (stable ⇒ slot order preserved:
    # exactly the lane order of the reference's equal-doc run) and redo
    # the run sum with the reference's tree: the guarded log-step scan's
    # rounding order depends only on offset-in-run and step count, both
    # reproduced here, so the sums are bit-identical
    flat_rc = (r * kc, t_slots)
    comp_key, comp_val = jax.lax.sort(
        [jnp.where(found, 0, 1).astype(jnp.int32).reshape(flat_rc),
         contrib.reshape(flat_rc)], num_keys=1)
    run_pos = jnp.arange(t_slots, dtype=jnp.int32)[None, :]
    m = cand_cnt.reshape(r * kc, 1)
    scan_keys = jnp.where(run_pos < m, 0, run_pos + 1)
    scan_tot = segmented_run_sum(scan_keys, comp_val, t_window)
    gather_at = jnp.clip(m - 1, 0, t_slots - 1)
    exact = jnp.take_along_axis(scan_tot, gather_at,
                                axis=1).reshape(r, kc)
    exact = jnp.where(a_vals > NEG_INF, exact, NEG_INF)

    # final order on EXACT scores with the reference tie rule (equal
    # scores → smaller doc id); -inf lanes pinned to (+inf, d_pad) keys
    # so they tail-sort identically
    neg = jnp.where(exact > NEG_INF, -exact, jnp.inf)
    docs_key = jnp.where(exact > NEG_INF, cand_docs, d_pad)
    neg_s, docs_s = jax.lax.sort([neg, docs_key], num_keys=2)
    vals = jnp.where(jnp.isinf(neg_s[:, :kk]), NEG_INF, -neg_s[:, :kk])
    hit_docs = jnp.where(vals > NEG_INF, docs_s[:, :kk], d_pad)
    return vals, hit_docs


# ---------------------------------------------------------------------------
# host-side slot planning
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SlotPlan:
    """Chunked term slots for a batch of rows (query × shard pairs)."""

    starts: np.ndarray    # int32[R, T]
    lengths: np.ndarray   # int32[R, T]
    weights: np.ndarray   # f32[R, T]
    min_count: np.ndarray  # int32[R]
    max_len: int          # L_c (static bucket)
    t_slots: int          # T (static)
    window: int           # max same-doc entries per row = max terms/row
                          # (chunks of one term partition docs, so the
                          # kernel's t_window only needs to cover TERMS,
                          # not slots — far fewer taps on chunked queries)


def _len_bucket(n: int, lane: int = 128) -> int:
    b = lane
    while b < n:
        b *= 2
    return b


def _cap_bucket(cap: int, lane: int) -> int:
    """Largest lane-based power-of-two bucket that does NOT exceed cap
    (rounding the cap UP would overrun callers' flat-array slack)."""
    b = lane
    while b * 2 <= cap:
        b *= 2
    return b


def plan_slots(rows: Sequence[Sequence[Tuple[int, int, float, int]]],
               min_counts: Sequence[int],
               chunk_cap: int = 4096,
               lane: int = 128) -> SlotPlan:
    """rows[r] = [(start, length, weight, term_id), ...] — one entry per
    query term with its postings-row extent in the flat arrays. Long rows
    split into chunks of ≤ L_c where L_c = min(bucket(max row length),
    largest bucket ≤ chunk_cap). Returns padded static-shape slot tensors."""
    longest = 1
    window = 1
    for row in rows:
        window = max(window, len(row))
        for (_, ln, _, _) in row:
            longest = max(longest, ln)
    max_len = min(_len_bucket(longest, lane), _cap_bucket(chunk_cap, lane))

    chunked: List[List[Tuple[int, int, float, int]]] = []
    t_needed = 1
    for row in rows:
        out = []
        for (s, ln, w, tid) in row:
            off = 0
            while off < ln:
                take = min(max_len, ln - off)
                out.append((s + off, take, w, tid))
                off += take
            if ln == 0:
                # keep empty terms as zero-length slots so min_count
                # semantics see the term as present-but-unmatched
                out.append((s, 0, w, tid))
        chunked.append(out)
        t_needed = max(t_needed, len(out))
    t_slots = 1
    while t_slots < t_needed:
        t_slots *= 2

    r = len(rows)
    starts = np.zeros((r, t_slots), dtype=np.int32)
    lengths = np.zeros((r, t_slots), dtype=np.int32)
    weights = np.zeros((r, t_slots), dtype=np.float32)
    for ri, out in enumerate(chunked):
        for ti, (s, ln, w, _tid) in enumerate(out[:t_slots]):
            starts[ri, ti] = s
            lengths[ri, ti] = ln
            weights[ri, ti] = w
    return SlotPlan(starts, lengths, weights,
                    np.asarray(min_counts, dtype=np.int32), max_len, t_slots,
                    window)


def eager_impacts(flat_docs: np.ndarray, flat_tfs: np.ndarray,
                  norms_u8: np.ndarray, k1: float, b: float,
                  avgdl: float) -> np.ndarray:
    """Precompute per-posting BM25 impacts (step 1 above). norms_u8 is the
    doc-axis norm column; flat_docs indexes into it (pad sentinel rows get
    impact 0 via tf==0)."""
    from elasticsearch_tpu.ops.smallfloat import LENGTH_TABLE
    d = norms_u8.shape[0]
    safe = np.minimum(flat_docs, d - 1)
    dl = LENGTH_TABLE[norms_u8[safe].astype(np.int64)].astype(np.float32)
    denom_add = (k1 * (1.0 - b + b * dl / (avgdl if avgdl > 0 else 1.0))
                 ).astype(np.float32)
    tf = flat_tfs.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        imp = tf / (tf + denom_add)
    return np.where(flat_tfs > 0, imp, 0.0).astype(np.float32)


def union_topk(scores_list, rows_list, ords_list, row_offsets, k: int):
    """Union-reduce per-pack kernel top-k columns (streaming delta path).

    The base pack and each resident delta pack run the device merge
    kernel independently; a doc lives in exactly one pack (deltas are
    append-only — an update of a committed doc forces a full rebuild),
    so the union is a pure k-way top-k over disjoint candidate sets: no
    dedup, totals add. Rows re-base into the concatenated union row
    space via ``row_offsets`` (per-pack starting row). Ties break by
    (score desc, pack order, in-pack kernel rank) so the reduce is
    deterministic and is the identity for a single operand.
    """
    scores = np.concatenate([np.asarray(s) for s in scores_list])
    rows = np.concatenate(
        [np.asarray(r, dtype=np.int64) + int(off)
         for r, off in zip(rows_list, row_offsets)])
    ords = np.concatenate([np.asarray(o) for o in ords_list])
    pack_tag = np.concatenate(
        [np.full(len(np.asarray(s)), i, dtype=np.int32)
         for i, s in enumerate(scores_list)])
    rank = np.concatenate(
        [np.arange(len(np.asarray(s)), dtype=np.int32)
         for s in scores_list])
    order = np.lexsort((rank, pack_tag, -scores))[:k]
    return scores[order], rows[order], ords[order]
