"""Impact-sorted-merge retrieval kernel — the TPU-native hot path.

Replaces the reference's per-segment postings traversal (SURVEY.md §3.3:
BulkScorer loop → BM25Scorer → TopScoreDocCollector) with a formulation
built from TPU-fast primitives only (measured on v5e: XLA scatter ≈ 10M
updates/s — unusable; sort/top_k/contiguous-slice ≈ memory-bandwidth):

  1. Eager impacts (BM25S-style, PAPERS.md): at pack-build time each
     posting stores  impact = tf / (tf + k1·(1 − b + b·dl/avgdl))  so
     query-time scoring is one multiply by the term's idf·(k1+1)·boost.
  2. Chunked slot gather: each query term's postings row is split into
     chunks of ≤ L_c (static bucket); a chunk = one (start, length, weight,
     term-id) slot. vmapped dynamic_slice → contiguous DMA, no gather.
  3. One stable sort of [R, T·L_c] by doc id — the multi-way postings merge
     (ConjunctionDISI/BooleanScorer analog) as a single sort.
  4. Windowed same-key sum: a doc appears in at most T slots, so the
     segmented sum over equal-doc runs is a T-tap shifted add — no
     associative_scan (tuple-carry scans blow up TPU compile time).
  5. run-end mask + lax.top_k over the sparse candidate axis (size T·L_c,
     NOT the doc axis) — top-1000 never touches a dense [D] array.

Semantics per row: OR-of-slots with msm support. The clause count per doc
is the equal-doc run length, which is exact because each slot holds a doc
at most once (postings rows have unique docs, and chunks of one term
partition its row). Ties break like Lucene: equal scores → smaller doc id
(sorted axis + top_k's earliest-index-wins).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = float("-inf")


def segmented_run_sum(sk: jax.Array, sv: jax.Array,
                      t_window: int) -> jax.Array:
    """Inclusive per-run prefix sums over a key-sorted [R, L] pair via
    Hillis-Steele doubling: after ceil(log2(t_window)) steps, each
    run-end position holds its run's full sum. Replaces the old linear
    T-tap shifted-add (VERDICT r4 weak #8): work/compile now scale with
    log(T), so 32+ term queries (multi_match / fuzzy expansions) stay
    on the kernel path instead of falling off it."""
    length = sk.shape[1]
    total = sv
    step = 1
    while step < t_window:
        shifted_t = jnp.pad(total, ((0, 0), (step, 0)))[:, :length]
        shifted_k = jnp.pad(sk, ((0, 0), (step, 0)),
                            constant_values=-1)[:, :length]
        total = total + jnp.where(shifted_k == sk, shifted_t, 0.0)
        step *= 2
    return total


@partial(jax.jit, static_argnames=("max_len", "d_pad", "k", "t_window",
                                   "with_counts", "with_totals"))
def sorted_merge_topk(
    flat_docs: jax.Array,    # int32[P_flat] postings doc ids (pad = d_pad)
    flat_impact: jax.Array,  # f32[P_flat] eager BM25 impacts
    starts: jax.Array,       # int32[R, T] absolute offsets into flat arrays
    lengths: jax.Array,      # int32[R, T] chunk lengths (0 = empty slot)
    weights: jax.Array,      # f32[R, T] idf·(k1+1)·boost per slot
    min_count: jax.Array,    # int32[R] minimum matched clauses (msm/AND)
    *,
    max_len: int,            # static: chunk length L_c
    d_pad: int,              # static: doc-axis pad (sentinel doc id)
    k: int,                  # static: top-k
    t_window: int,           # static: T (slot count = max same-doc entries)
    with_counts: bool,       # static: evaluate min_count (msm/AND)
    with_totals: bool = False,  # static: also return matched-doc counts
) -> Tuple[jax.Array, ...]:
    """→ (scores f32[R, k'], doc_ids int32[R, k'][, totals int32[R]]);
    empty lanes are (-inf, d_pad). k' = min(k, T·L_c). totals (when
    with_totals) is the exact per-row count of matching docs — the
    TotalHits value of the reference's query phase."""
    r, t_slots = starts.shape
    idx = jnp.arange(max_len, dtype=jnp.int32)

    def slice_one(s):
        return (jax.lax.dynamic_slice(flat_docs, (s,), (max_len,)),
                jax.lax.dynamic_slice(flat_impact, (s,), (max_len,)))

    docs, imps = jax.vmap(jax.vmap(slice_one))(starts)     # [R, T, L]
    valid = idx[None, None, :] < lengths[:, :, None]
    docs = jnp.where(valid, docs, d_pad)
    imp = jnp.where(valid, weights[:, :, None] * imps, 0.0)

    length = t_slots * max_len
    sk, sv = jax.lax.sort(
        [docs.reshape(r, length), imp.reshape(r, length)], num_keys=1)

    total = segmented_run_sum(sk, sv, t_window)

    run_end = jnp.concatenate(
        [sk[:, :-1] != sk[:, 1:], jnp.ones((r, 1), bool)], axis=1)
    ok = run_end & (sk < d_pad) & (total > 0)

    if with_counts:
        # clause count per doc = run length (each slot holds a doc at most
        # once: postings rows have unique docs, chunks of one term
        # partition its row). Runs are ≤ t_window long by the same
        # argument, so the log-step scan sees the whole run.
        cnt = segmented_run_sum(sk, jnp.ones_like(sv), t_window)
        ok = ok & (cnt >= min_count[:, None].astype(jnp.float32))

    score = jnp.where(ok, total, NEG_INF)
    vals, pos = jax.lax.top_k(score, min(k, length))
    hit_docs = jnp.take_along_axis(sk, pos, axis=1)
    hit_docs = jnp.where(vals > NEG_INF, hit_docs, d_pad)
    if with_totals:
        return vals, hit_docs, jnp.sum(ok, axis=1, dtype=jnp.int32)
    return vals, hit_docs


# ---------------------------------------------------------------------------
# host-side slot planning
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SlotPlan:
    """Chunked term slots for a batch of rows (query × shard pairs)."""

    starts: np.ndarray    # int32[R, T]
    lengths: np.ndarray   # int32[R, T]
    weights: np.ndarray   # f32[R, T]
    min_count: np.ndarray  # int32[R]
    max_len: int          # L_c (static bucket)
    t_slots: int          # T (static)
    window: int           # max same-doc entries per row = max terms/row
                          # (chunks of one term partition docs, so the
                          # kernel's t_window only needs to cover TERMS,
                          # not slots — far fewer taps on chunked queries)


def _len_bucket(n: int, lane: int = 128) -> int:
    b = lane
    while b < n:
        b *= 2
    return b


def _cap_bucket(cap: int, lane: int) -> int:
    """Largest lane-based power-of-two bucket that does NOT exceed cap
    (rounding the cap UP would overrun callers' flat-array slack)."""
    b = lane
    while b * 2 <= cap:
        b *= 2
    return b


def plan_slots(rows: Sequence[Sequence[Tuple[int, int, float, int]]],
               min_counts: Sequence[int],
               chunk_cap: int = 4096,
               lane: int = 128) -> SlotPlan:
    """rows[r] = [(start, length, weight, term_id), ...] — one entry per
    query term with its postings-row extent in the flat arrays. Long rows
    split into chunks of ≤ L_c where L_c = min(bucket(max row length),
    largest bucket ≤ chunk_cap). Returns padded static-shape slot tensors."""
    longest = 1
    window = 1
    for row in rows:
        window = max(window, len(row))
        for (_, ln, _, _) in row:
            longest = max(longest, ln)
    max_len = min(_len_bucket(longest, lane), _cap_bucket(chunk_cap, lane))

    chunked: List[List[Tuple[int, int, float, int]]] = []
    t_needed = 1
    for row in rows:
        out = []
        for (s, ln, w, tid) in row:
            off = 0
            while off < ln:
                take = min(max_len, ln - off)
                out.append((s + off, take, w, tid))
                off += take
            if ln == 0:
                # keep empty terms as zero-length slots so min_count
                # semantics see the term as present-but-unmatched
                out.append((s, 0, w, tid))
        chunked.append(out)
        t_needed = max(t_needed, len(out))
    t_slots = 1
    while t_slots < t_needed:
        t_slots *= 2

    r = len(rows)
    starts = np.zeros((r, t_slots), dtype=np.int32)
    lengths = np.zeros((r, t_slots), dtype=np.int32)
    weights = np.zeros((r, t_slots), dtype=np.float32)
    for ri, out in enumerate(chunked):
        for ti, (s, ln, w, _tid) in enumerate(out[:t_slots]):
            starts[ri, ti] = s
            lengths[ri, ti] = ln
            weights[ri, ti] = w
    return SlotPlan(starts, lengths, weights,
                    np.asarray(min_counts, dtype=np.int32), max_len, t_slots,
                    window)


def eager_impacts(flat_docs: np.ndarray, flat_tfs: np.ndarray,
                  norms_u8: np.ndarray, k1: float, b: float,
                  avgdl: float) -> np.ndarray:
    """Precompute per-posting BM25 impacts (step 1 above). norms_u8 is the
    doc-axis norm column; flat_docs indexes into it (pad sentinel rows get
    impact 0 via tf==0)."""
    from elasticsearch_tpu.ops.smallfloat import LENGTH_TABLE
    d = norms_u8.shape[0]
    safe = np.minimum(flat_docs, d - 1)
    dl = LENGTH_TABLE[norms_u8[safe].astype(np.int64)].astype(np.float32)
    denom_add = (k1 * (1.0 - b + b * dl / (avgdl if avgdl > 0 else 1.0))
                 ).astype(np.float32)
    tf = flat_tfs.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        imp = tf / (tf + denom_add)
    return np.where(flat_tfs > 0, imp, 0.0).astype(np.float32)
