"""BM25 scoring + boolean-mask kernels (JAX/XLA).

This module replaces the reference's per-segment hot loop (SURVEY.md §3.3:
Weight#bulkScorer → postings decode → BM25Similarity$BM25Scorer#score →
TopScoreDocCollector#collect) with batched array programs:

  score_and_mask:   micro-batch of B queries × one segment pack → dense
                    per-doc BM25 accumulators [B, D_pad] plus a per-doc
                    term-presence bitmask [B, D_pad] (bit t set ⇔ query
                    term-slot t matched the doc). Because a single term's
                    postings list never repeats a doc, scatter-ADD of
                    (1 << t) is an exact bitwise OR.
  eval_bool_masks:  flat boolean algebra over the bitmask — must (AND over
                    clauses, OR within), must_not, minimum_should_match —
                    the ConjunctionDISI / BooleanScorer analog, evaluated
                    densely instead of by doc-at-a-time leapfrog.
  range_mask_*:     doc-values range filters (numeric/date).
  topk:             TopScoreDocCollector analog via lax.top_k (ties break
                    toward the smaller doc id, matching Lucene).

Shapes are static per (T, L, D_pad) signature; the planner buckets query
term counts and postings lengths so the jit cache stays small (SURVEY.md
§7.3#1). The scoring formula is exactly Lucene's (§3.3):

    idf(t) · (k1+1) · tf / (tf + k1·(1−b+b·dl/avgdl))

with dl decoded from the SmallFloat4 norm byte via the 256-entry table and
idf/avgdl computed from SHARD-level stats at query time (§7.3#2). The idf
factor (and any query boost) arrives premultiplied per term slot.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


@functools.partial(jax.jit, static_argnames=("max_len", "d_pad"))
def score_and_mask(
    flat_docs: jax.Array,    # int32[P_pad] postings doc ids, pad = d_pad
    flat_tfs: jax.Array,     # int32[P_pad]
    norms_u8: jax.Array,     # uint8[D_pad]
    norm_cache: jax.Array,   # f32[256] = k1*(1-b+b*LENGTH_TABLE/avgdl)
    starts: jax.Array,       # int32[B, T] row start offsets into flat arrays
    lengths: jax.Array,      # int32[B, T] row lengths (0 = absent term)
    idf_boost: jax.Array,    # f32[B, T]  idf * (k1+1) * boost; 0 ⇒ non-scoring slot
    *,
    max_len: int,            # static: padded postings length bucket
    d_pad: int,              # static: padded doc-axis size
) -> Tuple[jax.Array, jax.Array]:
    """→ (scores f32[B, D_pad+1], termmask int32[B, D_pad+1]).

    The +1 column is the scatter drop-slot for padded lanes; callers slice
    it off (or keep it — topk over D_pad+1 with -inf there is also fine).
    Sequential scan over term slots keeps peak memory at B×max_len instead
    of B×T×max_len (stopword-scale postings would otherwise blow HBM)."""
    b, t = starts.shape
    norms_i32 = norms_u8.astype(jnp.int32)
    idx = jnp.arange(max_len, dtype=jnp.int32)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]

    def gather_one(s, ln):
        # NOT dynamic_slice: it clamps out-of-bounds starts, which would
        # silently read a neighboring term's postings when a row sits closer
        # than max_len to the end of the flat array. OOB lanes fill with the
        # drop sentinel instead.
        pos = s + idx
        docs = jnp.take(flat_docs, pos, mode="fill", fill_value=d_pad)
        tfs = jnp.take(flat_tfs, pos, mode="fill", fill_value=0)
        valid = idx < ln
        return jnp.where(valid, docs, d_pad), jnp.where(valid, tfs, 0)

    scores = jnp.zeros((b, d_pad + 1), dtype=jnp.float32)
    mask = jnp.zeros((b, d_pad + 1), dtype=jnp.int32)

    # unrolled python loop over T (T is small and static) — keeps each slot's
    # presence bit a compile-time constant and bounds peak memory at B×max_len
    for slot in range(t):
        start, length, w = starts[:, slot], lengths[:, slot], idf_boost[:, slot]
        docs, tfs = jax.vmap(gather_one)(start, length)       # [B, L]
        # norm lookup: dl term of the BM25 denominator for each matched doc
        safe_docs = jnp.minimum(docs, d_pad - 1)
        denom_add = norm_cache[norms_i32[safe_docs]]          # [B, L]
        tf = tfs.astype(jnp.float32)
        impact = w[:, None] * tf / (tf + denom_add)           # [B, L]
        impact = jnp.where(tfs > 0, impact, 0.0)
        scores = scores.at[rows, docs].add(impact, mode="drop")
        matched = jnp.where(tfs > 0, jnp.int32(1) << slot, 0)
        mask = mask.at[rows, docs].add(matched, mode="drop")
    return scores, mask


@jax.jit
def eval_bool_masks(
    termmask: jax.Array,      # int32[B, D]
    must_masks: jax.Array,    # int32[B, C]; 0 ⇒ neutral (always satisfied)
    must_not_mask: jax.Array, # int32[B];   0 ⇒ nothing excluded
    should_masks: jax.Array,  # int32[B, S]; 0 ⇒ ignored slot
    min_should_match: jax.Array,  # int32[B]
) -> jax.Array:
    """Flat one-level boolean evaluation → bool[B, D] match mask.

    must clause  : OR-of-terms (mask & clause) != 0, AND across clauses
    must_not     : (mask & mnm) == 0
    should       : count of matched should clauses >= min_should_match
    Nested bools are evaluated recursively by the planner by combining the
    masks this returns (SURVEY.md §7.3#7)."""
    tm = termmask[:, None, :]                                  # [B, 1, D]
    must = must_masks[:, :, None]                              # [B, C, 1]
    must_ok = jnp.all(((tm & must) != 0) | (must == 0), axis=1)  # [B, D]
    mn_ok = (termmask & must_not_mask[:, None]) == 0
    should = should_masks[:, :, None]
    should_hits = jnp.sum(((tm & should) != 0) & (should != 0), axis=1)
    should_ok = should_hits >= min_should_match[:, None]
    return must_ok & mn_ok & should_ok


@jax.jit
def range_mask_i64(col: jax.Array, lo: jax.Array, hi: jax.Array,
                   include_missing_sentinel: bool = False) -> jax.Array:
    """col i64[D]; lo/hi i64[B] → bool[B, D]. Missing sentinel (int64 min)
    never matches because lo > sentinel for any real bound."""
    return (col[None, :] >= lo[:, None]) & (col[None, :] <= hi[:, None])


@jax.jit
def range_mask_f64(col: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    ok = (col[None, :] >= lo[:, None]) & (col[None, :] <= hi[:, None])
    return ok & ~jnp.isnan(col)[None, :]


@functools.partial(jax.jit, static_argnames=("k",))
def topk(scores: jax.Array, *, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k per query row with Lucene tie-breaking (equal scores → smaller
    doc id wins). Routed through the hierarchical per-block reduction
    (sparse.hierarchical_top_k, PERF.md round 8), which is selection- AND
    tie-break-identical to lax.top_k — equal-score winners still come out
    in ascending doc-ordinal order — while shrinking the full-width sort
    network on wide (padded-doc-axis) score rows. Narrow or non-block
    widths fall back to lax.top_k inside the helper."""
    from elasticsearch_tpu.ops.sparse import hierarchical_top_k
    k = min(k, scores.shape[-1])
    return hierarchical_top_k(scores, k)


@jax.jit
def mask_scores(scores: jax.Array, match: jax.Array,
                live: jax.Array) -> jax.Array:
    """Apply the boolean match mask + live-docs (tombstone) mask: docs that
    fail either get -inf so they never surface in top-k."""
    ok = match & live[None, :]
    return jnp.where(ok, scores, NEG_INF)
