"""Fused Pallas spelling of the compressed merge kernel (PR 15).

One pl.pallas_call carries the whole hot loop the XLA variants spread
over separate ops: phase-A posting gather from the compressed u16/u8
resident streams, the packed single-key merge sort, the block-max skip
branch (the running top-k threshold lives INSIDE the kernel instead of
a separate masking pass) and per-block top-k selection + exact rescore.
The kernel grids over rows — each program instance owns one (query ×
shard) row's slot table, while the flat posting streams stay resident
in device memory and are sliced per slot inside the kernel, so the
intermediate sorted-operand materialisation between gather and merge
never round-trips through HBM.

Dispatch is backend-aware: on TPU the kernel compiles through Mosaic;
everywhere else it runs under interpret=True, which executes the exact
same trace the XLA "compressed" variant lowers from — the parity sweep
(tests/test_sparse_kernel.py) pins variant="pallas" bit-identical to
variant="ref" on CPU by construction. Real-chip soak is still pending
(README "kernel variants"): Mosaic support for lax.sort/top_k inside a
kernel varies by jaxlib generation, so serving keeps the variant behind
the `search.tpu_serving.kernel.pallas` knob with the same typed
fallback gates (planner.choose_kernel_variant) as the other variants,
and falls back to the plain core if Pallas itself is unavailable.

Operands, outputs, gates and semantics match
sparse.sorted_merge_topk(variant="compressed") exactly; see ops/sparse.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops import sparse

try:  # pragma: no cover - exercised by presence, not by a branch test
    from jax.experimental import pallas as pl
    _PALLAS_IMPORT_ERROR = None
except Exception as _e:  # pallas missing from this jaxlib build
    pl = None
    _PALLAS_IMPORT_ERROR = _e

#: names and order of the optional operands the kernel may receive after
#: the six required ones; absent operands are simply not passed
_OPTIONAL_OPERANDS = ("flat_rank", "res_starts", "res_lens", "res_vals",
                      "block_max", "blk_starts", "slot_terms",
                      "doc_bases", "dbs_starts", "dlo_starts")


def available() -> bool:
    """May variant="pallas" run in this process? False routes the
    planner (and direct callers) to the plain compressed core — the
    same typed-fallback style as the d_pad/weight gates."""
    return pl is not None


def fused_merge_topk(
    flat_docs: jax.Array,
    flat_impact: jax.Array,
    starts: jax.Array,
    lengths: jax.Array,
    weights: jax.Array,
    min_count: jax.Array,
    *,
    max_len: int,
    d_pad: int,
    k: int,
    t_window: int,
    with_counts: bool,
    with_totals: bool = False,
    flat_rank: Optional[jax.Array] = None,
    res_starts: Optional[jax.Array] = None,
    res_lens: Optional[jax.Array] = None,
    res_vals: Optional[jax.Array] = None,
    block_max: Optional[jax.Array] = None,
    blk_starts: Optional[jax.Array] = None,
    slot_terms: Optional[jax.Array] = None,
    doc_bases: Optional[jax.Array] = None,
    dbs_starts: Optional[jax.Array] = None,
    dlo_starts: Optional[jax.Array] = None,
) -> Tuple[jax.Array, ...]:
    """sorted_merge_topk(variant="pallas"): the compressed pipeline as
    one row-gridded Pallas kernel. Returns (scores, doc_ids[, totals])
    bit-identical to variant="compressed" on the same operands."""
    core_kw = dict(
        max_len=max_len, d_pad=d_pad, k=k, t_window=t_window,
        with_counts=with_counts, with_totals=with_totals,
        variant="compressed")
    optional = {
        "flat_rank": flat_rank, "res_starts": res_starts,
        "res_lens": res_lens, "res_vals": res_vals,
        "block_max": block_max, "blk_starts": blk_starts,
        "slot_terms": slot_terms, "doc_bases": doc_bases,
        "dbs_starts": dbs_starts, "dlo_starts": dlo_starts}
    if pl is None:
        # typed fallback — never error: the plain core computes the
        # same bits this kernel would
        return sparse._merge_topk_core(
            flat_docs, flat_impact, starts, lengths, weights, min_count,
            **core_kw, **optional)

    r, t_slots = starts.shape
    kk = min(k, t_slots * max_len)

    #: [R, T]-shaped operands are row-blocked (one program instance per
    #: row); flat streams/tables are whole-array blocks every instance
    #: reads through (resident, sliced per slot inside the kernel)
    per_row = {"starts", "lengths", "weights", "res_starts", "res_lens",
               "blk_starts", "slot_terms", "dbs_starts", "dlo_starts"}

    names = ["flat_docs", "flat_impact", "starts", "lengths", "weights",
             "min_count"]
    operands = [flat_docs, flat_impact, starts, lengths, weights,
                min_count]
    for name in _OPTIONAL_OPERANDS:
        if optional[name] is not None:
            names.append(name)
            operands.append(optional[name])

    def spec_for(name, arr):
        if name == "min_count":
            return pl.BlockSpec((1,), lambda i: (i,))
        if name in per_row:
            return pl.BlockSpec((1, arr.shape[1]), lambda i: (i, 0))
        shape = arr.shape
        return pl.BlockSpec(shape, lambda i: (0,) * len(shape))

    in_specs = [spec_for(n, a) for n, a in zip(names, operands)]
    out_shape = [jax.ShapeDtypeStruct((r, kk), jnp.float32),
                 jax.ShapeDtypeStruct((r, kk), jnp.int32)]
    out_specs = [pl.BlockSpec((1, kk), lambda i: (i, 0)),
                 pl.BlockSpec((1, kk), lambda i: (i, 0))]
    if with_totals:
        out_shape.append(jax.ShapeDtypeStruct((r,), jnp.int32))
        out_specs.append(pl.BlockSpec((1,), lambda i: (i,)))

    def kernel(*refs):
        in_refs = refs[:len(names)]
        out_refs = refs[len(names):]
        vals = dict(zip(names, (ref[...] for ref in in_refs)))
        extras = {name: vals.get(name) for name in _OPTIONAL_OPERANDS}
        out = sparse._merge_topk_core(
            vals["flat_docs"], vals["flat_impact"], vals["starts"],
            vals["lengths"], vals["weights"], vals["min_count"],
            **core_kw, **extras)
        for ref, val in zip(out_refs, out):
            ref[...] = val

    # real kernel on TPU, interpret elsewhere: the interpreter executes
    # the same jax trace the XLA variant compiles, so CPU parity is
    # bitwise by construction rather than by tolerance
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(r,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    return tuple(out)
