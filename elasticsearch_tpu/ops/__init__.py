"""TPU kernels: BM25 scoring, boolean masks, top-k, SmallFloat norms.

This package replaces the reference's L0 query-time kernels (SURVEY.md §1,
§3.3): postings decode + intersection + BM25 + top-k become array programs.

64-bit mode is enabled process-wide: doc-values columns are i64 (date
millis and longs overflow i32) and postings offsets may exceed 2^31 on
large shards. All hot-path arrays declare explicit narrow dtypes (f32/i32/
u8), so this does not widen the scoring kernels.
"""

import jax

jax.config.update("jax_enable_x64", True)
