"""Lucene SmallFloat byte4 encoding — the lossy 1-byte norm.

Reference: Lucene's org.apache.lucene.util.SmallFloat (intToByte4 /
byte4ToInt), used by BM25Similarity to store each document's field length in
one byte (SURVEY.md §3.3: "norm = 1-byte SmallFloat-encoded doc length
(lossy!) — decoded via 256-entry lookup table"). Exact replication is a
parity requirement (§7.3#2): scores drift silently otherwise.

Encoding: values 0..7 (i.e. <4 bits) are stored verbatim ("subnormal");
larger values keep the top 4 significant bits — an implicit leading 1, 3
mantissa bits, and a 5-bit shift stored +1.
"""

from __future__ import annotations

import numpy as np


def int_to_byte4(i: int) -> int:
    """Lucene SmallFloat.intToByte4 (via longToInt4). 0 <= i; returns 0..255."""
    if i < 0:
        raise ValueError(f"only non-negative values accepted: {i}")
    num_bits = i.bit_length()
    if num_bits < 4:
        return i
    shift = num_bits - 4
    encoded = (i >> shift) & 0x07
    encoded |= (shift + 1) << 3
    return encoded


def byte4_to_int(b: int) -> int:
    """Lucene SmallFloat.byte4ToInt (via int4ToLong). b is 0..255."""
    bits = b & 0x07
    shift = (b >> 3) - 1
    if shift == -1:
        return bits
    return (bits | 0x08) << shift


# 256-entry decode table: LENGTH_TABLE[norm_byte] = decoded field length
LENGTH_TABLE = np.array([byte4_to_int(b) for b in range(256)], dtype=np.int64)


def encode_norm(field_length: int) -> int:
    """Field length (token count) → 1-byte norm, exactly as
    BM25Similarity#computeNorm does (intToByte4 of the length)."""
    return int_to_byte4(max(0, int(field_length)))


def decode_norms(norm_bytes: np.ndarray) -> np.ndarray:
    """u8 norms → decoded field lengths (i64)."""
    return LENGTH_TABLE[norm_bytes.astype(np.int64)]


def encode_norms(field_lengths: np.ndarray) -> np.ndarray:
    """Vectorized intToByte4 over an i64 field-length column (the bulk
    write path's norms build). Exact for lengths < 2^53 — np.frexp's
    exponent IS the bit length there."""
    v = np.maximum(field_lengths.astype(np.int64), 0)
    _, nb = np.frexp(v.astype(np.float64))  # bit length (0 for v == 0)
    shift = np.maximum(nb - 4, 0).astype(np.int64)
    enc = np.where(nb < 4, v, ((v >> shift) & 0x07) | ((shift + 1) << 3))
    return enc.astype(np.uint8)


def bm25_norm_cache(k1: float, b: float, avgdl: float) -> np.ndarray:
    """The per-norm-byte BM25 denominator term, as Lucene's BM25Scorer caches:
    cache[n] = k1 * (1 - b + b * LENGTH_TABLE[n] / avgdl); the score is then
    idf * (k1+1) * tf / (tf + cache[norm]) (SURVEY.md §3.3 formula)."""
    if avgdl <= 0:
        avgdl = 1.0
    return (k1 * ((1.0 - b) + b * LENGTH_TABLE.astype(np.float64) / avgdl)).astype(np.float32)


def idf(doc_freq: np.ndarray, doc_count: int) -> np.ndarray:
    """Lucene BM25 idf: ln(1 + (N - n + 0.5) / (n + 0.5)), with SHARD-level
    N (docCount) and n (docFreq) (SURVEY.md §3.3, §7.3#2)."""
    n = np.asarray(doc_freq, dtype=np.float64)
    return np.log(1.0 + (doc_count - n + 0.5) / (n + 0.5)).astype(np.float32)
