"""Exact scalar reference implementation of Lucene BM25 scoring (numpy).

This is the parity oracle (SURVEY.md §7.2 phase 3: "Parity harness: same
corpus through a knowledge-equivalent reimplementation of the formula —
score-level diff") and doubles as the CPU baseline scorer for bench.py.
It mirrors the reference hot path (§3.3) doc-at-a-time semantics:

  per segment: for each query term with df>0
      idf = ln(1 + (N - n + 0.5)/(n + 0.5))           # SHARD-level N, n
      for (doc, tf) in postings:
          dl = LENGTH_TABLE[norm_byte[doc]]            # lossy SmallFloat4
          score[doc] += boost · idf · (k1+1) · tf / (tf + k1(1-b+b·dl/avgdl))
  top-k by (score desc, doc id asc)                    # Lucene tie-break
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.index.segment import Segment
from elasticsearch_tpu.ops.smallfloat import LENGTH_TABLE, encode_norm


def shard_stats(segments: Sequence[Segment], field: str) -> Tuple[int, float]:
    """→ (doc_count, avgdl) at shard level, as Lucene CollectionStatistics
    computes them: docCount = docs that have the field, avgdl =
    sumTotalTermFreq / docCount (SURVEY.md §7.3#2)."""
    doc_count = 0
    sum_ttf = 0
    for seg in segments:
        st = seg.field_stats.get(field)
        if st:
            doc_count += st.doc_count
            sum_ttf += st.sum_total_term_freq
    avgdl = (sum_ttf / doc_count) if doc_count else 1.0
    return doc_count, avgdl


def shard_doc_freq(segments: Sequence[Segment], field: str, term: str) -> int:
    return sum(seg.doc_freq(field, term) for seg in segments)


def bm25_idf(doc_count: int, doc_freq: int) -> float:
    return math.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5))


def score_segment(
    segment: Segment,
    field: str,
    terms: Sequence[str],
    *,
    doc_count: int,
    avgdl: float,
    doc_freqs: Dict[str, int],
    k1: float = 1.2,
    b: float = 0.75,
    boost: float = 1.0,
) -> np.ndarray:
    """Dense per-doc scores (f32) for an OR-of-terms (match) query over one
    segment, using shard-level stats. Lossy norm decode included: the norm
    byte round-trips through SmallFloat4 exactly as at index time."""
    scores = np.zeros(segment.num_docs, dtype=np.float64)
    norms = segment.norms.get(field)
    if norms is None:
        return scores.astype(np.float32)
    dl = LENGTH_TABLE[norms.astype(np.int64)].astype(np.float64)
    denom_add = k1 * (1.0 - b + b * dl / (avgdl if avgdl > 0 else 1.0))
    # float32 cache like Lucene's per-norm cache
    denom_add = denom_add.astype(np.float32).astype(np.float64)
    for term in terms:
        entry = segment.postings.get(field, {}).get(term)
        if entry is None:
            continue
        n = doc_freqs.get(term, 0)
        if n <= 0:
            continue
        idf = bm25_idf(doc_count, n)
        docs, tfs = entry
        tf = tfs.astype(np.float64)
        w = boost * idf * (k1 + 1.0)
        scores[docs] += w * tf / (tf + denom_add[docs])
    return scores.astype(np.float32)


def score_match_query(
    segments: Sequence[Segment],
    field: str,
    terms: Sequence[str],
    k1: float = 1.2,
    b: float = 0.75,
) -> List[np.ndarray]:
    """Score a match query across all segments of a shard with shard-level
    stats — one dense score array per segment."""
    doc_count, avgdl = shard_stats(segments, field)
    dfs = {t: shard_doc_freq(segments, field, t) for t in terms}
    return [
        score_segment(seg, field, terms, doc_count=doc_count, avgdl=avgdl,
                      doc_freqs=dfs, k1=k1, b=b)
        for seg in segments
    ]


def topk_from_scores(scores: np.ndarray, k: int,
                     min_score: float = 0.0) -> List[Tuple[int, float]]:
    """(doc, score) descending, ties toward smaller doc id; drops scores
    <= min_score (non-matches)."""
    if len(scores) == 0:
        return []
    k = min(k, len(scores))
    # argsort on (-score, doc) gives Lucene order; scores are descending, so
    # the first below-threshold entry ends the scan
    order = np.lexsort((np.arange(len(scores)), -scores))
    out = []
    for doc in order:
        s = float(scores[doc])
        if s <= min_score:
            break
        out.append((int(doc), s))
        if len(out) == k:
            break
    return out
