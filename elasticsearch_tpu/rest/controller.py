"""RestController — path-trie routing of REST requests to handlers.

Reference: `rest/RestController#dispatchRequest` (SURVEY.md §2.1#10): a
path trie with literal and `{param}` wildcard nodes; handlers parse the
request into transport actions. Error shape follows the reference's
`ElasticsearchException` REST serialization: {"error": {"type", "reason",
"root_cause": [...]}, "status": N}.
"""

from __future__ import annotations

import dataclasses
import json
import re
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common import errors as es_errors
from elasticsearch_tpu.common import profiler as _profiler
from elasticsearch_tpu.common import tenancy as _tenancy
from elasticsearch_tpu.common import tracing as _tracing


@dataclasses.dataclass
class RestRequest:
    method: str
    path: str
    params: Dict[str, str]          # query-string + path params
    body: Any                        # parsed JSON (dict) | raw str for NDJSON
    raw_body: bytes = b""

    def param(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self.params.get(key, default)

    def param_int(self, key: str, default: int = 0) -> int:
        v = self.params.get(key)
        return default if v is None else int(v)

    def param_bool(self, key: str, default: bool = False) -> bool:
        v = self.params.get(key)
        if v is None:
            return default
        return v in ("", "true", "1")


Handler = Callable[[RestRequest], Tuple[int, Dict[str, Any]]]


class _TrieNode:
    __slots__ = ("children", "wildcard", "wildcard_name", "handlers")

    def __init__(self):
        self.children: Dict[str, "_TrieNode"] = {}
        self.wildcard: Optional["_TrieNode"] = None
        self.wildcard_name: Optional[str] = None
        self.handlers: Dict[str, Handler] = {}


STATUS_BY_EXC = [
    (es_errors.ResourceNotFoundException, 404),
    (es_errors.DocumentMissingException, 404),
    (es_errors.ResourceAlreadyExistsException, 400),
    (es_errors.VersionConflictEngineException, 409),
    (es_errors.IllegalArgumentException, 400),
    (es_errors.ParsingException, 400),
    (es_errors.CircuitBreakingException, 429),
    (es_errors.EsRejectedExecutionException, 429),
    (es_errors.IndexBlockException, 403),
    (es_errors.ClusterBlockException, 503),
]


def error_status(exc: Exception) -> int:
    for klass, status in STATUS_BY_EXC:
        if isinstance(exc, klass):
            return status
    # any other EsException carries its own status (reference:
    # ElasticsearchException#status)
    if isinstance(exc, es_errors.EsException):
        return int(getattr(exc, "status", 500))
    return 500


def error_body(exc: Exception, status: int) -> Dict[str, Any]:
    if isinstance(exc, es_errors.EsException):
        # structured rendering (type/reason plus metadata such as a
        # SearchPhaseExecutionException's phase and failed_shards)
        body = exc.to_xcontent()
        cause = {"type": body["type"], "reason": body["reason"]}
        return {"error": {"root_cause": [cause], **body}, "status": status}
    t = type(exc).__name__
    # CamelCase → snake_case exception type names like the reference
    snake = re.sub(r"(?<!^)(?=[A-Z])", "_", t).lower()
    snake = snake.replace("_exception", "_exception")
    cause = {"type": snake, "reason": str(exc)}
    return {"error": {"root_cause": [cause], **cause}, "status": status}


def rejection_headers(exc: Exception, status: int
                      ) -> Optional[Dict[str, str]]:
    """Backoff headers for overload/unavailable answers: every 429/503
    carries `Retry-After` so clients across all rejection paths
    (pressure, backpressure, tenant quota, degraded serving) back off
    the same way. Rides the payload as a reserved `_headers` key —
    dispatch returns (status, body) with no header channel — which the
    HTTP edges (node handler, front wire encoder) pop and emit."""
    if status not in (429, 503):
        return None
    retry_after = getattr(exc, "retry_after_s", 1.0)
    try:
        retry_after = max(1, int(round(float(retry_after))))
    except (TypeError, ValueError):
        retry_after = 1
    return {"Retry-After": str(retry_after)}


_SEARCH_SUFFIXES = ("_search", "_msearch", "_count", "_search_shards",
                    "_rank_eval")
_WRITE_SUFFIXES = ("_bulk", "_update_by_query", "_delete_by_query",
                   "_reindex")
_GET_SUFFIXES = ("_mget",)


def classify_pool(method: str, path: str) -> str:
    """Route → named thread pool (reference: each ActionType declares
    its executor). Doc CRUD is checked FIRST by position — an _id that
    happens to spell an endpoint name (`GET /idx/_doc/_search`) must not
    misroute — then API suffixes at their actual position (last segment;
    `_search/scroll` is the only two-segment tail). Management runs
    unpooled."""
    parts = path.strip("/").split("/")
    if len(parts) >= 2 and parts[1] in ("_doc", "_create", "_update"):
        return "get" if method in ("GET", "HEAD") else "write"
    last = parts[-1]
    if last in _SEARCH_SUFFIXES or (
            len(parts) >= 2 and parts[-2] == "_search"):
        return "search"
    if last in _WRITE_SUFFIXES:
        return "write"
    if last in _GET_SUFFIXES:
        return "get"
    return ""


def front_search_index(method: str, path: str,
                       params: Optional[Dict[str, str]] = None
                       ) -> Optional[str]:
    """The target index when (method, path) is the serving-front fast
    path — exactly ``/{index}/_search`` on a non-underscore index with
    no scroll continuation — else None (the front then proxies the raw
    request to the batcher's full dispatch). Import-light on purpose:
    front processes route with this before any body parse."""
    if method not in ("GET", "POST"):
        return None
    parts = path.strip("/").split("/")
    if len(parts) != 2 or parts[1] != "_search":
        return None
    index = parts[0]
    if not index or index.startswith("_"):
        return None
    if params and params.get("scroll"):
        return None
    return index


class RestController:
    def __init__(self):
        self._root = _TrieNode()
        # set by the node: ThreadPools admission gates per request class
        self.thread_pools = None
        # set by the node: per-request root spans (None ⇒ no tracing)
        self.tracer = None

    def register(self, method: str, template: str, handler: Handler) -> None:
        node = self._root
        for part in template.strip("/").split("/"):
            if not part:
                continue
            if part.startswith("{") and part.endswith("}"):
                if node.wildcard is None:
                    node.wildcard = _TrieNode()
                    node.wildcard_name = part[1:-1]
                node = node.wildcard
            else:
                node = node.children.setdefault(part, _TrieNode())
        node.handlers[method.upper()] = handler

    def _resolve(self, path: str) -> Tuple[Optional[_TrieNode], Dict[str, str]]:
        node = self._root
        params: Dict[str, str] = {}
        for part in path.strip("/").split("/"):
            if not part:
                continue
            nxt = node.children.get(part)
            if nxt is None and node.wildcard is not None:
                params[node.wildcard_name] = part
                nxt = node.wildcard
            if nxt is None:
                return None, {}
            node = nxt
        return node, params

    def dispatch(self, method: str, path: str,
                 query_params: Optional[Dict[str, str]] = None,
                 body: Any = None,
                 raw_body: bytes = b"") -> Tuple[int, Dict[str, Any]]:
        node, path_params = self._resolve(path)
        if node is None or not node.handlers:
            return 400, error_body(
                es_errors.IllegalArgumentException(
                    f"no handler found for uri [{path}] and method [{method}]"),
                400)
        handler = node.handlers.get(method.upper())
        if handler is None:
            if method.upper() == "HEAD" and "GET" in node.handlers:
                handler = node.handlers["GET"]
            else:
                return 405, error_body(
                    es_errors.IllegalArgumentException(
                        f"incorrect HTTP method for uri [{path}]: allowed "
                        f"{sorted(node.handlers)}"), 405)
        params = dict(query_params or {})
        params.update(path_params)
        # trace context: adopt a caller-supplied `traceparent` (HTTP
        # header or query param — the caller's sampling decision wins),
        # else open a locally-sampled root span
        traceparent = params.pop("traceparent", None)
        # tenant identity: validated here at the admission boundary and
        # bound to the request thread — pressure charges, search quota,
        # batch lanes and task stamping all read the thread-local
        try:
            tenant = _tenancy.resolve_tenant(
                params.pop(_tenancy.TENANT_PARAM, None))
        except es_errors.IllegalArgumentException as exc:
            return 400, error_body(exc, 400)
        req = RestRequest(method.upper(), path, params, body, raw_body)
        span = None
        tracer = self.tracer
        if tracer is not None and (traceparent or tracer.enabled):
            span = tracer.start_span(
                f"rest {req.method} {path}",
                parent=_tracing.parse_traceparent(traceparent),
                attributes={"http.method": req.method, "http.path": path},
                root=True)
            if not span.is_recording:
                span = None
            elif tenant != _tenancy.DEFAULT_TENANT:
                # tenant-stamped root spans make /_tpu/traces?tenant=
                # and the slowlog attribution work; the default tenant
                # stays unstamped so single-tenant traces are unchanged
                span.set_attribute("tenant", tenant)
        # profiler thread tags: the sampling profiler can't read this
        # thread's locals, so publish (pool, trace_id) to its shared
        # ident map. `active()` is a single set-emptiness check — the
        # hot path pays nothing while the sampler is off.
        if _profiler.active():
            _profiler.tag_thread(
                classify_pool(req.method, path) or "management",
                span.trace_id if span is not None else None)
        prev_tenant = _tenancy.bind_tenant(tenant)
        try:
            if span is None:
                if self.thread_pools is not None:
                    with self.thread_pools.execute(
                            classify_pool(req.method, path)):
                        return handler(req)
                return handler(req)
            with _tracing.use_span(span):
                try:
                    if self.thread_pools is not None:
                        with self.thread_pools.execute(
                                classify_pool(req.method, path)):
                            status, payload = handler(req)
                    else:
                        status, payload = handler(req)
                except Exception as exc:
                    span.set_attribute(
                        "error", f"{type(exc).__name__}: {exc}")
                    span.set_attribute("http.status", error_status(exc))
                    raise
                else:
                    span.set_attribute("http.status", status)
                    return status, payload
                finally:
                    span.end()
        except Exception as exc:  # noqa: BLE001 — REST boundary
            status = error_status(exc)
            if status == 500:
                traceback.print_exc()
            payload = error_body(exc, status)
            headers = rejection_headers(exc, status)
            if headers:
                payload["_headers"] = headers
            return status, payload
        finally:
            _tenancy.bind_tenant(prev_tenant)
            _profiler.untag_thread()
