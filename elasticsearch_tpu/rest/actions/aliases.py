"""Alias REST actions (reference: RestIndexPutAliasAction,
RestIndicesAliasesAction, RestGetAliasesAction — SURVEY.md §2.1#49/50).
"""

from __future__ import annotations

from typing import Any, Dict, List

from elasticsearch_tpu.common.errors import (IllegalArgumentException,
                                             ResourceNotFoundException)
from elasticsearch_tpu.rest.controller import RestController, RestRequest


def _alias_map(node) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """alias → index → props, from whichever metadata is authoritative."""
    if node.cluster is not None:
        view = node.cluster._StateView(node.cluster.applied_state())
        return view.aliases
    return node.indices.aliases


def _apply_actions(node, actions: List[dict]):
    from elasticsearch_tpu.indices.service import parse_alias_action
    if node.cluster is not None:
        node.cluster.update_aliases(actions)
        return
    import fnmatch
    for action in actions:
        kind, idx_expr, alias, props = parse_alias_action(action)
        matched = ([n for n in node.indices.indices
                    if fnmatch.fnmatchcase(n, idx_expr)]
                   if ("*" in idx_expr or "?" in idx_expr)
                   else [idx_expr])
        for name in matched:
            if kind == "add":
                node.indices.put_alias(name, alias, props)
            else:
                node.indices.delete_alias(name, alias)


def register(controller: RestController, node) -> None:

    def put_alias(req: RestRequest):
        body = req.body or {}
        spec = {"index": req.param("index"), "alias": req.param("name")}
        if body.get("filter") is not None:
            spec["filter"] = body["filter"]
        if body.get("is_write_index"):
            spec["is_write_index"] = True
        _apply_actions(node, [{"add": spec}])
        return 200, {"acknowledged": True}

    def delete_alias(req: RestRequest):
        _apply_actions(node, [{"remove": {"index": req.param("index"),
                                          "alias": req.param("name")}}])
        return 200, {"acknowledged": True}

    def update_aliases(req: RestRequest):
        actions = (req.body or {}).get("actions")
        if not isinstance(actions, list) or not actions:
            raise IllegalArgumentException("[aliases] requires [actions]")
        _apply_actions(node, actions)
        return 200, {"acknowledged": True}

    def get_aliases(req: RestRequest):
        amap = _alias_map(node)
        want_alias = req.param("name")
        want_index = req.param("index")
        out: Dict[str, Dict[str, Any]] = {}
        import fnmatch
        for alias, targets in amap.items():
            if want_alias and not fnmatch.fnmatchcase(alias, want_alias):
                continue
            for index, props in targets.items():
                if want_index and index != want_index:
                    continue
                out.setdefault(index, {"aliases": {}})["aliases"][
                    alias] = props
        if want_alias and not out and "*" not in want_alias:
            raise ResourceNotFoundException(
                f"alias [{want_alias}] missing")
        if not want_alias:
            # every index appears, aliased or not (reference shape)
            names = (node.cluster.resolve_indices(want_index or "_all")
                     if node.cluster is not None else
                     [n for n in sorted(node.indices.indices)
                      if not want_index or n == want_index])
            for n in names:
                out.setdefault(n, {"aliases": {}})
        return 200, out

    def head_alias(req: RestRequest):
        amap = _alias_map(node)
        import fnmatch
        found = any(fnmatch.fnmatchcase(a, req.param("name"))
                    for a in amap)
        return (200, {}) if found else (404, {})

    controller.register("PUT", "/{index}/_alias/{name}", put_alias)
    controller.register("POST", "/{index}/_alias/{name}", put_alias)
    controller.register("PUT", "/{index}/_aliases/{name}", put_alias)
    controller.register("DELETE", "/{index}/_alias/{name}", delete_alias)
    controller.register("DELETE", "/{index}/_aliases/{name}",
                        delete_alias)
    controller.register("POST", "/_aliases", update_aliases)
    controller.register("GET", "/_alias", get_aliases)
    controller.register("GET", "/_alias/{name}", get_aliases)
    controller.register("GET", "/{index}/_alias", get_aliases)
    controller.register("GET", "/{index}/_alias/{name}", get_aliases)
    controller.register("HEAD", "/_alias/{name}", head_alias)
