"""Introspection / debugging APIs: _field_caps, _validate/query,
_explain, _termvectors, _nodes/hot_threads, _cluster/allocation/explain
(reference: FieldCapabilities*, TransportValidateQueryAction,
TransportExplainAction, TermVectorsService, HotThreads,
ClusterAllocationExplainAction — SURVEY.md §2.1#40/47/49/56, §5.1).
"""

from __future__ import annotations

import collections
import sys
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (DocumentMissingException,
                                             IllegalArgumentException,
                                             IndexNotFoundException,
                                             ResourceNotFoundException)
from elasticsearch_tpu.rest.controller import RestController, RestRequest

# field types that aggregate via doc-values columns
_AGGREGATABLE = {"keyword", "long", "integer", "short", "byte", "double",
                 "float", "half_float", "date", "boolean", "ip",
                 "rank_feature", "geo_point"}
_SEARCHABLE_EXTRA = {"dense_vector", "rank_feature", "geo_point"}


def field_caps(node, index_expr: Optional[str],
               fields_param: Optional[str]) -> Dict[str, Any]:
    """→ the _field_caps response: per field, per type, searchable /
    aggregatable, with the contributing indices listed (reference:
    FieldCapabilitiesResponse)."""
    import fnmatch

    from elasticsearch_tpu.search.coordinator import resolve_targets
    names, _filters = resolve_targets(node.indices, index_expr)
    patterns = [p.strip() for p in (fields_param or "*").split(",")
                if p.strip()]
    per_field: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for name in names:
        svc = node.indices.index(name)
        for path, ft in svc.mapper.mapper.fields.items():
            if not any(fnmatch.fnmatchcase(path, p) for p in patterns):
                continue
            t = ft.type_name
            entry = per_field.setdefault(path, {}).setdefault(t, {
                "type": t,
                "metadata_field": False,
                "searchable": bool(getattr(ft, "is_indexed", True))
                or t in _SEARCHABLE_EXTRA,
                "aggregatable": t in _AGGREGATABLE,
                "indices": []})
            entry["indices"].append(name)
    out_fields: Dict[str, Any] = {}
    for path, types in per_field.items():
        out: Dict[str, Any] = {}
        for t, entry in types.items():
            # `indices` is only reported when the field does NOT span
            # every target index (reference behavior)
            if len(entry["indices"]) == len(names):
                entry = {k: v for k, v in entry.items()
                         if k != "indices"}
            out[t] = entry
        out_fields[path] = out
    return {"indices": sorted(names), "fields": out_fields}


def validate_query(node, index_expr: Optional[str],
                   body: Optional[Dict[str, Any]],
                   explain: bool) -> Dict[str, Any]:
    from elasticsearch_tpu.search import dsl
    from elasticsearch_tpu.search.coordinator import resolve_targets
    names, _ = resolve_targets(node.indices, index_expr)
    spec = (body or {}).get("query") or {"match_all": {}}
    try:
        parsed = dsl.parse_query(spec)
    except Exception as exc:  # noqa: BLE001 — the point is to report it
        out = {"valid": False,
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if explain:
            out["error"] = str(exc)
        return out
    out = {"valid": True,
           "_shards": {"total": 1, "successful": 1, "failed": 0}}
    if explain:
        out["explanations"] = [
            {"index": name, "valid": True,
             "explanation": parsed.query_name()} for name in names]
    return out


def explain_doc(node, index: str, doc_id: str,
                body: Optional[Dict[str, Any]],
                params: Dict[str, str]) -> Dict[str, Any]:
    """GET /{index}/_explain/{id}: does the query match this doc, and
    with what score (reference: TransportExplainAction; the Lucene
    explanation tree is summarized — scores here come from one fused
    kernel, not a per-clause scorer walk)."""
    import numpy as np

    from elasticsearch_tpu.search import dsl
    from elasticsearch_tpu.search.planner import SegmentQueryExecutor
    spec = (body or {}).get("query")
    if spec is None:
        raise IllegalArgumentException("[_explain] requires a [query]")
    query = dsl.parse_query(spec)
    svc = node.indices.index(index)
    shard_num = svc.shard_for_id(doc_id, params.get("routing"))
    reader = svc.shard(shard_num).acquire_searcher()
    for view_idx, view in enumerate(reader.views):
        ord_ = view.segment.id_to_ord.get(doc_id)
        if ord_ is None or not view.live_mask[ord_]:
            continue
        mask, score = SegmentQueryExecutor(reader, view_idx).execute(
            query)
        matched = bool(np.asarray(mask)[ord_])
        value = float(np.asarray(score)[ord_]) if matched else 0.0
        desc = f"score({query.query_name()})" if matched else \
            "no matching clause"
        return {"_index": index, "_id": doc_id, "matched": matched,
                "explanation": {"value": value, "description": desc,
                                "details": []}}
    raise DocumentMissingException(f"[{doc_id}]: document missing")


def termvectors(node, index: str, doc_id: str,
                body: Optional[Dict[str, Any]],
                params: Dict[str, str]) -> Dict[str, Any]:
    """GET /{index}/_termvectors/{id}: per text field, the doc's terms
    with frequencies and positions (re-derived from _source through the
    field's analyzer — the realtime flavor of TermVectorsService)."""
    from elasticsearch_tpu.mapping.types import TextFieldType
    body = body or {}
    svc = node.indices.index(index)
    shard_num = svc.shard_for_id(doc_id, params.get("routing"))
    doc = svc.shard(shard_num).get(doc_id)
    if doc is None:
        return {"_index": index, "_id": doc_id, "found": False}
    source = doc.get("_source") or {}
    want = body.get("fields") or params.get("fields")
    if isinstance(want, str):
        want = [f.strip() for f in want.split(",") if f.strip()]
    from elasticsearch_tpu.ingest import get_field
    reader = svc.shard(shard_num).acquire_searcher()
    tv: Dict[str, Any] = {}
    for path, ft in svc.mapper.mapper.fields.items():
        if not isinstance(ft, TextFieldType):
            continue
        if want and path not in want:
            continue
        # dotted traversal: object-mapped fields live nested in _source;
        # multi-fields (title.en) read their parent's value
        value = get_field(source, path)
        if value is None and "." in path:
            value = get_field(source, path.rsplit(".", 1)[0])
        if value is None:
            continue
        values = value if isinstance(value, list) else [value]
        term_stats: Dict[str, Dict[str, Any]] = {}
        pos_base = 0
        for v in values:
            tokens = ft.analyzer.analyze(str(v))
            for tok in tokens:
                entry = term_stats.setdefault(
                    tok.term, {"term_freq": 0, "tokens": []})
                entry["term_freq"] += 1
                entry["tokens"].append(
                    {"position": pos_base + tok.position})
            pos_base += 100 + len(tokens)
        if not term_stats:
            continue
        doc_count, avgdl = reader.field_stats(path)
        field_block: Dict[str, Any] = {
            "field_statistics": {
                "sum_doc_freq": sum(
                    reader.doc_freq(path, t) for t in term_stats),
                "doc_count": doc_count,
                "sum_ttf": int(avgdl * doc_count)},
            "terms": {}}
        want_stats = (str(params.get("term_statistics",
                                     body.get("term_statistics",
                                              "false"))).lower()
                      == "true")
        for term in sorted(term_stats):
            entry = dict(term_stats[term])
            if want_stats:
                entry["doc_freq"] = reader.doc_freq(path, term)
            field_block["terms"][term] = entry
        tv[path] = field_block
    return {"_index": index, "_id": doc_id, "found": True,
            "took": 0, "term_vectors": tv}


def hot_threads(node, params: Dict[str, str]) -> str:
    """_nodes/hot_threads: sample every Python thread's stack N times
    with the profiler's frame walker and report each busy thread's most
    common sampled stack — real stack dumps, not just queue counts
    (reference: monitor/jvm/HotThreads — a text report, not JSON)."""
    import threading

    from elasticsearch_tpu.common.profiler import walk_frames

    snapshots = int(params.get("snapshots", 3))
    interval_s = 0.05
    threads = int(params.get("threads", 3))
    counts: Dict[str, int] = collections.Counter()
    # per thread: how often each distinct stack was observed
    stacks: Dict[str, collections.Counter] = {}
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    for i in range(snapshots):
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = tuple(walk_frames(frame, 16))  # leaf-first
            if not stack:
                continue
            key = names.get(ident, str(ident))
            counts[key] += 1
            stacks.setdefault(key, collections.Counter())[stack] += 1
        if i + 1 < snapshots:
            time.sleep(interval_s)
    lines = [f"::: {{{node.node_name}}}",
             f"   Hot threads at {time.strftime('%Y-%m-%dT%H:%M:%S')}, "
             f"interval={int(interval_s * 1000)}ms, busiestThreads="
             f"{threads}, ignoreIdleThreads=true:"]
    for name, cnt in counts.most_common(threads):
        share = 100.0 * cnt / max(snapshots, 1)
        lines.append(f"   {share:.1f}% sampled usage by thread "
                     f"'{name}'")
        top = stacks.get(name, collections.Counter()).most_common(1)
        if top:
            stack, seen = top[0]
            lines.append(f"     {seen}/{cnt} snapshots in:")
            for fr in stack:
                fname, _, func = fr.partition(":")
                lines.append(f"       {func} ({fname})")
    # per-pool admission state rides along so stall diagnosis (is the
    # pool saturated or is one thread wedged?) is one call, not two
    pools = getattr(node, "thread_pools", None)
    if pools is not None:
        lines.append("   Thread pools:")
        for pname, st in sorted(pools.stats().items()):
            lines.append(
                f"   [{pname}] active={st['active']}/{st['threads']} "
                f"queue={st['queue']}/{st['queue_size']} "
                f"rejected={st['rejected']} completed={st['completed']}")
    return "\n".join(lines) + "\n"


def allocation_explain(node, body: Optional[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """_cluster/allocation/explain (reference:
    ClusterAllocationExplainAction): where one shard is and why, or —
    with an empty body — the first unassigned shard found."""
    body = body or {}
    cluster = node.cluster
    if cluster is None:
        # single-node: explain against the local registry
        index = body.get("index")
        names = [index] if index else sorted(node.indices.indices)
        shard_num = int(body.get("shard", 0))
        for name in names:
            try:
                svc = node.indices.index(name)
            except IndexNotFoundException:
                raise
            if shard_num not in svc.shards:
                continue
            return {"index": name, "shard": shard_num,
                    "primary": bool(body.get("primary", True)),
                    "current_state": "started",
                    "current_node": {"id": node.node_name,
                                     "name": node.node_name},
                    "explanation": "shard is started on the only node"}
        raise IllegalArgumentException(
            "unable to find any shards to explain "
            f"[{body}] in the routing table")
    state = cluster.applied_state()
    targets = []
    if body.get("index") is not None:
        targets.append((str(body["index"]), int(body.get("shard", 0)),
                        bool(body.get("primary", True))))
    else:
        # first unassigned shard, as the reference defaults
        for name, meta in state.indices.items():
            for s in range(meta.number_of_shards):
                copies = state.shard_copies(name, s)
                started = [c for c in copies if c.state == "STARTED"]
                if len(started) < 1 + meta.number_of_replicas:
                    targets.append((name, s, len(started) == 0))
                    break
    if not targets:
        raise IllegalArgumentException(
            "unable to find any unassigned shards to explain; specify "
            "the target shard [index/shard/primary] in the request")
    name, shard_num, primary = targets[0]
    meta = state.indices.get(name)
    if meta is None:
        raise IndexNotFoundException(f"no such index [{name}]")
    copies = state.shard_copies(name, shard_num)
    started = [c for c in copies if c.state == "STARTED"]
    out: Dict[str, Any] = {"index": name, "shard": shard_num,
                           "primary": primary}
    if started:
        c = started[0]
        nname = state.nodes[c.node_id].name \
            if c.node_id in state.nodes else c.node_id
        out["current_state"] = "started"
        out["current_node"] = {"id": c.node_id, "name": nname}
        out["explanation"] = (
            f"shard has {len(started)} started "
            f"{'copies' if len(started) > 1 else 'copy'} of "
            f"{1 + meta.number_of_replicas} wanted")
    else:
        out["current_state"] = "unassigned"
        out["unassigned_info"] = {"reason": "NODE_LEFT" if copies
                                  else "INDEX_CREATED"}
        out["explanation"] = (
            "cannot allocate because no node holds an in-sync copy "
            "of the shard" if copies else
            "the shard has never been assigned")
    return out


def register(controller: RestController, node) -> None:
    def do_field_caps(req: RestRequest):
        fields = req.params.get("fields")
        if fields is None and isinstance(req.body, dict):
            f = req.body.get("fields")
            fields = ",".join(f) if isinstance(f, list) else f
        return 200, field_caps(node, req.param("index"), fields)

    def do_validate(req: RestRequest):
        explain = str(req.params.get("explain", "false")).lower() == \
            "true"
        return 200, validate_query(node, req.param("index"),
                                   req.body or {}, explain)

    def do_explain(req: RestRequest):
        return 200, explain_doc(node, req.param("index"),
                                req.param("id"), req.body or {},
                                req.params)

    def do_termvectors(req: RestRequest):
        return 200, termvectors(node, req.param("index"),
                                req.param("id"), req.body or {},
                                req.params)

    def do_hot_threads(req: RestRequest):
        return 200, hot_threads(node, req.params)

    def do_alloc_explain(req: RestRequest):
        return 200, allocation_explain(node, req.body or {})

    def do_tpu_stats(req: RestRequest):
        # serving-path observability: stage timers (totals + per-query
        # p50/p95/p99), plan/pack cache hit rates, prewarm progress and
        # the kernel-path breaker state — the production view of what
        # bench logs show offline
        tpu = getattr(node, "tpu_search", None)
        profiler = getattr(node, "profiler", None)
        if tpu is None:
            out: Dict[str, Any] = {"enabled": False}
        else:
            out = {"enabled": True}
            out.update(tpu.stats())
        merge_status = getattr(node, "merge_status", None)
        if merge_status is not None:
            # where deferred k-way merges run (inline / front / pool)
            # and what they cost
            out["merge"] = merge_status()
        if profiler is not None:
            out["profiler"] = profiler.info()
        return 200, out

    def do_tpu_traces(req: RestRequest):
        # recent finished spans (newest first), filterable by trace id /
        # minimum duration — the query surface for the tracing layer
        tracer = getattr(node, "tracer", None)
        if tracer is None:
            return 200, {"sample_rate": 0.0, "total": 0, "spans": []}
        trace_id = req.params.get("trace_id")
        tenant = req.params.get("tenant") or None
        min_ms = float(req.params.get("min_duration_ms", 0) or 0)
        limit = int(req.params.get("limit", 200) or 200)
        if trace_id:
            spans = [s for s in tracer.trace(trace_id)
                     if (s["duration_ms"] or 0) >= min_ms
                     and (tenant is None
                          or s.get("attributes", {}).get("tenant")
                          == tenant)]
        else:
            spans = tracer.spans(min_duration_ms=min_ms, limit=limit,
                                 tenant=tenant)
        return 200, {"sample_rate": tracer.sample_rate,
                     "slow_threshold_ms": tracer.slow_threshold_ms,
                     "total": len(spans), "spans": spans}

    def do_profile_flamegraph(req: RestRequest):
        # folded stacks from the continuous host sampler. Default
        # format is folded text (str payload → text/plain — paste
        # straight into flamegraph.pl / speedscope); format=json returns
        # structured stacks. ?trace_id= filters to samples taken while
        # that trace was live on the sampled thread.
        sampler = node.profiler.sampler
        trace_id = req.params.get("trace_id") or None
        pool = req.params.get("pool") or None
        top = int(req.params.get("top", 0) or 0) or None
        fmt = str(req.params.get("format", "folded")).lower()
        # multi-process merge: when serving fronts exist, every line is
        # prefixed with its process role (batcher; / front-N;) and the
        # fronts' shm-published folded stacks join the scrape. With no
        # fronts the output stays byte-identical to single-process.
        supervisor = getattr(node, "serving_front", None)
        front_folded = supervisor.front_folded() if supervisor else {}
        if fmt == "json":
            stacks = [{"stack": line.split(";"), "count": count}
                      for line, count in sampler.folded(
                          trace_id=trace_id, top=top, pool=pool)]
            if supervisor is not None:
                for s in stacks:
                    s["stack"].insert(0, sampler.role)
                for role, folded in front_folded.items():
                    for line in folded.splitlines():
                        stack, _, count = line.rpartition(" ")
                        if stack and count.isdigit():
                            stacks.append(
                                {"stack": [role] + stack.split(";"),
                                 "count": int(count)})
            return 200, {"enabled": sampler.running,
                         **sampler.stats(), "stacks": stacks}
        if not sampler.running and not sampler.samples_total \
                and not front_folded:
            return 200, {"enabled": False,
                         "reason": "search.profiler.enabled is false"}
        text = sampler.folded_text(trace_id=trace_id, top=top, pool=pool)
        if supervisor is not None:
            lines = [f"{sampler.role};{line}"
                     for line in text.splitlines()]
            for role, folded in front_folded.items():
                lines.extend(f"{role};{line}"
                             for line in folded.splitlines())
            text = "\n".join(lines) + ("\n" if lines else "")
        return 200, text

    def do_profile_timeline(req: RestRequest):
        # queue-depth / in-flight occupancy gauges sampled on the
        # profiler's tick — batching behavior over time, not totals
        sampler = node.profiler.sampler
        limit = int(req.params.get("limit", 0) or 0)
        return 200, {"enabled": sampler.running,
                     "interval_s": round(1.0 / sampler.hz, 4),
                     "points": sampler.timeline(limit=limit)}

    def do_device_start(req: RestRequest):
        name = req.params.get("name")
        if name is None and isinstance(req.body, dict):
            name = req.body.get("name")
        out = node.profiler.device.start(name)
        return (200 if out.get("started") else 409), out

    def do_device_stop(req: RestRequest):
        out = node.profiler.device.stop()
        return (200 if out.get("stopped") else 409), out

    def do_tpu_events(req: RestRequest):
        # the flight-recorder query surface: filtered view of the
        # bounded event ring (oldest-first; causal order by seq)
        from elasticsearch_tpu.common import events as ev
        rec = ev.get_recorder()
        if rec is None:
            return 200, {"enabled": False, "events": []}
        since = req.params.get("since_seq")
        out = rec.events(
            etype=req.params.get("type") or None,
            severity=req.params.get("severity") or None,
            since_seq=int(since) if since else None,
            trace_id=req.params.get("trace_id") or None,
            tenant=req.params.get("tenant") or None,
            limit=int(req.params.get("limit", 256) or 256))
        return 200, {"enabled": True, "last_seq": rec.last_seq,
                     "dropped": rec.c_dropped.count,
                     "total": len(out), "events": out}

    def do_tpu_incidents(req: RestRequest):
        from elasticsearch_tpu.common import events as ev
        rec = ev.get_recorder()
        if rec is None:
            return 200, {"enabled": False, "incidents": []}
        incidents = rec.list_incidents()
        return 200, {"enabled": True, "total": len(incidents),
                     "incidents": incidents}

    def do_tpu_incident_get(req: RestRequest):
        from elasticsearch_tpu.common import events as ev
        rec = ev.get_recorder()
        inc_id = req.param("incident_id")
        snap = rec.get_incident(inc_id) if rec is not None else None
        if snap is None:
            raise ResourceNotFoundException(
                f"no such incident [{inc_id}]")
        return 200, snap

    def do_prometheus(req: RestRequest):
        # text exposition (str payload → text/plain at the HTTP layer);
        # the overload-protection families
        # (es_tpu_indexing_pressure_*, es_tpu_search_backpressure_*)
        # scrape here, mirroring the `indexing_pressure` and
        # `search_backpressure` sections of _nodes/stats
        return 200, node.metrics.prometheus_text()

    controller.register("GET", "/_field_caps", do_field_caps)
    controller.register("POST", "/_field_caps", do_field_caps)
    controller.register("GET", "/{index}/_field_caps", do_field_caps)
    controller.register("POST", "/{index}/_field_caps", do_field_caps)
    controller.register("GET", "/{index}/_validate/query", do_validate)
    controller.register("POST", "/{index}/_validate/query", do_validate)
    controller.register("GET", "/_validate/query", do_validate)
    controller.register("POST", "/_validate/query", do_validate)
    controller.register("GET", "/{index}/_explain/{id}", do_explain)
    controller.register("POST", "/{index}/_explain/{id}", do_explain)
    controller.register("GET", "/{index}/_termvectors/{id}",
                        do_termvectors)
    controller.register("POST", "/{index}/_termvectors/{id}",
                        do_termvectors)
    controller.register("GET", "/_nodes/hot_threads", do_hot_threads)
    controller.register("GET", "/_nodes/{node_id}/hot_threads",
                        do_hot_threads)
    controller.register("GET", "/_cluster/allocation/explain",
                        do_alloc_explain)
    controller.register("POST", "/_cluster/allocation/explain",
                        do_alloc_explain)
    controller.register("GET", "/_tpu/stats", do_tpu_stats)
    controller.register("GET", "/_tpu/traces", do_tpu_traces)
    controller.register("GET", "/_tpu/events", do_tpu_events)
    controller.register("GET", "/_tpu/incidents", do_tpu_incidents)
    controller.register("GET", "/_tpu/incidents/{incident_id}",
                        do_tpu_incident_get)
    controller.register("GET", "/_tpu/profile/flamegraph",
                        do_profile_flamegraph)
    controller.register("GET", "/_tpu/profile/timeline",
                        do_profile_timeline)
    controller.register("POST", "/_tpu/profile/device/start",
                        do_device_start)
    controller.register("POST", "/_tpu/profile/device/stop",
                        do_device_stop)
    controller.register("GET", "/_prometheus/metrics", do_prometheus)
