"""Snapshot/repository REST actions (reference: RestPutRepository
Action, RestCreateSnapshotAction, RestRestoreSnapshotAction et al —
SURVEY.md §2.1#43)."""

from __future__ import annotations

from elasticsearch_tpu import snapshots as snap_mod
from elasticsearch_tpu.rest.controller import RestController, RestRequest


def register(controller: RestController, node) -> None:

    def put_repo(req: RestRequest):
        node.repositories.put(req.param("repo"), req.body or {})
        return 200, {"acknowledged": True}

    def get_repo(req: RestRequest):
        name = req.param("repo")
        if name and name not in ("_all", "*"):
            return 200, {name: node.repositories.get(name)}
        return 200, node.repositories.all()

    def delete_repo(req: RestRequest):
        node.repositories.delete(req.param("repo"))
        return 200, {"acknowledged": True}

    def put_snapshot(req: RestRequest):
        return 200, snap_mod.create_snapshot(
            node, req.param("repo"), req.param("snapshot"), req.body)

    def get_snapshot(req: RestRequest):
        return 200, snap_mod.get_snapshots(
            node, req.param("repo"), req.param("snapshot") or "_all")

    def snapshot_status(req: RestRequest):
        return 200, snap_mod.snapshot_status(
            node, req.param("repo"), req.param("snapshot"))

    def delete_snapshot(req: RestRequest):
        return 200, snap_mod.delete_snapshot(
            node, req.param("repo"), req.param("snapshot"))

    def restore(req: RestRequest):
        return 200, snap_mod.restore_snapshot(
            node, req.param("repo"), req.param("snapshot"), req.body)

    controller.register("PUT", "/_snapshot/{repo}", put_repo)
    controller.register("POST", "/_snapshot/{repo}", put_repo)
    controller.register("GET", "/_snapshot/{repo}", get_repo)
    controller.register("GET", "/_snapshot", get_repo)
    controller.register("DELETE", "/_snapshot/{repo}", delete_repo)
    controller.register("PUT", "/_snapshot/{repo}/{snapshot}",
                        put_snapshot)
    controller.register("GET", "/_snapshot/{repo}/{snapshot}",
                        get_snapshot)
    controller.register("GET", "/_snapshot/{repo}/{snapshot}/_status",
                        snapshot_status)
    controller.register("DELETE", "/_snapshot/{repo}/{snapshot}",
                        delete_snapshot)
    controller.register("POST", "/_snapshot/{repo}/{snapshot}/_restore",
                        restore)
