"""Tasks REST actions: list running tasks, cancel one.

Reference: `RestListTasksAction`, `RestCancelTasksAction`
(SURVEY.md §2.1#46). Response shape: {"nodes": {node_id: {"name": ...,
"tasks": {"node:id": {...}}}}}. In cluster mode the listing fans out to
every node and a cancel routes to the task's owning node by id prefix.
"""

from __future__ import annotations

from typing import Any, Dict

from elasticsearch_tpu.common.errors import (IllegalArgumentException,
                                             ResourceNotFoundException)
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.tasks import ACTION_TASKS_CANCEL, ACTION_TASKS_LIST


def _local_tasks_json(node, actions=None) -> Dict[str, Any]:
    return {t.full_id: t.to_json()
            for t in node.task_manager.list(actions)}


def register(controller: RestController, node) -> None:
    # the cross-node transport handlers live in tasks.register_transport_
    # handlers, wired by ClusterService at cluster start

    def list_tasks(req: RestRequest):
        actions = req.params.get("actions")
        nodes_out: Dict[str, Any] = {
            node.node_id: {"name": node.node_name,
                           "tasks": _local_tasks_json(node, actions)}}
        if node.cluster is not None:
            state = node.cluster.applied_state()
            futures = []
            for n in state.data_nodes():
                if n.node_id == node.node_id:
                    continue
                futures.append((n, node.cluster.transport.send_request_async(
                    n.address, ACTION_TASKS_LIST, {"actions": actions})))
            for n, fut in futures:
                try:
                    nodes_out[n.node_id] = {
                        "name": n.name,
                        "tasks": fut.result(timeout=10.0)["tasks"]}
                except Exception:  # noqa: BLE001 — node unreachable
                    pass
        return 200, {"nodes": nodes_out}

    def cancel_task(req: RestRequest):
        full_id = req.param("task_id")
        if not full_id or ":" not in full_id:
            raise IllegalArgumentException(
                f"malformed task id [{full_id}], expected nodeId:taskId")
        owner_id, _, seq = full_id.rpartition(":")
        if not seq.isdigit():
            raise IllegalArgumentException(
                f"malformed task id [{full_id}]")
        if owner_id == node.node_id:
            task = node.task_manager.cancel(int(seq))
            return 200, {"nodes": {node.node_id: {
                "name": node.node_name,
                "tasks": {task.full_id: task.to_json()}}}}
        if node.cluster is not None:
            state = node.cluster.applied_state()
            owner = state.nodes.get(owner_id)
            if owner is not None:
                from elasticsearch_tpu.transport.service import \
                    RemoteTransportException
                try:
                    result = node.cluster.transport.send_request(
                        owner.address, ACTION_TASKS_CANCEL,
                        {"task_id": int(seq)}, timeout=10.0)
                except RemoteTransportException as e:
                    from elasticsearch_tpu.cluster.service import \
                        _rehydrate_error
                    raise _rehydrate_error(e) from e
                return 200, {"nodes": {owner_id: {
                    "name": owner.name,
                    "tasks": {full_id: result["task"]}}}}
        raise ResourceNotFoundException(
            f"task [{full_id}] belongs to an unknown node")

    controller.register("GET", "/_tasks", list_tasks)
    controller.register("POST", "/_tasks/{task_id}/_cancel", cancel_task)
