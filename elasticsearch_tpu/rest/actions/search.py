"""Search/count/analyze REST actions (reference: RestSearchAction,
RestCountAction, RestAnalyzeAction — SURVEY.md §2.1#10, §3.3)."""

from __future__ import annotations

from typing import Any, Dict

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.search import coordinator


def register(controller: RestController, node) -> None:
    indices = node.indices

    def do_search(req: RestRequest):
        task = node.task_manager.register(
            "indices:data/read/search",
            description=f"indices[{req.param('index') or '_all'}]")
        try:
            if node.cluster is not None:
                return 200, node.cluster.route_search(
                    req.param("index"), req.body or {}, req.params,
                    task=task)
            return 200, coordinator.search(
                indices, req.param("index"), req.body or {}, req.params,
                tpu_search=getattr(node, "tpu_search", None), task=task)
        finally:
            node.task_manager.unregister(task)

    def do_count(req: RestRequest):
        if node.cluster is not None:
            return 200, node.cluster.route_count(req.param("index"),
                                                 req.body or {})
        return 200, coordinator.count(indices, req.param("index"),
                                      req.body or {})

    def do_analyze(req: RestRequest):
        body = req.body or {}
        text = body.get("text")
        if text is None:
            raise IllegalArgumentException("[_analyze] requires text")
        texts = text if isinstance(text, list) else [text]
        index = req.param("index")
        analyzer_name = body.get("analyzer", "standard")
        if index and body.get("field"):
            svc = indices.index(index)
            ft = svc.mapper.field_type(body["field"])
            analyzer = getattr(ft, "analyzer", None)
        else:
            from elasticsearch_tpu.analysis import AnalysisRegistry
            from elasticsearch_tpu.common.settings import Settings
            registry = AnalysisRegistry().build(Settings.EMPTY)
            analyzer = registry.get(analyzer_name)
        if analyzer is None:
            raise IllegalArgumentException(
                f"failed to find analyzer [{analyzer_name}]")
        tokens = []
        for t in texts:
            for pos, term in enumerate(analyzer.terms(str(t))):
                tokens.append({"token": term, "position": pos,
                               "type": "<ALPHANUM>"})
        return 200, {"tokens": tokens}

    controller.register("GET", "/_search", do_search)
    controller.register("POST", "/_search", do_search)
    controller.register("GET", "/{index}/_search", do_search)
    controller.register("POST", "/{index}/_search", do_search)
    controller.register("GET", "/_count", do_count)
    controller.register("POST", "/_count", do_count)
    controller.register("GET", "/{index}/_count", do_count)
    controller.register("POST", "/{index}/_count", do_count)
    def do_rank_eval(req: RestRequest):
        from elasticsearch_tpu.search import rank_eval
        index_expr = req.param("index")

        def run(search_body):
            return coordinator.search(
                indices, index_expr, search_body, {},
                tpu_search=getattr(node, "tpu_search", None))

        return 200, rank_eval.evaluate(run, req.body or {})

    controller.register("GET", "/_rank_eval", do_rank_eval)
    controller.register("POST", "/_rank_eval", do_rank_eval)
    controller.register("GET", "/{index}/_rank_eval", do_rank_eval)
    controller.register("POST", "/{index}/_rank_eval", do_rank_eval)
    controller.register("GET", "/_analyze", do_analyze)
    controller.register("POST", "/_analyze", do_analyze)
    controller.register("GET", "/{index}/_analyze", do_analyze)
    controller.register("POST", "/{index}/_analyze", do_analyze)
