"""Search/count/analyze REST actions (reference: RestSearchAction,
RestCountAction, RestAnalyzeAction — SURVEY.md §2.1#10, §3.3)."""

from __future__ import annotations

from typing import Any, Dict

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.search import coordinator


def register(controller: RestController, node) -> None:
    indices = node.indices

    def _execute_search(index, body, params, task):
        """One search request — pit bodies, cluster routing, and the
        local planner all covered (shared by _search and _msearch so an
        item body never silently drops a key)."""
        if "_knn_docs" in (body or {}):
            # internal wire key (resolved knn winners between cluster
            # coordinator and shard groups) — never client-settable: it
            # would inject arbitrary per-doc scores past knn validation
            raise IllegalArgumentException(
                "unknown search body keys ['_knn_docs']")
        from elasticsearch_tpu.search import scroll as scroll_mod
        if "pit" in body:
            if not isinstance(body["pit"], dict):
                raise IllegalArgumentException(
                    "[pit] must be an object with an [id]")
            return scroll_mod.search_pit(node, body, params, task=task)
        from elasticsearch_tpu import ccs
        federated = ccs.maybe_cross_cluster(node, index, body, params,
                                            task=task)
        if federated is not None:
            return federated
        if node.cluster is not None:
            return node.cluster.route_search(index, body, params,
                                             task=task)
        return coordinator.search(
            indices, index, body, params,
            tpu_search=getattr(node, "tpu_search", None), task=task)

    def do_search(req: RestRequest):
        from elasticsearch_tpu.search import scroll as scroll_mod
        task = node.task_manager.register(
            "indices:data/read/search",
            description=f"indices[{req.param('index') or '_all'}]")
        release_quota = None
        try:
            body = req.body or {}
            # per-tenant carve first (a 429 here is THIS tenant over its
            # concurrency share — other tenants keep passing), then node
            # duress: under it the oldest stale search tasks are
            # cancelled and an expensive incoming search declined
            quotas = getattr(node, "tenants", None)
            if quotas is not None:
                release_quota = quotas.admit_search()
            backpressure = getattr(node, "search_backpressure", None)
            if backpressure is not None:
                backpressure.admit(body, task=task)
            if req.params.get("scroll"):
                return 200, scroll_mod.start_scroll(
                    node, req.param("index"), body, req.params, task=task)
            return 200, _execute_search(req.param("index"), body,
                                        req.params, task)
        finally:
            if release_quota is not None:
                release_quota()
            node.task_manager.unregister(task)

    def scroll_page(req: RestRequest):
        from elasticsearch_tpu.search import scroll as scroll_mod
        body = req.body or {}
        scroll_id = (req.param("scroll_id") or body.get("scroll_id")
                     or req.params.get("scroll_id"))
        if not scroll_id:
            raise IllegalArgumentException("[scroll_id] is required")
        keep = body.get("scroll") or req.params.get("scroll")
        return 200, scroll_mod.next_page(node, scroll_id, keep)

    def clear_scroll(req: RestRequest):
        from elasticsearch_tpu.search import scroll as scroll_mod
        body = req.body or {}
        ids = req.param("scroll_id") or body.get("scroll_id")
        if isinstance(ids, str):
            ids = [ids]
        return 200, scroll_mod.clear(node, ids)

    def open_pit(req: RestRequest):
        from elasticsearch_tpu.search import scroll as scroll_mod
        keep = req.params.get("keep_alive")
        if not keep:
            raise IllegalArgumentException(
                "[open_point_in_time] requires [keep_alive]")
        return 200, scroll_mod.open_pit(node, req.param("index"), keep)

    def close_pit(req: RestRequest):
        from elasticsearch_tpu.search import scroll as scroll_mod
        body = req.body or {}
        pit_id = body.get("id")
        if not pit_id:
            raise IllegalArgumentException(
                "[close_point_in_time] requires [id]")
        return 200, scroll_mod.close_pit(node, pit_id)

    def do_count(req: RestRequest):
        if node.cluster is not None:
            return 200, node.cluster.route_count(req.param("index"),
                                                 req.body or {})
        return 200, coordinator.count(indices, req.param("index"),
                                      req.body or {})

    def do_analyze(req: RestRequest):
        body = req.body or {}
        text = body.get("text")
        if text is None:
            raise IllegalArgumentException("[_analyze] requires text")
        texts = text if isinstance(text, list) else [text]
        index = req.param("index")
        analyzer_name = body.get("analyzer", "standard")
        if index and body.get("field"):
            svc = indices.index(index)
            ft = svc.mapper.field_type(body["field"])
            analyzer = getattr(ft, "analyzer", None)
        elif index:
            # the index's OWN registry: custom analyzers defined in
            # index.analysis.* resolve here (reference:
            # TransportAnalyzeAction on an index)
            svc = indices.index(index)
            analyzer = svc.mapper.analyzers.get(analyzer_name)
        else:
            from elasticsearch_tpu.analysis import AnalysisRegistry
            from elasticsearch_tpu.common.settings import Settings
            registry = AnalysisRegistry().build(Settings.EMPTY)
            analyzer = registry.get(analyzer_name)
        if analyzer is None:
            raise IllegalArgumentException(
                f"failed to find analyzer [{analyzer_name}]")
        tokens = []
        for t in texts:
            # analyze() preserves position stacking (synonyms/ngrams at
            # one position) and stop-word holes
            for tok in analyzer.analyze(str(t)):
                tokens.append({"token": tok.term,
                               "position": tok.position,
                               "type": "<ALPHANUM>"})
        return 200, {"tokens": tokens}

    def do_msearch(req: RestRequest):
        """_msearch: NDJSON header/body pairs; one response per search,
        failures reported per item (reference: RestMultiSearchAction)."""
        import json as _json
        raw = req.raw_body.decode("utf-8", errors="replace") \
            if req.raw_body else (
                req.body if isinstance(req.body, str) else "")
        lines = [ln for ln in raw.split("\n") if ln.strip()]
        if not lines:
            raise IllegalArgumentException(
                "[_msearch] request body or source parameter is "
                "required")
        if len(lines) % 2 != 0:
            raise IllegalArgumentException(
                "[_msearch] expects header/body line pairs")
        task = node.task_manager.register(
            "indices:data/read/msearch",
            description=f"[{len(lines) // 2}] searches")
        responses = []
        default_index = req.param("index")
        release_quota = None
        try:
            # one admission slot covers the whole msearch (its items run
            # sequentially on this thread — charging per item would let
            # one request hold N slots)
            quotas = getattr(node, "tenants", None)
            if quotas is not None:
                release_quota = quotas.admit_search()
            for i in range(0, len(lines), 2):
                task.ensure_not_cancelled()
                try:
                    header = _json.loads(lines[i])
                    body = _json.loads(lines[i + 1])
                    index = header.get("index", default_index)
                    if isinstance(index, list):
                        index = ",".join(index)
                    backpressure = getattr(node, "search_backpressure",
                                           None)
                    if backpressure is not None:
                        # per item: a declined search is ITS 429 entry,
                        # the sibling searches still run
                        backpressure.admit(body, task=task)
                    # item dicts are annotated below — never defer the
                    # merge of an msearch item past this loop
                    from elasticsearch_tpu.search import merge as merge_mod
                    with merge_mod.deferring(False):
                        item = _execute_search(index, body, {}, task)
                    item["status"] = 200
                    responses.append(item)
                except Exception as exc:  # noqa: BLE001 — per item
                    from elasticsearch_tpu.common.errors import \
                        TaskCancelledException
                    if isinstance(exc, TaskCancelledException):
                        raise
                    from elasticsearch_tpu.rest.controller import (
                        error_body, error_status)
                    status = error_status(exc)
                    item = error_body(exc, status)
                    item["status"] = status
                    responses.append(item)
        finally:
            if release_quota is not None:
                release_quota()
            node.task_manager.unregister(task)
        return 200, {"took": sum(r.get("took", 0) for r in responses),
                     "responses": responses}

    controller.register("POST", "/_msearch", do_msearch)
    controller.register("GET", "/_msearch", do_msearch)
    controller.register("POST", "/{index}/_msearch", do_msearch)
    controller.register("GET", "/{index}/_msearch", do_msearch)
    controller.register("GET", "/_search", do_search)
    controller.register("POST", "/_search", do_search)
    controller.register("GET", "/{index}/_search", do_search)
    controller.register("POST", "/{index}/_search", do_search)
    controller.register("GET", "/_search/scroll", scroll_page)
    controller.register("POST", "/_search/scroll", scroll_page)
    controller.register("GET", "/_search/scroll/{scroll_id}", scroll_page)
    controller.register("POST", "/_search/scroll/{scroll_id}", scroll_page)
    controller.register("DELETE", "/_search/scroll", clear_scroll)
    controller.register("DELETE", "/_search/scroll/{scroll_id}",
                        clear_scroll)
    controller.register("POST", "/{index}/_pit", open_pit)
    controller.register("DELETE", "/_pit", close_pit)
    controller.register("GET", "/_count", do_count)
    controller.register("POST", "/_count", do_count)
    controller.register("GET", "/{index}/_count", do_count)
    controller.register("POST", "/{index}/_count", do_count)
    def do_rank_eval(req: RestRequest):
        from elasticsearch_tpu.search import rank_eval
        index_expr = req.param("index")

        def run(search_body):
            return coordinator.search(
                indices, index_expr, search_body, {},
                tpu_search=getattr(node, "tpu_search", None))

        return 200, rank_eval.evaluate(run, req.body or {})

    controller.register("GET", "/_rank_eval", do_rank_eval)
    controller.register("POST", "/_rank_eval", do_rank_eval)
    controller.register("GET", "/{index}/_rank_eval", do_rank_eval)
    controller.register("POST", "/{index}/_rank_eval", do_rank_eval)
    controller.register("GET", "/_analyze", do_analyze)
    controller.register("POST", "/_analyze", do_analyze)
    controller.register("GET", "/{index}/_analyze", do_analyze)
    controller.register("POST", "/{index}/_analyze", do_analyze)
