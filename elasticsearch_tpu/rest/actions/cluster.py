"""Cluster-level + _cat REST actions (reference: RestClusterHealthAction,
rest/action/cat/* — SURVEY.md §2.1#47/56). Single-node health semantics:
green when every shard is assigned (they always are locally), yellow
reserved for unassigned replicas once the cluster layer lands."""

from __future__ import annotations

import time
from typing import Any, Dict, List

from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.search.coordinator import resolve_indices
from elasticsearch_tpu.version import __version__ as VERSION


def _parse_time_s(value: str) -> float:
    """Reference TimeValue grammar subset: "500ms" | "30s" | "1m" |
    bare seconds."""
    v = value.strip().lower()
    try:
        for suffix, scale in (("ms", 0.001), ("s", 1.0), ("m", 60.0),
                              ("h", 3600.0)):
            if v.endswith(suffix):
                return float(v[:-len(suffix)]) * scale
        return float(v)
    except ValueError:
        return 30.0


def _cat_table(req, headers: List[str], rows: List[List[Any]]):
    """The _cat text-table renderer shared by every cat endpoint."""
    if req.param_bool("v"):
        all_rows = [headers] + [[str(c) for c in r] for r in rows]
    else:
        all_rows = [[str(c) for c in r] for r in rows]
    widths = [max((len(r[i]) for r in all_rows), default=0)
              for i in range(len(headers))]
    lines = [" ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in all_rows]
    return 200, {"_cat": "\n".join(lines) + "\n"}


def register(controller: RestController, node) -> None:
    indices = node.indices

    def root(req: RestRequest):
        return 200, {
            "name": node.node_name,
            "cluster_name": node.cluster_name,
            "cluster_uuid": node.cluster_uuid,
            "version": {"number": VERSION,
                        "build_flavor": "tpu",
                        "lucene_version": "n/a (XLA/Pallas kernels)"},
            "tagline": "You Know, for Search — on TPUs",
        }

    def health(req: RestRequest):
        if node.cluster is not None:
            out = node.cluster.health()
            want = req.params.get("wait_for_status")
            if want in ("green", "yellow"):
                import time as _time
                rank = {"green": 0, "yellow": 1, "red": 2}
                deadline = _time.monotonic() + _parse_time_s(
                    req.params.get("timeout", "30s"))
                while (rank[out["status"]] > rank[want]
                       and _time.monotonic() < deadline):
                    _time.sleep(0.1)
                    out = node.cluster.health()
                out["timed_out"] = rank[out["status"]] > rank[want]
            return 200, out
        n_shards = sum(svc.num_shards for svc in indices.indices.values())
        return 200, {
            "cluster_name": node.cluster_name,
            "status": "green",
            "timed_out": False,
            "number_of_nodes": 1,
            "number_of_data_nodes": 1,
            "active_primary_shards": n_shards,
            "active_shards": n_shards,
            "relocating_shards": 0,
            "initializing_shards": 0,
            "unassigned_shards": 0,
            "delayed_unassigned_shards": 0,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": 100.0,
        }

    def cluster_stats(req: RestRequest):
        total_docs = sum(svc.stats()["docs"]["count"]
                         for svc in indices.indices.values())
        return 200, {
            "cluster_name": node.cluster_name,
            "status": "green",
            "indices": {"count": len(indices.indices),
                        "docs": {"count": total_docs}},
            "nodes": {"count": {"total": 1, "data": 1, "master": 1}},
        }

    def nodes_stats(req: RestRequest):
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        out = {"_nodes": {"total": 1, "successful": 1},
               "cluster_name": node.cluster_name,
               "nodes": {node.node_id: {
                   "name": node.node_name,
                   "indices": indices.stats(),
                   "process": {"max_rss_bytes": ru.ru_maxrss * 1024},
                   "jvm": None,
               }}}
        if node.tpu_search is not None:
            out["nodes"][node.node_id]["tpu_search"] = \
                node.tpu_search.stats()
        if getattr(node, "thread_pools", None) is not None:
            out["nodes"][node.node_id]["thread_pool"] = \
                node.thread_pools.stats()
        if getattr(node, "breakers", None) is not None:
            # the service's own stats() — includes the PARENT breaker,
            # the signal the hierarchy exists for
            out["nodes"][node.node_id]["breakers"] = \
                node.breakers.stats()
        if getattr(node, "indexing_pressure", None) is not None:
            # per-stage current/total/rejection byte accounting
            # (reference: the 7.9+ `indexing_pressure` stats section)
            out["nodes"][node.node_id]["indexing_pressure"] = \
                node.indexing_pressure.stats()
        if getattr(node, "search_backpressure", None) is not None:
            out["nodes"][node.node_id]["search_backpressure"] = \
                node.search_backpressure.stats()
        if getattr(node, "tenants", None) is not None:
            # per-tenant QoS: weights, caps, in-flight and rejections
            out["nodes"][node.node_id]["tenants"] = node.tenants.stats()
        # bounded-retry allocation visibility: total shard-copy
        # allocation failures (corrupt store opens, failed recoveries)
        # plus the currently-throttled streaks per [index][shard]
        alloc = getattr(getattr(node, "cluster", None), "allocation", None)
        out["nodes"][node.node_id]["allocations"] = {
            "failed_allocations":
                alloc.c_failed_allocations.count if alloc else 0,
            "failed_streaks":
                {f"{i}[{s}]": n for (i, s), n in
                 sorted(alloc.failed_allocations.items())} if alloc
                else {},
        }
        return 200, out

    # ---------------- _cat ----------------

    _maybe_table = _cat_table

    def cat_indices(req: RestRequest):
        rows = []
        for name in resolve_indices(indices, req.param("index")):
            svc = indices.index(name)
            st = svc.stats()
            rows.append(["green", "open", name, svc.index_uuid,
                         svc.num_shards, svc.num_replicas,
                         st["docs"]["count"], 0])
        return _maybe_table(req, ["health", "status", "index", "uuid", "pri",
                                  "rep", "docs.count", "docs.deleted"], rows)

    def cat_health(req: RestRequest):
        return _maybe_table(req, ["epoch", "timestamp", "cluster", "status",
                                  "node.total", "shards"],
                            [[int(time.time()),
                              time.strftime("%H:%M:%S"),
                              node.cluster_name, "green", 1,
                              sum(s.num_shards
                                  for s in indices.indices.values())]])

    def cat_count(req: RestRequest):
        from elasticsearch_tpu.search import coordinator
        c = coordinator.count(indices, req.param("index"), None)
        return _maybe_table(req, ["epoch", "timestamp", "count"],
                            [[int(time.time()), time.strftime("%H:%M:%S"),
                              c["count"]]])

    def cat_shards(req: RestRequest):
        if node.cluster is not None:
            state = node.cluster.applied_state()
            rows = []
            for name in node.cluster.resolve_indices(req.param("index")):
                for s, copies in sorted(
                        state.routing.get(name, {}).items()):
                    for c in copies:
                        node_name = (state.nodes[c.node_id].name
                                     if c.node_id in state.nodes else "-")
                        rows.append([name, s, "p" if c.primary else "r",
                                     c.state, "-", node_name])
            return _maybe_table(req, ["index", "shard", "prirep", "state",
                                      "docs", "node"], rows)
        rows = []
        for name in resolve_indices(indices, req.param("index")):
            svc = indices.index(name)
            for num, shard in sorted(svc.shards.items()):
                rows.append([name, num, "p" if shard.primary else "r",
                             "STARTED", shard.engine.num_docs(),
                             node.node_name])
        return _maybe_table(req, ["index", "shard", "prirep", "state",
                                  "docs", "node"], rows)

    def get_cluster_settings(req: RestRequest):
        if node.cluster is not None:
            state = node.cluster.applied_state()
            return 200, {"persistent": dict(state.persistent_settings),
                         "transient": dict(state.transient_settings)}
        return 200, {"persistent": dict(node.persistent_settings),
                     "transient": dict(node.transient_settings)}

    def put_cluster_settings(req: RestRequest):
        body = req.body or {}
        persistent = body.get("persistent") or {}
        transient = body.get("transient") or {}
        if not persistent and not transient:
            from elasticsearch_tpu.common.errors import \
                IllegalArgumentException
            raise IllegalArgumentException(
                "no settings to update: provide [persistent] and/or "
                "[transient]")
        if node.cluster is not None:
            return 200, node.cluster.update_cluster_settings(persistent,
                                                             transient)
        return 200, node.update_cluster_settings_local(persistent,
                                                       transient)

    def cluster_state(req: RestRequest):
        if node.cluster is not None:
            return 200, node.cluster.state_json()
        return 200, {"cluster_name": node.cluster_name,
                     "cluster_uuid": node.cluster_uuid,
                     "master_node": node.node_id,
                     "nodes": {node.node_id: {"name": node.node_name}}}

    def cat_nodes(req: RestRequest):
        if node.cluster is not None:
            state = node.cluster.applied_state()
            rows = []
            for n in state.data_nodes():
                role = "m" if n.node_id == state.master_node_id else "-"
                rows.append([n.host, n.port, role, n.name])
            return _maybe_table(req, ["host", "port", "master", "name"],
                                rows)
        return _maybe_table(req, ["host", "port", "master", "name"],
                            [["127.0.0.1", 9200, "m", node.node_name]])

    def cat_root(req: RestRequest):
        paths = ["/_cat/aliases", "/_cat/allocation", "/_cat/count",
                 "/_cat/health", "/_cat/indices", "/_cat/master",
                 "/_cat/nodes", "/_cat/plugins", "/_cat/recovery",
                 "/_cat/shards", "/_cat/tasks"]
        return 200, {"_cat": "=^.^=\n" + "\n".join(paths) + "\n"}

    def cat_aliases(req: RestRequest):
        from elasticsearch_tpu.rest.actions.aliases import _alias_map
        rows = []
        for alias, targets in sorted(_alias_map(node).items()):
            for index, props in sorted(targets.items()):
                rows.append([alias, index,
                             "*" if props.get("filter") else "-",
                             "true" if props.get("is_write_index")
                             else "-"])
        return _maybe_table(req, ["alias", "index", "filter",
                                  "is_write_index"], rows)

    def cat_master(req: RestRequest):
        if node.cluster is not None:
            master = node.cluster.coordinator.master_node()
            if master is None:
                return _maybe_table(req, ["id", "host", "node"], [])
            return _maybe_table(req, ["id", "host", "node"],
                                [[master.node_id, master.host,
                                  master.name]])
        return _maybe_table(req, ["id", "host", "node"],
                            [[node.node_id, "127.0.0.1",
                              node.node_name]])

    def cat_allocation(req: RestRequest):
        rows = []
        if node.cluster is not None:
            state = node.cluster.applied_state()
            per_node = {nid: 0 for nid in state.nodes}
            for shards in state.routing.values():
                for copies in shards.values():
                    for c in copies:
                        if c.node_id in per_node:
                            per_node[c.node_id] += 1
            for nid, count in sorted(per_node.items()):
                n = state.nodes[nid]
                rows.append([count, n.host, n.name])
        else:
            total = sum(len(svc.shards)
                        for svc in indices.indices.values())
            rows.append([total, "127.0.0.1", node.node_name])
        return _maybe_table(req, ["shards", "host", "node"], rows)

    def cat_recovery(req: RestRequest):
        rows = []
        for name in resolve_indices(indices, req.param("index")):
            svc = indices.index(name)
            for num, shard in sorted(svc.shards.items()):
                rows.append([name, num, "done",
                             "existing_store" if shard.primary
                             else "peer", node.node_name])
        return _maybe_table(req, ["index", "shard", "stage", "type",
                                  "node"], rows)

    def cat_plugins(req: RestRequest):
        rows = [[node.node_name, mod, "-"]
                for mod in node.plugins.loaded_modules]
        return _maybe_table(req, ["name", "component", "version"], rows)

    def cat_tasks(req: RestRequest):
        rows = [[t.action, t.full_id, "transport",
                 t.start_time_millis, t.description]
                for t in node.task_manager.list()]
        return _maybe_table(req, ["action", "task_id", "type",
                                  "start_time", "description"], rows)

    controller.register("GET", "/_cat", cat_root)
    controller.register("GET", "/_cat/aliases", cat_aliases)
    controller.register("GET", "/_cat/master", cat_master)
    controller.register("GET", "/_cat/allocation", cat_allocation)
    controller.register("GET", "/_cat/recovery", cat_recovery)
    controller.register("GET", "/_cat/recovery/{index}", cat_recovery)
    controller.register("GET", "/_cat/plugins", cat_plugins)
    controller.register("GET", "/_cat/tasks", cat_tasks)
    controller.register("GET", "/", root)
    controller.register("GET", "/_cluster/settings", get_cluster_settings)
    controller.register("PUT", "/_cluster/settings", put_cluster_settings)
    controller.register("GET", "/_cluster/state", cluster_state)
    controller.register("GET", "/_cat/nodes", cat_nodes)
    controller.register("GET", "/_cluster/health", health)
    controller.register("GET", "/_cluster/stats", cluster_stats)
    controller.register("GET", "/_nodes/stats", nodes_stats)
    controller.register("GET", "/_cat/indices", cat_indices)
    controller.register("GET", "/_cat/indices/{index}", cat_indices)
    controller.register("GET", "/_cat/health", cat_health)
    controller.register("GET", "/_cat/count", cat_count)
    controller.register("GET", "/_cat/count/{index}", cat_count)
    controller.register("GET", "/_cat/shards", cat_shards)
    controller.register("GET", "/_cat/shards/{index}", cat_shards)
