"""Document CRUD + bulk REST actions.

Reference: `RestIndexAction`, `RestGetAction`, `RestDeleteAction`,
`RestBulkAction`, `RestMultiGetAction` (SURVEY.md §2.1#10, §3.2). The
bulk body is NDJSON action/metadata lines exactly like the reference."""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, Tuple

from elasticsearch_tpu.common.errors import (DocumentMissingException,
                                             IllegalArgumentException,
                                             EsException)
from elasticsearch_tpu.rest.controller import (RestController, RestRequest,
                                               error_status)


def _auto_id() -> str:
    return uuid.uuid4().hex[:20]


def register(controller: RestController, node) -> None:
    indices = node.indices

    def _index_doc(index: str, doc_id, body, params,
                   op_type: str = "index") -> Tuple[int, Dict]:
        if not isinstance(body, dict):
            raise IllegalArgumentException("request body is required")
        svc = node.get_or_autocreate_index(index)
        created_id = doc_id or _auto_id()
        shard = svc.shard(svc.shard_for_id(created_id,
                                           params.get("routing")))
        kwargs = {"op_type": op_type} if op_type != "index" else {}
        if params.get("if_seq_no") is not None:
            kwargs["if_seq_no"] = int(params["if_seq_no"])
        if params.get("if_primary_term") is not None:
            kwargs["if_primary_term"] = int(params["if_primary_term"])
        if params.get("version") is not None:
            kwargs["version"] = int(params["version"])
            kwargs["version_type"] = params.get("version_type", "internal")
        result = shard.apply_index_on_primary(created_id, body, **kwargs)
        if params.get("refresh") in ("", "true", "wait_for"):
            shard.refresh()
        status = 201 if result.created else 200
        return status, {
            "_index": index, "_id": result.doc_id,
            "_version": result.version, "result": result.result,
            "_seq_no": result.seq_no, "_primary_term": result.primary_term,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
        }

    def put_doc(req: RestRequest):
        if req.params.get("op_type") == "create":
            return create_doc(req)
        return _index_doc(req.param("index"), req.param("id"), req.body,
                          req.params)

    def create_doc(req: RestRequest):
        """op_type=create: 409 if the doc exists — enforced inside the
        engine's write lock so concurrent creates serialize (reference:
        version_conflict_engine_exception on op_type=create)."""
        return _index_doc(req.param("index"), req.param("id"), req.body,
                          req.params, op_type="create")

    def post_doc(req: RestRequest):
        return _index_doc(req.param("index"), None, req.body, req.params)

    def get_doc(req: RestRequest):
        svc = indices.index(req.param("index"))
        doc_id = req.param("id")
        shard = svc.shard(svc.shard_for_id(doc_id, req.param("routing")))
        got = shard.get(doc_id)
        if got is None:
            return 404, {"_index": req.param("index"), "_id": doc_id,
                         "found": False}
        got["_index"] = req.param("index")
        return 200, got

    def delete_doc(req: RestRequest):
        svc = indices.index(req.param("index"))
        doc_id = req.param("id")
        shard = svc.shard(svc.shard_for_id(doc_id, req.param("routing")))
        result = shard.apply_delete_on_primary(doc_id)
        if req.param("refresh") in ("", "true", "wait_for"):
            shard.refresh()
        if not result.found:
            return 404, {"_index": req.param("index"), "_id": doc_id,
                         "result": "not_found", "_version": result.version,
                         "_seq_no": result.seq_no,
                         "_primary_term": result.primary_term}
        return 200, {"_index": req.param("index"), "_id": doc_id,
                     "result": "deleted", "_version": result.version,
                     "_seq_no": result.seq_no,
                     "_primary_term": result.primary_term,
                     "_shards": {"total": 1, "successful": 1, "failed": 0}}

    def update_doc(req: RestRequest):
        """_update: doc merge or scripted update is reference behavior;
        doc-merge and doc_as_upsert are supported here."""
        svc = indices.index(req.param("index"))
        doc_id = req.param("id")
        shard = svc.shard(svc.shard_for_id(doc_id, req.param("routing")))
        body = req.body or {}
        partial = body.get("doc")
        if partial is None:
            raise IllegalArgumentException(
                "[_update] requires a [doc] (scripted updates need the "
                "script module)")
        existing = shard.get(doc_id)
        if existing is None:
            if body.get("doc_as_upsert") or "upsert" in body:
                base = body.get("upsert", {})
            else:
                raise DocumentMissingException(f"[{doc_id}]: document missing")
        else:
            base = dict(existing["_source"] or {})
        merged = _deep_merge(base, partial)
        result = shard.apply_index_on_primary(doc_id, merged)
        if req.param("refresh") in ("", "true", "wait_for"):
            shard.refresh()
        return 200, {"_index": req.param("index"), "_id": doc_id,
                     "_version": result.version, "result": result.result,
                     "_seq_no": result.seq_no,
                     "_primary_term": result.primary_term}

    def mget(req: RestRequest):
        body = req.body or {}
        docs_spec = body.get("docs")
        default_index = req.param("index")
        if docs_spec is None and "ids" in body:
            docs_spec = [{"_id": i} for i in body["ids"]]
        if docs_spec is None:
            raise IllegalArgumentException("[_mget] requires docs or ids")
        out = []
        for spec in docs_spec:
            index = spec.get("_index", default_index)
            doc_id = spec["_id"]
            try:
                svc = indices.index(index)
                shard = svc.shard(svc.shard_for_id(doc_id))
                got = shard.get(doc_id)
            except EsException:
                got = None
            if got is None:
                out.append({"_index": index, "_id": doc_id, "found": False})
            else:
                got["_index"] = index
                out.append(got)
        return 200, {"docs": out}

    def bulk(req: RestRequest):
        t0 = time.perf_counter()
        raw = req.raw_body.decode("utf-8") if req.raw_body else (
            req.body if isinstance(req.body, str) else "")
        default_index = req.param("index")
        lines = [ln for ln in raw.split("\n") if ln.strip()]
        items = []
        errors = False
        i = 0
        refresh_shards = set()
        while i < len(lines):
            try:
                action_line = json.loads(lines[i])
            except json.JSONDecodeError as e:
                raise IllegalArgumentException(
                    f"Malformed action/metadata line [{i + 1}]: {e}")
            if len(action_line) != 1:
                raise IllegalArgumentException(
                    f"Malformed action/metadata line [{i + 1}]")
            op, meta = next(iter(action_line.items()))
            if op not in ("index", "create", "delete", "update"):
                raise IllegalArgumentException(f"Unknown bulk action [{op}]")
            index = meta.get("_index", default_index)
            doc_id = meta.get("_id")
            i += 1
            source = None
            if op != "delete":
                if i >= len(lines):
                    raise IllegalArgumentException(
                        "Validation Failed: bulk source line missing")
                source = json.loads(lines[i])
                i += 1
            try:
                if index is None:
                    raise IllegalArgumentException("_index is missing")
                svc = node.get_or_autocreate_index(index)
                the_id = doc_id or _auto_id()
                shard = svc.shard(svc.shard_for_id(
                    the_id, meta.get("routing")))
                if op == "delete":
                    r = shard.apply_delete_on_primary(the_id)
                    status = 200 if r.found else 404
                    items.append({"delete": {
                        "_index": index, "_id": the_id, "_version": r.version,
                        "result": "deleted" if r.found else "not_found",
                        "_seq_no": r.seq_no, "_primary_term": r.primary_term,
                        "status": status}})
                    if not r.found:
                        pass  # not an "error" per reference semantics
                elif op == "update":
                    partial = (source or {}).get("doc")
                    existing = shard.get(the_id)
                    if existing is None and not (source or {}).get("doc_as_upsert"):
                        raise DocumentMissingException(
                            f"[{the_id}]: document missing")
                    base = dict((existing or {}).get("_source") or {})
                    r = shard.apply_index_on_primary(
                        the_id, _deep_merge(base, partial or {}))
                    items.append({"update": {
                        "_index": index, "_id": the_id, "_version": r.version,
                        "result": r.result, "_seq_no": r.seq_no,
                        "_primary_term": r.primary_term, "status": 200}})
                else:
                    r = shard.apply_index_on_primary(
                        the_id, source,
                        **({"op_type": "create"} if op == "create" else {}))
                    status = 201 if r.created else 200
                    items.append({op: {
                        "_index": index, "_id": the_id, "_version": r.version,
                        "result": r.result, "_seq_no": r.seq_no,
                        "_primary_term": r.primary_term, "status": status}})
                refresh_shards.add(shard)
            except EsException as exc:
                errors = True
                items.append({op: {
                    "_index": index, "_id": doc_id, "status": error_status(exc),
                    "error": {"type": type(exc).__name__, "reason": str(exc)}}})
        if req.param("refresh") in ("", "true", "wait_for"):
            for shard in refresh_shards:
                shard.refresh()
        return 200, {"took": int((time.perf_counter() - t0) * 1000),
                     "errors": errors, "items": items}

    controller.register("PUT", "/{index}/_doc/{id}", put_doc)
    controller.register("POST", "/{index}/_doc/{id}", put_doc)
    controller.register("PUT", "/{index}/_create/{id}", create_doc)
    controller.register("POST", "/{index}/_create/{id}", create_doc)
    controller.register("POST", "/{index}/_doc", post_doc)
    controller.register("GET", "/{index}/_doc/{id}", get_doc)
    controller.register("DELETE", "/{index}/_doc/{id}", delete_doc)
    controller.register("POST", "/{index}/_update/{id}", update_doc)
    controller.register("POST", "/_bulk", bulk)
    controller.register("PUT", "/_bulk", bulk)
    controller.register("POST", "/{index}/_bulk", bulk)
    controller.register("GET", "/_mget", mget)
    controller.register("POST", "/_mget", mget)
    controller.register("GET", "/{index}/_mget", mget)
    controller.register("POST", "/{index}/_mget", mget)


def _deep_merge(base: dict, update: dict) -> dict:
    out = dict(base)
    for k, v in update.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
