"""Document CRUD + bulk REST actions.

Reference: `RestIndexAction`, `RestGetAction`, `RestDeleteAction`,
`RestBulkAction`, `RestMultiGetAction` (SURVEY.md §2.1#10, §3.2). The
bulk body is NDJSON action/metadata lines exactly like the reference.

The op executors are module-level functions so the cluster transport
layer (cluster/service.py) can run the exact same local path when a
remote node forwards an operation to the shard owner — the reference's
TransportShardBulkAction / TransportGetAction primary-phase analog."""

from __future__ import annotations

import contextlib
import json
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (DocumentMissingException,
                                             IllegalArgumentException,
                                             EsException,
                                             EsRejectedExecutionException)
from elasticsearch_tpu.common.pressure import operation_bytes
from elasticsearch_tpu.rest.controller import (RestController, RestRequest,
                                               error_status)


def _auto_id() -> str:
    return uuid.uuid4().hex[:20]


def _coordinating_charge(node, source):
    """Admission charge for one client write at the coordinating stage
    (429 when over budget). No-op on node doubles without the tracker."""
    pressure = getattr(node, "indexing_pressure", None)
    if pressure is None:
        return contextlib.nullcontext()
    return pressure.coordinating(operation_bytes(source))


def _primary_charge(node, source):
    """Admission charge at the primary stage; skips the limit re-check
    when this thread's coordinating charge already admitted the op."""
    pressure = getattr(node, "indexing_pressure", None)
    if pressure is None:
        return contextlib.nullcontext()
    return pressure.primary(operation_bytes(source))


# ----------------------------------------------------------------------
# local op executors — run on the node that owns the target shard
# ----------------------------------------------------------------------

def run_ingest_pipeline(node, svc, body: dict, params
                        ) -> Tuple[Optional[dict], Optional[str]]:
    """→ (transformed source | None when dropped, pipeline id | None).
    Resolution order: ?pipeline= param, then index.default_pipeline
    ("_none" disables). Reference: IngestService#resolvePipelines."""
    pid = params.get("pipeline") or svc.settings.get(
        "index.default_pipeline")
    if not pid or pid == "_none":
        return body, None
    pipeline = node.ingest.get(pid)
    return pipeline.execute(body), pid


def _apply_refresh(node, shard, params, seq_no: int) -> None:
    """refresh= handling for a single-doc write. `wait_for` blocks on
    the shard's visibility checkpoint (the background NRT cycle does
    the refreshing) instead of forcing an immediate refresh — the
    reference semantics — and falls back to a forced refresh when no
    refresher is running or the wait times out, so the contract
    ("searchable when the call returns") always holds."""
    refresh = params.get("refresh")
    if refresh not in ("", "true", "wait_for"):
        return
    if refresh == "wait_for" and getattr(node, "refresher_active", False):
        if shard.wait_for_visible(seq_no):
            return
    shard.refresh()


def exec_index_doc(node, index: str, doc_id: Optional[str], body, params,
                   op_type: str = "index",
                   shard_num: Optional[int] = None) -> Tuple[int, Dict]:
    if not isinstance(body, dict):
        raise IllegalArgumentException("request body is required")
    # primary-stage bytes are held across apply AND replication — the
    # ack means every copy has the op, so the memory is in flight that
    # whole time
    with _primary_charge(node, body):
        index = node.indices.resolve_write_index(index)
        # cluster mode: the state applier creates local indices; a missing
        # index here is a routing error, not an auto-create trigger
        svc = (node.indices.index(index) if node.cluster is not None
               else node.get_or_autocreate_index(index))
        svc.check_write_block()
        created_id = doc_id or _auto_id()
        body, _pid = run_ingest_pipeline(node, svc, body, params)
        if body is None:  # a drop processor fired: acknowledged, not indexed
            return 200, {"_index": index, "_id": created_id,
                         "_version": -1, "result": "noop",
                         "_shards": {"total": 0, "successful": 0,
                                     "failed": 0}}
        if shard_num is None:
            shard_num = svc.shard_for_id(created_id, params.get("routing"))
        shard = svc.shard(shard_num)
        kwargs = {"op_type": op_type} if op_type != "index" else {}
        if params.get("if_seq_no") is not None:
            kwargs["if_seq_no"] = int(params["if_seq_no"])
        if params.get("if_primary_term") is not None:
            kwargs["if_primary_term"] = int(params["if_primary_term"])
        if params.get("version") is not None:
            kwargs["version"] = int(params["version"])
            kwargs["version_type"] = params.get("version_type", "internal")
        result = shard.apply_index_on_primary(created_id, body, **kwargs)
        node.replicate("index", index, shard_num, created_id, body, result)
        _apply_refresh(node, shard, params, result.seq_no)
        status = 201 if result.created else 200
        return status, {
            "_index": index, "_id": result.doc_id,
            "_version": result.version, "result": result.result,
            "_seq_no": result.seq_no, "_primary_term": result.primary_term,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
        }


def exec_get_doc(node, index: str, doc_id: str, params,
                 shard_num: Optional[int] = None) -> Tuple[int, Dict]:
    index = node.indices.resolve_write_index(index)
    svc = node.indices.index(index)
    if shard_num is None:
        shard_num = svc.shard_for_id(doc_id, params.get("routing"))
    shard = svc.shard(shard_num)
    got = shard.get(doc_id)
    if got is None:
        return 404, {"_index": index, "_id": doc_id, "found": False}
    got["_index"] = index
    return 200, got


def exec_delete_doc(node, index: str, doc_id: str, params,
                    shard_num: Optional[int] = None) -> Tuple[int, Dict]:
    with _primary_charge(node, None):
        index = node.indices.resolve_write_index(index)
        svc = node.indices.index(index)
        svc.check_write_block()
        if shard_num is None:
            shard_num = svc.shard_for_id(doc_id, params.get("routing"))
        shard = svc.shard(shard_num)
        result = shard.apply_delete_on_primary(doc_id)
        node.replicate("delete", index, shard_num, doc_id, None, result)
        _apply_refresh(node, shard, params, result.seq_no)
    if not result.found:
        return 404, {"_index": index, "_id": doc_id,
                     "result": "not_found", "_version": result.version,
                     "_seq_no": result.seq_no,
                     "_primary_term": result.primary_term}
    return 200, {"_index": index, "_id": doc_id,
                 "result": "deleted", "_version": result.version,
                 "_seq_no": result.seq_no,
                 "_primary_term": result.primary_term,
                 "_shards": {"total": 1, "successful": 1, "failed": 0}}


def run_update_script(script, source: Dict[str, Any],
                      *, op: str = "index") -> Tuple[str, Dict[str, Any]]:
    """Execute an update script against a `ctx` holding `_source` and
    `op` (reference: UpdateHelper#executeScriptedUpsert). → (op,
    new_source); op ∈ index|none|delete. Mutates a COPY."""
    import copy
    from elasticsearch_tpu.script import ScriptException
    ctx = {"_source": copy.deepcopy(source), "op": op,
           "_now": int(time.time() * 1000)}
    try:
        script.execute({"ctx": ctx})
    except ScriptException as e:
        raise IllegalArgumentException(
            f"failed to execute script: "
            f"{e.args[0] if e.args else e}") from None
    out_op = ctx.get("op", "index")
    if out_op in ("noop", "none"):
        out_op = "none"
    elif out_op not in ("index", "delete", "create"):
        raise IllegalArgumentException(
            f"Operation type [{out_op}] not allowed, only "
            f"[create, index, noop, delete] are allowed")
    new_source = ctx.get("_source")
    if not isinstance(new_source, dict):
        raise IllegalArgumentException(
            "update script removed [ctx._source]")
    return out_op, new_source


def exec_update_doc(node, index: str, doc_id: str, body, params,
                    shard_num: Optional[int] = None) -> Tuple[int, Dict]:
    """_update: doc-merge, doc_as_upsert, and scripted updates
    (ctx._source mutation, ctx.op noop/delete, scripted_upsert) —
    reference: UpdateHelper#prepare."""
    with _primary_charge(node, body):
        return _exec_update_doc(node, index, doc_id, body, params,
                                shard_num=shard_num)


def _exec_update_doc(node, index: str, doc_id: str, body, params,
                     shard_num: Optional[int] = None) -> Tuple[int, Dict]:
    index = node.indices.resolve_write_index(index)
    svc = node.indices.index(index)
    svc.check_write_block()
    if shard_num is None:
        shard_num = svc.shard_for_id(doc_id, params.get("routing"))
    shard = svc.shard(shard_num)
    body = body or {}
    partial = body.get("doc")
    script = None
    if "script" in body:
        if partial is not None:
            raise IllegalArgumentException(
                "Validation Failed: can't provide both script and doc")
        from elasticsearch_tpu.script import (ScriptException,
                                              compile_script)
        try:
            script = compile_script(body["script"])
        except ScriptException as e:
            raise IllegalArgumentException(
                str(e.args[0] if e.args else e)) from None
    if partial is None and script is None:
        raise IllegalArgumentException(
            "Validation Failed: script or doc is missing")
    existing = shard.get(doc_id)
    if existing is None:
        if script is not None:
            if "upsert" not in body:
                raise DocumentMissingException(
                    f"[{doc_id}]: document missing")
            base = body["upsert"]
            if body.get("scripted_upsert"):
                op, merged = run_update_script(script, base, op="create")
                if op == "delete":   # deleting a doc that never existed
                    op = "none"
            else:
                op, merged = "index", base
        elif body.get("doc_as_upsert"):
            op, merged = "index", partial
        elif "upsert" in body:
            op, merged = "index", body["upsert"]
        else:
            raise DocumentMissingException(f"[{doc_id}]: document missing")
    else:
        base = dict(existing["_source"] or {})
        if script is not None:
            op, merged = run_update_script(script, base)
        else:
            merged = _deep_merge(base, partial)
            # doc-merge with no change is a noop (detect_noop default)
            op = "none" if (body.get("detect_noop", True)
                            and merged == base) else "index"
    if op == "none":
        return 200, {"_index": index, "_id": doc_id,
                     "_version": (existing or {}).get("_version", 1),
                     "result": "noop",
                     "_shards": {"total": 0, "successful": 0,
                                 "failed": 0}}
    if op == "delete":
        result = shard.apply_delete_on_primary(doc_id)
        node.replicate("delete", index, shard_num, doc_id, None, result)
        _apply_refresh(node, shard, params, result.seq_no)
        return 200, {"_index": index, "_id": doc_id,
                     "_version": result.version, "result": "deleted",
                     "_seq_no": result.seq_no,
                     "_primary_term": result.primary_term}
    result = shard.apply_index_on_primary(doc_id, merged)
    node.replicate("index", index, shard_num, doc_id, merged, result)
    _apply_refresh(node, shard, params, result.seq_no)
    return 200, {"_index": index, "_id": doc_id,
                 "_version": result.version, "result": result.result,
                 "_seq_no": result.seq_no,
                 "_primary_term": result.primary_term}


# ----------------------------------------------------------------------
# bulk: parse NDJSON → op list; apply list locally; REST reassembles
# ----------------------------------------------------------------------

def parse_bulk_body(raw: str, default_index: Optional[str]
                    ) -> List[Dict[str, Any]]:
    """NDJSON → [{op, index, id, routing, source}] with reference-shaped
    validation errors."""
    lines = [ln for ln in raw.split("\n") if ln.strip()]
    ops: List[Dict[str, Any]] = []
    i = 0
    while i < len(lines):
        try:
            action_line = json.loads(lines[i])
        except json.JSONDecodeError as e:
            raise IllegalArgumentException(
                f"Malformed action/metadata line [{i + 1}]: {e}")
        if len(action_line) != 1:
            raise IllegalArgumentException(
                f"Malformed action/metadata line [{i + 1}]")
        op, meta = next(iter(action_line.items()))
        if op not in ("index", "create", "delete", "update"):
            raise IllegalArgumentException(f"Unknown bulk action [{op}]")
        index = meta.get("_index", default_index)
        doc_id = meta.get("_id")
        i += 1
        source = None
        if op != "delete":
            if i >= len(lines):
                raise IllegalArgumentException(
                    "Validation Failed: bulk source line missing")
            source = json.loads(lines[i])
            i += 1
        ops.append({"op": op, "index": index,
                    "id": doc_id or _auto_id(),
                    "routing": meta.get("routing"), "source": source,
                    "pipeline": meta.get("pipeline")})
    return ops


def apply_bulk_ops(node, ops: List[Dict[str, Any]], *,
                   refresh: bool = False,
                   wait_for: bool = False,
                   pressure_stage: str = "coordinating"
                   ) -> List[Dict[str, Any]]:
    """Apply parsed bulk ops against LOCAL shards; returns response items
    in op order. Per-op failures become error items, never exceptions
    (reference: BulkItemResponse).

    Admission is PER OP: each op charges its bytes against indexing
    pressure before any work; a rejected op becomes a per-item 429 error
    entry while its siblings still apply (reference: bulk item-level
    EsRejectedExecutionException). `pressure_stage` names the stage the
    caller is charging — "coordinating" for client-facing entry points,
    "primary" when a remote coordinating node already admitted the ops
    (checked against the shared limit), "primary_local" when this node's
    own coordinating admission covers them (accounted, not re-checked).
    Every admitted charge is released when the request finishes, through
    failure paths included.

    Maximal runs of plain index ops (no CAS) group per shard and apply
    through the engine's batched path — one lock + one translog fsync per
    (shard, run), analysis out of the lock (reference:
    TransportShardBulkAction's shard-level grouping, SURVEY.md §3.2/P6).
    Runs preserve total op order, so mixed sequences on one _id keep
    their semantics."""
    items: List[Optional[Dict[str, Any]]] = [None] * len(ops)
    refresh_shards = set()
    pressure = getattr(node, "indexing_pressure", None)
    releases: List[Any] = []
    try:
        if pressure is not None:
            for pos, entry in enumerate(ops):
                nbytes = operation_bytes(entry.get("source"))
                try:
                    if pressure_stage == "coordinating":
                        releases.append(pressure.mark_coordinating(nbytes))
                    elif pressure_stage == "primary":
                        releases.append(pressure.mark_primary(nbytes))
                    else:  # primary_local: admitted by this node already
                        releases.append(pressure.mark_primary(
                            nbytes, local_to_coordinating=True))
                except EsRejectedExecutionException as exc:
                    items[pos] = _bulk_error_item(
                        entry["op"], entry.get("index"), entry.get("id"),
                        exc)
        i = 0
        while i < len(ops):
            if items[i] is not None:  # rejected at admission
                i += 1
            elif _plain_index_op(ops[i]):
                j = i
                while (j < len(ops) and items[j] is None
                       and _plain_index_op(ops[j])):
                    j += 1
                _apply_index_run(node, ops, range(i, j), items,
                                 refresh_shards)
                i = j
            else:
                items[i] = _apply_one_op(node, ops[i], refresh_shards)
                i += 1
        if refresh:
            for shard in refresh_shards:
                # refresh=wait_for rides the background NRT cycle: wait
                # until the shard's visibility checkpoint covers every
                # op this request applied (its local checkpoint), and
                # only force a refresh when no cycle runs / wait times out
                if wait_for and getattr(node, "refresher_active", False):
                    if shard.wait_for_visible(shard.local_checkpoint):
                        continue
                shard.refresh()
        return items  # type: ignore[return-value]
    finally:
        for release in releases:
            release()


def _plain_index_op(entry: Dict[str, Any]) -> bool:
    return (entry["op"] == "index"
            and entry.get("if_seq_no") is None)


def _resolve_target(node, entry: Dict[str, Any]):
    """Shared bulk-op target resolution: (concrete index, IndexService,
    shard number). Raises EsException on a missing/unresolvable index."""
    index = entry["index"]
    if index is None:
        raise IllegalArgumentException("_index is missing")
    index = node.indices.resolve_write_index(index)
    svc = (node.indices.index(index) if node.cluster is not None
           else node.get_or_autocreate_index(index))
    svc.check_write_block()
    shard_num = entry.get("shard")
    if shard_num is None:
        shard_num = svc.shard_for_id(entry["id"], entry.get("routing"))
    return index, svc, shard_num


def _apply_index_run(node, ops, positions, items, refresh_shards) -> None:
    """Apply a run of plain index ops grouped per (index, shard) through
    the engine bulk path; fill `items` at each op's position."""
    groups: Dict[Any, List[int]] = {}
    for pos in positions:
        entry = ops[pos]
        try:
            index, svc, shard_num = _resolve_target(node, entry)
            source, _pid = run_ingest_pipeline(
                node, svc, entry.get("source"),
                {"pipeline": entry.get("pipeline")})
            if source is None:  # drop processor
                items[pos] = {"index": {
                    "_index": index, "_id": entry["id"], "_version": -1,
                    "result": "noop", "status": 200}}
                continue
            entry["_resolved"] = (index, shard_num, source)
            groups.setdefault((index, shard_num), []).append(pos)
        except EsException as exc:
            items[pos] = _bulk_error_item("index", entry["index"],
                                          entry["id"], exc)
    # shard bulks apply CONCURRENTLY (engine locks are per shard; the
    # analysis hot loop runs native code that releases the GIL) —
    # reference: TransportBulkAction fans shard bulks out in parallel
    def run_group(item):
        (index, shard_num), poss = item
        try:
            svc = node.indices.index(index)
            shard = svc.shard(shard_num)
            docs = [(ops[p]["id"], ops[p]["_resolved"][2]) for p in poss]
            return shard, shard.apply_bulk_index_on_primary(docs)
        except EsException as exc:
            return None, exc

    group_items = list(groups.items())
    if len(group_items) > 1:
        outs = list(_bulk_executor().map(run_group, group_items))
    else:
        outs = [run_group(g) for g in group_items]
    for ((index, shard_num), poss), (shard, results) in zip(group_items,
                                                            outs):
        if shard is None:
            for p in poss:
                items[p] = _bulk_error_item("index", index, ops[p]["id"],
                                            results)
            continue
        refresh_shards.add(shard)
        for p, r in zip(poss, results):
            the_id = ops[p]["id"]
            if isinstance(r, Exception):
                if not isinstance(r, EsException):
                    raise r
                items[p] = _bulk_error_item("index", index, the_id, r)
                continue
            node.replicate("index", index, shard_num, the_id,
                           ops[p]["_resolved"][2], r)
            items[p] = {"index": {
                "_index": index, "_id": the_id, "_version": r.version,
                "result": r.result, "_seq_no": r.seq_no,
                "_primary_term": r.primary_term,
                "status": 201 if r.created else 200}}


_BULK_EXECUTOR = None


def _bulk_executor():
    """Shared pool for concurrent shard-bulk application."""
    global _BULK_EXECUTOR
    if _BULK_EXECUTOR is None:
        import os
        from concurrent.futures import ThreadPoolExecutor
        # floor of 4: shard bulks overlap on GIL-releasing work (native
        # analysis, translog I/O, numpy) even on small host cpu counts
        _BULK_EXECUTOR = ThreadPoolExecutor(
            max_workers=min(8, max(4, os.cpu_count() or 1)),
            thread_name_prefix="shard-bulk")
    return _BULK_EXECUTOR


def _bulk_error_item(op, index, the_id, exc) -> Dict[str, Any]:
    return {op: {
        "_index": index, "_id": the_id, "status": error_status(exc),
        "error": {"type": type(exc).__name__, "reason": str(exc)}}}


def _apply_one_op(node, entry: Dict[str, Any],
                  refresh_shards) -> Dict[str, Any]:
    """Apply one non-batchable bulk op (delete/update/create/CAS)."""
    op, index, the_id = entry["op"], entry["index"], entry["id"]
    source = entry.get("source")
    try:
        index, svc, shard_num = _resolve_target(node, entry)
        shard = svc.shard(shard_num)
        seqno_kwargs = {}
        if entry.get("if_seq_no") is not None:
            seqno_kwargs = {
                "if_seq_no": int(entry["if_seq_no"]),
                "if_primary_term": int(entry["if_primary_term"])}
        if op == "delete":
            r = shard.apply_delete_on_primary(the_id, **seqno_kwargs)
            node.replicate("delete", index, shard_num, the_id, None, r)
            refresh_shards.add(shard)
            status = 200 if r.found else 404
            return {"delete": {
                "_index": index, "_id": the_id, "_version": r.version,
                "result": "deleted" if r.found else "not_found",
                "_seq_no": r.seq_no, "_primary_term": r.primary_term,
                "status": status}}
        if op == "update":
            body = source or {}
            script = None
            if "script" in body:
                if body.get("doc") is not None:
                    raise IllegalArgumentException(
                        "Validation Failed: can't provide both script "
                        "and doc")
                from elasticsearch_tpu.script import (ScriptException,
                                                      compile_script)
                try:
                    script = compile_script(body["script"])
                except ScriptException as e:
                    raise IllegalArgumentException(
                        str(e.args[0] if e.args else e)) from None
            partial = body.get("doc")
            existing = shard.get(the_id)
            if existing is None and not body.get("doc_as_upsert"):
                raise DocumentMissingException(
                    f"[{the_id}]: document missing")
            base = dict((existing or {}).get("_source") or {})
            if script is not None:
                upd_op, merged = run_update_script(script, base)
            else:
                upd_op, merged = "index", _deep_merge(base, partial or {})
            if upd_op == "none":
                return {"update": {
                    "_index": index, "_id": the_id,
                    "_version": (existing or {}).get("_version", 1),
                    "result": "noop", "status": 200}}
            if upd_op == "delete":
                r = shard.apply_delete_on_primary(the_id)
                node.replicate("delete", index, shard_num, the_id,
                               None, r)
                refresh_shards.add(shard)
                return {"update": {
                    "_index": index, "_id": the_id,
                    "_version": r.version, "result": "deleted",
                    "_seq_no": r.seq_no,
                    "_primary_term": r.primary_term, "status": 200}}
            r = shard.apply_index_on_primary(the_id, merged)
            node.replicate("index", index, shard_num, the_id, merged, r)
            refresh_shards.add(shard)
            return {"update": {
                "_index": index, "_id": the_id, "_version": r.version,
                "result": r.result, "_seq_no": r.seq_no,
                "_primary_term": r.primary_term, "status": 200}}
        source, _pid = run_ingest_pipeline(
            node, svc, source,
            {"pipeline": entry.get("pipeline")})
        if source is None:  # drop processor
            return {op: {
                "_index": index, "_id": the_id, "_version": -1,
                "result": "noop", "status": 200}}
        r = shard.apply_index_on_primary(
            the_id, source, **seqno_kwargs,
            **({"op_type": "create"} if op == "create" else {}))
        node.replicate("index", index, shard_num, the_id, source, r)
        refresh_shards.add(shard)
        status = 201 if r.created else 200
        return {op: {
            "_index": index, "_id": the_id, "_version": r.version,
            "result": r.result, "_seq_no": r.seq_no,
            "_primary_term": r.primary_term, "status": status}}
    except EsException as exc:
        return _bulk_error_item(op, index, the_id, exc)


def bulk_has_errors(items: List[Dict[str, Any]]) -> bool:
    return any("error" in next(iter(it.values())) for it in items)


# ----------------------------------------------------------------------
# REST registration
# ----------------------------------------------------------------------

def register(controller: RestController, node) -> None:
    indices = node.indices

    def put_doc(req: RestRequest):
        op_type = ("create" if req.params.get("op_type") == "create"
                   else "index")
        with _coordinating_charge(node, req.body):
            if node.cluster is not None:
                return node.cluster.route_doc_op(
                    "index" if op_type == "index" else "create",
                    req.param("index"), req.param("id"), req.body,
                    req.params)
            return exec_index_doc(node, req.param("index"),
                                  req.param("id"), req.body, req.params,
                                  op_type=op_type)

    def create_doc(req: RestRequest):
        """op_type=create: 409 if the doc exists — enforced inside the
        engine's write lock so concurrent creates serialize (reference:
        version_conflict_engine_exception on op_type=create)."""
        with _coordinating_charge(node, req.body):
            if node.cluster is not None:
                return node.cluster.route_doc_op(
                    "create", req.param("index"), req.param("id"),
                    req.body, req.params)
            return exec_index_doc(node, req.param("index"),
                                  req.param("id"), req.body, req.params,
                                  op_type="create")

    def post_doc(req: RestRequest):
        with _coordinating_charge(node, req.body):
            if node.cluster is not None:
                return node.cluster.route_doc_op(
                    "index", req.param("index"), None, req.body,
                    req.params)
            return exec_index_doc(node, req.param("index"), None,
                                  req.body, req.params)

    def get_doc(req: RestRequest):
        if node.cluster is not None:
            return node.cluster.route_doc_op(
                "get", req.param("index"), req.param("id"), None, req.params)
        return exec_get_doc(node, req.param("index"), req.param("id"),
                            req.params)

    def delete_doc(req: RestRequest):
        with _coordinating_charge(node, None):
            if node.cluster is not None:
                return node.cluster.route_doc_op(
                    "delete", req.param("index"), req.param("id"), None,
                    req.params)
            return exec_delete_doc(node, req.param("index"),
                                   req.param("id"), req.params)

    def update_doc(req: RestRequest):
        with _coordinating_charge(node, req.body):
            if node.cluster is not None:
                return node.cluster.route_doc_op(
                    "update", req.param("index"), req.param("id"),
                    req.body, req.params)
            return exec_update_doc(node, req.param("index"),
                                   req.param("id"), req.body, req.params)

    def mget(req: RestRequest):
        body = req.body or {}
        docs_spec = body.get("docs")
        default_index = req.param("index")
        if docs_spec is None and "ids" in body:
            docs_spec = [{"_id": i} for i in body["ids"]]
        if docs_spec is None:
            raise IllegalArgumentException("[_mget] requires docs or ids")
        out = []
        for spec in docs_spec:
            index = spec.get("_index", default_index)
            doc_id = spec["_id"]
            try:
                if node.cluster is not None:
                    _status, got = node.cluster.route_doc_op(
                        "get", index, doc_id, None, {})
                    if not got.get("found", "_source" in got):
                        got = None
                else:
                    svc = indices.index(index)
                    shard = svc.shard(svc.shard_for_id(doc_id))
                    got = shard.get(doc_id)
                    if got is not None:
                        got["_index"] = index
            except EsException:
                got = None
            if got is None:
                out.append({"_index": index, "_id": doc_id, "found": False})
            else:
                out.append(got)
        return 200, {"docs": out}

    def bulk(req: RestRequest):
        t0 = time.perf_counter()
        raw = req.raw_body.decode("utf-8") if req.raw_body else (
            req.body if isinstance(req.body, str) else "")
        ops = parse_bulk_body(raw, req.param("index"))
        url_pipeline = req.params.get("pipeline")
        if url_pipeline:
            for entry in ops:
                if not entry.get("pipeline"):
                    entry["pipeline"] = url_pipeline
        refresh = req.param("refresh") in ("", "true", "wait_for")
        if node.cluster is not None:
            items = node.cluster.route_bulk(ops, refresh=refresh)
        else:
            items = apply_bulk_ops(
                node, ops, refresh=refresh,
                wait_for=req.param("refresh") == "wait_for")
        return 200, {"took": int((time.perf_counter() - t0) * 1000),
                     "errors": bulk_has_errors(items), "items": items}

    def _by_query(action: str, fn, *args):
        task = node.task_manager.register(action)
        try:
            return 200, fn(*args, task=task)
        finally:
            node.task_manager.unregister(task)

    def do_reindex(req: RestRequest):
        from elasticsearch_tpu import reindex as reindex_mod
        return _by_query("indices:data/write/reindex",
                         reindex_mod.reindex, node, req.body or {},
                         req.params)

    def do_update_by_query(req: RestRequest):
        from elasticsearch_tpu import reindex as reindex_mod
        return _by_query("indices:data/write/update/byquery",
                         reindex_mod.update_by_query, node,
                         req.param("index"), req.body, req.params)

    def do_delete_by_query(req: RestRequest):
        from elasticsearch_tpu import reindex as reindex_mod
        return _by_query("indices:data/write/delete/byquery",
                         reindex_mod.delete_by_query, node,
                         req.param("index"), req.body, req.params)

    controller.register("POST", "/_reindex", do_reindex)
    controller.register("POST", "/{index}/_update_by_query",
                        do_update_by_query)
    controller.register("POST", "/{index}/_delete_by_query",
                        do_delete_by_query)
    controller.register("PUT", "/{index}/_doc/{id}", put_doc)
    controller.register("POST", "/{index}/_doc/{id}", put_doc)
    controller.register("PUT", "/{index}/_create/{id}", create_doc)
    controller.register("POST", "/{index}/_create/{id}", create_doc)
    controller.register("POST", "/{index}/_doc", post_doc)
    controller.register("GET", "/{index}/_doc/{id}", get_doc)
    controller.register("DELETE", "/{index}/_doc/{id}", delete_doc)
    controller.register("POST", "/{index}/_update/{id}", update_doc)
    controller.register("POST", "/_bulk", bulk)
    controller.register("PUT", "/_bulk", bulk)
    controller.register("POST", "/{index}/_bulk", bulk)
    controller.register("GET", "/_mget", mget)
    controller.register("POST", "/_mget", mget)
    controller.register("GET", "/{index}/_mget", mget)
    controller.register("POST", "/{index}/_mget", mget)


def _deep_merge(base: dict, update: dict) -> dict:
    out = dict(base)
    for k, v in update.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
