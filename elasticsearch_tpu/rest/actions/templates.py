"""Index template REST actions (reference: RestPutComposableIndex
TemplateAction et al — SURVEY.md §2.1#49)."""

from __future__ import annotations

import fnmatch

from elasticsearch_tpu.rest.controller import RestController, RestRequest


def _registry(node):
    if node.cluster is not None:
        return node.cluster.applied_state().index_templates
    return node.templates.templates


def register(controller: RestController, node) -> None:

    def put_template(req: RestRequest):
        name = req.param("name")
        if node.cluster is not None:
            node.cluster.put_template(name, req.body or {})
        else:
            node.templates.put(name, req.body or {})
        return 200, {"acknowledged": True}

    def get_template(req: RestRequest):
        name = req.param("name")
        registry = _registry(node)
        if name and not any(c in name for c in "*?["):
            if name not in registry:
                from elasticsearch_tpu.common.errors import \
                    ResourceNotFoundException
                raise ResourceNotFoundException(
                    f"index template matching [{name}] not found")
            names = [name]
        elif name:
            names = sorted(fnmatch.filter(registry, name))
        else:
            names = sorted(registry)
        return 200, {"index_templates": [
            {"name": n, "index_template": registry[n]} for n in names]}

    def head_template(req: RestRequest):
        return (200, {}) if req.param("name") in _registry(node) \
            else (404, {})

    def delete_template(req: RestRequest):
        name = req.param("name")
        if node.cluster is not None:
            node.cluster.delete_template(name)
        else:
            node.templates.delete(name)
        return 200, {"acknowledged": True}

    def cat_templates(req: RestRequest):
        from elasticsearch_tpu.rest.actions.cluster import _cat_table
        rows = [[n, "[" + ", ".join(t["index_patterns"]) + "]",
                 t.get("priority", 0), t.get("version") or "-"]
                for n, t in sorted(_registry(node).items())]
        return _cat_table(req, ["name", "index_patterns", "order",
                                "version"], rows)

    controller.register("PUT", "/_index_template/{name}", put_template)
    controller.register("POST", "/_index_template/{name}", put_template)
    controller.register("GET", "/_index_template/{name}", get_template)
    controller.register("GET", "/_index_template", get_template)
    controller.register("HEAD", "/_index_template/{name}", head_template)
    controller.register("DELETE", "/_index_template/{name}",
                        delete_template)
    controller.register("GET", "/_cat/templates", cat_templates)
