"""Index administration REST actions: create/delete/get index, mappings,
settings, refresh/flush/forcemerge, open/close stubs (reference:
`action/admin/indices/**` + `RestCreateIndexAction` etc., SURVEY.md
§2.1#49)."""

from __future__ import annotations

from typing import Any, Dict

from elasticsearch_tpu.common.errors import IndexNotFoundException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.search.coordinator import resolve_indices


def register(controller: RestController, node) -> None:
    indices = node.indices

    def create_index(req: RestRequest):
        body = req.body or {}
        mappings = body.get("mappings")
        name = req.param("index")
        if node.cluster is not None:
            node.cluster.create_index(name, body.get("settings") or {},
                                      mappings)
        else:
            node.create_index(name, Settings(
                Settings.normalize_index_settings(
                    body.get("settings"))), mappings)
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "index": name}

    def delete_index(req: RestRequest):
        from elasticsearch_tpu.search.coordinator import \
            resolve_concrete_indices
        if node.cluster is not None:
            view = node.cluster._StateView(node.cluster.applied_state())
            for name in resolve_concrete_indices(view,
                                                 req.param("index")):
                node.cluster.delete_index(name)
            return 200, {"acknowledged": True}
        for name in resolve_concrete_indices(indices,
                                             req.param("index")):
            indices.delete_index(name)
            tpu = getattr(node, "tpu_search", None)
            if tpu is not None:  # drop resident packs + HBM accounting
                tpu.invalidate_index(name)
        return 200, {"acknowledged": True}

    def close_index(req: RestRequest):
        from elasticsearch_tpu.search.coordinator import \
            resolve_concrete_indices
        if node.cluster is not None:
            out = None
            for name in resolve_concrete_indices(
                    node.cluster._StateView(node.cluster.applied_state()),
                    req.param("index")):
                out = node.cluster.close_index_admin(name)
            return 200, out or {"acknowledged": True}
        closed = {}
        for name in resolve_concrete_indices(indices, req.param("index")):
            indices.close_index(name)
            closed[name] = {"closed": True}
            tpu = getattr(node, "tpu_search", None)
            if tpu is not None:
                tpu.invalidate_index(name)
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "indices": closed}

    def open_index(req: RestRequest):
        from elasticsearch_tpu.search.coordinator import \
            resolve_concrete_indices
        if node.cluster is not None:
            out = None
            for name in resolve_concrete_indices(
                    node.cluster._StateView(node.cluster.applied_state()),
                    req.param("index")):
                out = node.cluster.open_index_admin(name)
            return 200, out or {"acknowledged": True}
        for name in resolve_concrete_indices(indices, req.param("index")):
            indices.open_index(name)
        return 200, {"acknowledged": True, "shards_acknowledged": True}

    def rollover(req: RestRequest):
        from elasticsearch_tpu import lifecycle
        return 200, lifecycle.rollover(
            node, req.param("index"), req.body,
            new_index=req.params.get("new_index") or None,
            dry_run=req.params.get("dry_run") in ("", "true", True))

    def rollover_named(req: RestRequest):
        from elasticsearch_tpu import lifecycle
        return 200, lifecycle.rollover(
            node, req.param("index"), req.body,
            new_index=req.param("new_index"),
            dry_run=req.params.get("dry_run") in ("", "true", True))

    def shrink_index(req: RestRequest):
        from elasticsearch_tpu import lifecycle
        return 200, lifecycle.shrink(node, req.param("index"),
                                     req.param("target"), req.body)

    def split_index(req: RestRequest):
        from elasticsearch_tpu import lifecycle
        return 200, lifecycle.split(node, req.param("index"),
                                    req.param("target"), req.body)

    def get_index(req: RestRequest):
        if node.cluster is not None:
            state = node.cluster.applied_state()
            out = {}
            for name in node.cluster.resolve_indices(req.param("index")):
                meta = state.indices[name]
                out[name] = {
                    "aliases": dict(meta.aliases),
                    "mappings": meta.mapping or {},
                    "settings": {"index": {
                        "number_of_shards": str(meta.number_of_shards),
                        "number_of_replicas": str(meta.number_of_replicas),
                        "uuid": meta.uuid}},
                }
            if not out:
                raise IndexNotFoundException(
                    f"no such index [{req.param('index')}]")
            return 200, out
        out = {}
        for name in resolve_indices(indices, req.param("index")):
            svc = indices.index(name)
            out[name] = {
                "aliases": {a: p for a, tgts in indices.aliases.items()
                            for i, p in tgts.items() if i == name},
                "mappings": svc.mapper.to_mapping(),
                "settings": {"index": {
                    "number_of_shards": str(svc.num_shards),
                    "number_of_replicas": str(svc.num_replicas),
                    "uuid": svc.index_uuid,
                    **{k[len("index."):]: v for k, v in
                       svc.settings.get_as_dict().items()
                       if k.startswith("index.") and k not in
                       ("index.number_of_shards", "index.number_of_replicas")},
                }},
            }
        if not out:
            raise IndexNotFoundException(
                f"no such index [{req.param('index')}]")
        return 200, out

    def head_index(req: RestRequest):
        if node.cluster is not None:
            names = node.cluster.resolve_indices(req.param("index"))
            return (200, {}) if names else (404, {})
        names = resolve_indices(indices, req.param("index"))
        return (200, {}) if names else (404, {})

    def put_mapping(req: RestRequest):
        tpu = getattr(node, "tpu_search", None)
        if node.cluster is not None:
            for name in node.cluster.resolve_indices(req.param("index")):
                node.cluster.put_mapping(name, req.body or {})
                if tpu is not None:
                    tpu.invalidate_plans(name)
            return 200, {"acknowledged": True}
        for name in resolve_indices(indices, req.param("index")):
            indices.index(name).mapper.merge(req.body or {})
            if tpu is not None:
                # lowered plans key on the mapping generation; purge the
                # now-unreachable entries so the LRU doesn't carry them
                tpu.invalidate_plans(name)
        indices.persist_metadata()  # mapping is part of gateway state
        return 200, {"acknowledged": True}

    def get_mapping(req: RestRequest):
        if node.cluster is not None:
            state = node.cluster.applied_state()
            return 200, {
                name: {"mappings": state.indices[name].mapping or {}}
                for name in node.cluster.resolve_indices(
                    req.param("index"))}
        out = {}
        for name in resolve_indices(indices, req.param("index")):
            out[name] = {"mappings": indices.index(name).mapper.to_mapping()}
        return 200, out

    def put_settings(req: RestRequest):
        body = req.body or {}
        # accepted spellings (all reference forms): {"index": {...}},
        # {"settings": {...}}, flat dotted keys ("index.x" / "x")
        changes = Settings.normalize_index_settings(
            body.get("settings", body))
        if node.cluster is not None:
            for name in node.cluster.resolve_indices(req.param("index")):
                node.cluster.update_index_settings(name, changes)
            return 200, {"acknowledged": True}
        from elasticsearch_tpu.indices.service import IndexService
        IndexService.validate_dynamic_settings(changes)
        for name in resolve_indices(indices, req.param("index")):
            indices.index(name).apply_dynamic_settings(changes)
        indices.persist_metadata()
        return 200, {"acknowledged": True}

    def get_settings(req: RestRequest):
        out = {}
        for name in resolve_indices(indices, req.param("index")):
            svc = indices.index(name)
            out[name] = {"settings": {"index": {
                "number_of_shards": str(svc.num_shards),
                "number_of_replicas": str(svc.num_replicas),
                "uuid": svc.index_uuid}}}
        return 200, out

    def refresh(req: RestRequest):
        if node.cluster is not None:
            return 200, node.cluster.broadcast_maintenance(
                "refresh", req.param("index"))
        n = 0
        for name in resolve_indices(indices, req.param("index")):
            indices.index(name).refresh()
            n += indices.index(name).num_shards
        return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}

    def flush(req: RestRequest):
        if node.cluster is not None:
            return 200, node.cluster.broadcast_maintenance(
                "flush", req.param("index"))
        n = 0
        for name in resolve_indices(indices, req.param("index")):
            indices.index(name).flush()
            n += indices.index(name).num_shards
        return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}

    def forcemerge(req: RestRequest):
        if node.cluster is not None:
            return 200, node.cluster.broadcast_maintenance(
                "forcemerge", req.param("index"))
        n = 0
        for name in resolve_indices(indices, req.param("index")):
            svc = indices.index(name)
            for shard in svc.shards.values():
                shard.engine.force_merge()
                n += 1
        return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}

    def index_stats(req: RestRequest):
        names = resolve_indices(indices, req.param("index"))
        out_indices = {}
        total_docs = 0
        total_segments = 0
        for name in names:
            svc = indices.index(name)
            st = svc.stats()
            total_docs += st["docs"]["count"]
            segs = sum(p["segments"] for p in st["per_shard"])
            total_segments += segs
            out_indices[name] = {
                "primaries": {"docs": {"count": st["docs"]["count"]},
                              "segments": {"count": segs}},
                "total": {"docs": {"count": st["docs"]["count"]},
                          "segments": {"count": segs}},
            }
        return 200, {
            "_shards": {"total": sum(indices.index(n).num_shards for n in names)},
            "_all": {"primaries": {"docs": {"count": total_docs},
                                   "segments": {"count": total_segments}}},
            "indices": out_indices,
        }

    controller.register("PUT", "/{index}", create_index)
    controller.register("DELETE", "/{index}", delete_index)
    controller.register("POST", "/{index}/_close", close_index)
    controller.register("POST", "/{index}/_open", open_index)
    controller.register("POST", "/{index}/_rollover", rollover)
    controller.register("POST", "/{index}/_rollover/{new_index}",
                        rollover_named)
    controller.register("PUT", "/{index}/_shrink/{target}", shrink_index)
    controller.register("POST", "/{index}/_shrink/{target}", shrink_index)
    controller.register("PUT", "/{index}/_split/{target}", split_index)
    controller.register("POST", "/{index}/_split/{target}", split_index)
    controller.register("GET", "/{index}", get_index)
    controller.register("HEAD", "/{index}", head_index)
    controller.register("PUT", "/{index}/_mapping", put_mapping)
    controller.register("GET", "/{index}/_mapping", get_mapping)
    controller.register("GET", "/_mapping", get_mapping)
    controller.register("GET", "/{index}/_settings", get_settings)
    controller.register("GET", "/_settings", get_settings)
    controller.register("PUT", "/{index}/_settings", put_settings)
    controller.register("POST", "/{index}/_refresh", refresh)
    controller.register("POST", "/_refresh", refresh)
    controller.register("GET", "/{index}/_refresh", refresh)
    controller.register("POST", "/{index}/_flush", flush)
    controller.register("POST", "/_flush", flush)
    controller.register("POST", "/{index}/_forcemerge", forcemerge)
    controller.register("GET", "/{index}/_stats", index_stats)
    controller.register("GET", "/_stats", index_stats)
