"""Ingest pipeline REST actions (reference: RestPutPipelineAction,
RestGetPipelineAction, RestDeletePipelineAction,
RestSimulatePipelineAction — SURVEY.md §2.1#41)."""

from __future__ import annotations

from typing import Any, Dict

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.ingest import IngestProcessorException, Pipeline
from elasticsearch_tpu.rest.controller import RestController, RestRequest


def register(controller: RestController, node) -> None:

    def put_pipeline(req: RestRequest):
        body = req.body
        if not isinstance(body, dict):
            raise IllegalArgumentException("pipeline body is required")
        pid = req.param("id")
        if node.cluster is not None:
            node.cluster.put_pipeline(pid, body)
        else:
            node.ingest.put(pid, body)
            node.persist_ingest_pipelines()
        return 200, {"acknowledged": True}

    def get_pipeline(req: RestRequest):
        pid = req.param("id")
        if pid:
            return 200, {pid: node.ingest.get(pid).body}
        return 200, node.ingest.bodies()

    def delete_pipeline(req: RestRequest):
        pid = req.param("id")
        if node.cluster is not None:
            node.cluster.delete_pipeline(pid)
        else:
            node.ingest.delete(pid)
            node.persist_ingest_pipelines()
        return 200, {"acknowledged": True}

    def simulate(req: RestRequest):
        body = req.body or {}
        docs = body.get("docs")
        if not isinstance(docs, list) or not docs:
            raise IllegalArgumentException("[_simulate] requires [docs]")
        pid = req.param("id")
        if pid:
            pipeline = node.ingest.get(pid)
        else:
            if "pipeline" not in body:
                raise IllegalArgumentException(
                    "[_simulate] requires a [pipeline] definition or an "
                    "id in the path")
            pipeline = Pipeline("_simulate_pipeline", body["pipeline"])
        out = []
        for doc in docs:
            source = (doc or {}).get("_source")
            if not isinstance(source, dict):
                raise IllegalArgumentException(
                    "[_simulate] each doc requires [_source]")
            try:
                result = pipeline.execute(source)
                if result is None:
                    out.append({"doc": None, "dropped": True})
                else:
                    out.append({"doc": {
                        "_index": (doc or {}).get("_index", "_index"),
                        "_id": (doc or {}).get("_id", "_id"),
                        "_source": result}})
            except IngestProcessorException as e:
                out.append({"error": {
                    "type": "ingest_processor_exception",
                    "reason": str(e)}})
        return 200, {"docs": out}

    controller.register("PUT", "/_ingest/pipeline/{id}", put_pipeline)
    controller.register("GET", "/_ingest/pipeline/{id}", get_pipeline)
    controller.register("GET", "/_ingest/pipeline", get_pipeline)
    controller.register("DELETE", "/_ingest/pipeline/{id}",
                        delete_pipeline)
    controller.register("POST", "/_ingest/pipeline/{id}/_simulate",
                        simulate)
    controller.register("GET", "/_ingest/pipeline/{id}/_simulate",
                        simulate)
    controller.register("POST", "/_ingest/pipeline/_simulate", simulate)
