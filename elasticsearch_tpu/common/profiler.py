"""Host/device profiling layer.

Three coordinated pieces (ISSUE 6):

1. ``HostSampler`` — a continuous low-overhead sampling profiler over
   ``sys._current_frames()``.  Each sample tags the thread with its pool
   (REST threads are tagged by the controller at admission, batcher /
   prewarm threads are recognised by name) and — when a traced request
   is live on that thread — the trace id, so the flamegraph endpoint can
   filter samples down to a single slow trace.  Samples aggregate into
   folded stacks (``pool;thread;frame;... count``) served at
   ``GET /_tpu/profile/flamegraph``.

2. A timeline ring: every sampler tick also polls a gauge source (the
   micro-batcher queue depths) into a bounded ring served at
   ``GET /_tpu/profile/timeline`` — queue depth / device occupancy over
   time, not just totals.

3. ``DeviceProfiler`` — bounded on-disk device trace sessions wrapping
   ``jax.profiler.start_trace`` / ``stop_trace`` behind
   ``POST /_tpu/profile/device/{start,stop}``.

The whole module is built around one invariant: **when no sampler is
running, request threads pay nothing**.  ``tag_thread`` et al. are a
single module-global read + early return — no allocation, no lock.
"""

from __future__ import annotations

import os
import shutil
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------
# thread tag registry (cross-thread: thread-locals are invisible to the
# sampler thread, so taggable state lives in a shared ident-keyed map)
# ---------------------------------------------------------------------

# ident -> [pool, trace_id, stage]; values mutated in place (GIL-atomic
# list item writes) so re-tagging a stage never allocates a new entry.
_TAGS: Dict[int, list] = {}
# samplers currently running in this process; emptiness is THE hot-path
# gate.  A set (not a bool) so two nodes in one test process compose.
_RUNNING: set = set()


def active() -> bool:
    return bool(_RUNNING)


def tag_thread(pool: str, trace_id: Optional[str] = None) -> None:
    """Tag the calling thread for the sampler. No-op while sampler off."""
    if not _RUNNING:
        return
    _TAGS[threading.get_ident()] = [pool, trace_id, None]


def tag_stage(stage: Optional[str]) -> None:
    """Record the calling thread's current trace stage (cheap re-tag)."""
    if not _RUNNING:
        return
    ident = threading.get_ident()
    tag = _TAGS.get(ident)
    if tag is None:
        _TAGS[ident] = [None, None, stage]
    else:
        tag[2] = stage


def untag_thread() -> None:
    if not _TAGS:
        return
    _TAGS.pop(threading.get_ident(), None)


# Pools recognised by thread-name prefix (threads we own but that never
# pass through REST admission).
_NAME_POOLS: Tuple[Tuple[str, str], ...] = (
    ("micro-batcher-pack", "tpu_batcher"),
    ("micro-batcher-complete", "tpu_completer"),
    ("tpu-prewarm", "tpu_prewarm"),
    ("MainThread", "main"),
)


def _pool_for_name(name: str) -> str:
    for prefix, pool in _NAME_POOLS:
        if name.startswith(prefix):
            return pool
    return "other"


# ---------------------------------------------------------------------
# frame walker — shared by the sampler and hot_threads
# ---------------------------------------------------------------------

def walk_frames(frame: Any, limit: int = 64) -> List[str]:
    """Leaf-first ``file.py:func`` frames via raw ``f_back`` traversal.

    Deliberately avoids ``traceback.extract_stack`` (which touches
    linecache and allocates FrameSummary objects) — this runs at
    sampling frequency against every live thread.
    """
    out: List[str] = []
    f = frame
    while f is not None and len(out) < limit:
        code = f.f_code
        fname = code.co_filename
        i = fname.rfind("/")
        out.append((fname[i + 1:] if i >= 0 else fname)
                   + ":" + code.co_name)
        f = f.f_back
    return out


class HostSampler:
    """Continuous sampling profiler over ``sys._current_frames()``.

    Keeps individual samples (not pre-folded counts) in a bounded deque
    so the flamegraph endpoint can slice by retention window and by
    trace id after the fact.
    """

    MAX_SAMPLES = 200_000
    TIMELINE_POINTS = 4096

    def __init__(self, hz: float = 20.0, retention_s: float = 300.0,
                 max_depth: int = 64, role: str = "batcher"):
        # which process this sampler runs in ("batcher" or "front-N");
        # folded lines stay role-free — the flamegraph merge prefixes
        # roles only when serving fronts exist, so single-process
        # output is byte-stable
        self.role = role
        self.hz = max(0.5, min(250.0, float(hz)))
        self.retention_s = max(1.0, float(retention_s))
        self.max_depth = max_depth
        # sample := (ts, pool, thread_name, stage, stack_tuple, trace_id)
        self._samples: deque = deque(maxlen=self.MAX_SAMPLES)
        self._timeline: deque = deque(maxlen=self.TIMELINE_POINTS)
        self.timeline_source: Optional[Callable[[], Dict[str, float]]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_total = 0
        self.ticks_total = 0
        self._busy_s = 0.0
        self._started_at = 0.0
        self._names: Dict[int, str] = {}

    # -- lifecycle ----------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._started_at = time.perf_counter()
        self._busy_s = 0.0
        self._thread = threading.Thread(
            target=self._loop, name="host-profiler", daemon=True)
        _RUNNING.add(id(self))
        self._thread.start()

    def stop(self) -> None:
        _RUNNING.discard(id(self))
        if not _RUNNING:
            _TAGS.clear()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling loop ------------------------------------------------

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            t0 = time.perf_counter()
            try:
                self._tick(me)
            except Exception:  # never kill the sampler on a bad tick
                pass
            self._busy_s += time.perf_counter() - t0

    def _tick(self, me: int) -> None:
        now = time.time()
        frames = sys._current_frames()
        names = self._names
        refresh = any(ident not in names for ident in frames)
        if refresh:
            self._names = names = {
                t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}
        self.ticks_total += 1
        append = self._samples.append
        for ident, frame in frames.items():
            if ident == me:
                continue
            stack = tuple(reversed(walk_frames(frame, self.max_depth)))
            tag = _TAGS.get(ident)
            name = names.get(ident, "?")
            if tag is not None and tag[0]:
                pool, trace_id, stage = tag[0], tag[1], tag[2]
            else:
                pool = _pool_for_name(name)
                trace_id = tag[1] if tag else None
                stage = tag[2] if tag else None
            append((now, pool, name, stage, stack, trace_id))
            self.samples_total += 1
        src = self.timeline_source
        if src is not None:
            try:
                gauges = src()
                if gauges:
                    self._timeline.append((now, gauges))
            except Exception:
                pass
        self._expire(now)

    def _expire(self, now: float) -> None:
        cutoff = now - self.retention_s
        samples = self._samples
        while samples and samples[0][0] < cutoff:
            samples.popleft()
        timeline = self._timeline
        while timeline and timeline[0][0] < cutoff:
            timeline.popleft()

    # -- views --------------------------------------------------------

    def folded(self, trace_id: Optional[str] = None,
               top: Optional[int] = None,
               pool: Optional[str] = None) -> List[Tuple[str, int]]:
        """Aggregated folded stacks, hottest first.

        Line format: ``pool;thread[;stage];frame;...;leaf_frame``.
        """
        counts: Dict[str, int] = {}
        for ts, p, name, stage, stack, tid in list(self._samples):
            if trace_id is not None and tid != trace_id:
                continue
            if pool is not None and p != pool:
                continue
            head = p + ";" + name + ((";" + stage) if stage else "")
            key = head + ";" + ";".join(stack) if stack else head
            counts[key] = counts.get(key, 0) + 1
        ranked = sorted(counts.items(), key=lambda kv: -kv[1])
        return ranked[:top] if top else ranked

    def folded_text(self, **kw: Any) -> str:
        return "".join(f"{line} {count}\n"
                       for line, count in self.folded(**kw))

    def timeline(self, limit: int = 0) -> List[Dict[str, Any]]:
        points = list(self._timeline)
        if limit:
            points = points[-limit:]
        return [dict(gauges, t=ts) for ts, gauges in points]

    def overhead_fraction(self) -> float:
        wall = time.perf_counter() - self._started_at
        if wall <= 0.0 or not self._started_at:
            return 0.0
        return self._busy_s / wall

    def stats(self) -> Dict[str, Any]:
        return {
            "running": self.running,
            "role": self.role,
            "hz": self.hz,
            "retention_s": self.retention_s,
            "samples_total": self.samples_total,
            "ticks_total": self.ticks_total,
            "retained_samples": len(self._samples),
            "timeline_points": len(self._timeline),
            "overhead_fraction": round(self.overhead_fraction(), 6),
        }


# ---------------------------------------------------------------------
# device profiling sessions
# ---------------------------------------------------------------------

class DeviceProfiler:
    """Bounded on-disk device trace sessions around jax.profiler.

    At most ``max_sessions`` session directories are kept under
    ``base_dir``; starting a new one evicts the oldest.  Failures to
    import or start the backend profiler are reported, not raised —
    the serving path never depends on profiler availability.
    """

    def __init__(self, base_dir: str, max_sessions: int = 4):
        self.base_dir = base_dir
        self.max_sessions = max(1, int(max_sessions))
        self._lock = threading.Lock()
        self._active_dir: Optional[str] = None
        self._started_at = 0.0
        self.sessions_total = 0
        self.last_error: Optional[str] = None

    def start(self, name: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            if self._active_dir is not None:
                return {"started": False, "error": "session already running",
                        "dir": self._active_dir}
            session = name or f"session-{self.sessions_total:04d}-{int(time.time())}"
            session = session.replace("/", "_").replace("..", "_")
            target = os.path.join(self.base_dir, session)
            try:
                os.makedirs(target, exist_ok=True)
                self._evict_beyond(keep=self.max_sessions - 1,
                                   protect=target)
                import jax
                jax.profiler.start_trace(target)
            except Exception as exc:
                self.last_error = f"{type(exc).__name__}: {exc}"
                return {"started": False, "error": self.last_error}
            self._active_dir = target
            self._started_at = time.perf_counter()
            self.sessions_total += 1
            return {"started": True, "dir": target}

    def stop(self) -> Dict[str, Any]:
        with self._lock:
            if self._active_dir is None:
                return {"stopped": False, "error": "no session running"}
            target, dt = self._active_dir, \
                time.perf_counter() - self._started_at
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as exc:
                self.last_error = f"{type(exc).__name__}: {exc}"
                self._active_dir = None
                return {"stopped": False, "error": self.last_error,
                        "dir": target}
            self._active_dir = None
            return {"stopped": True, "dir": target,
                    "seconds": round(dt, 3)}

    def _evict_beyond(self, keep: int, protect: str) -> None:
        try:
            entries = [os.path.join(self.base_dir, e)
                       for e in os.listdir(self.base_dir)]
            dirs = sorted((d for d in entries
                           if os.path.isdir(d) and d != protect),
                          key=os.path.getmtime)
            for stale in dirs[:max(0, len(dirs) - keep)]:
                shutil.rmtree(stale, ignore_errors=True)
        except OSError:
            pass

    def info(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "active": self._active_dir is not None,
            "base_dir": self.base_dir,
            "max_sessions": self.max_sessions,
            "sessions_total": self.sessions_total,
        }
        if self._active_dir is not None:
            out["dir"] = self._active_dir
            out["seconds"] = round(
                time.perf_counter() - self._started_at, 3)
        if self.last_error:
            out["last_error"] = self.last_error
        return out


# ---------------------------------------------------------------------
# node-facing facade
# ---------------------------------------------------------------------

class Profiler:
    """Per-node facade: the host sampler + device session manager.

    Constructed unconditionally (so endpoints and metrics stay shaped
    the same) but ``start()`` only spawns the sampler thread when
    ``search.profiler.enabled`` is on.
    """

    def __init__(self, *, enabled: bool = False, hz: float = 20.0,
                 retention_s: float = 300.0,
                 device_dir: str = "profile_sessions"):
        self.enabled = bool(enabled)
        self.sampler = HostSampler(hz=hz, retention_s=retention_s)
        self.device = DeviceProfiler(device_dir)

    def start(self) -> None:
        if self.enabled:
            self.sampler.start()

    def close(self) -> None:
        self.sampler.stop()

    def info(self) -> Dict[str, Any]:
        return {"enabled": self.enabled,
                "sampler": self.sampler.stats(),
                "device": self.device.info()}
