"""Distributed tracing — spans, context propagation, a bounded ring of
finished spans per node.

Reference analog: the `tracing/` Task/APM layer (SURVEY.md §2.1#47-ish):
the REST layer opens (or adopts, via a W3C `traceparent`-style header) a
root span per request; the coordinator attaches the trace context to
every transport fan-out payload; shard-side handlers continue the span;
the TPU serving pipeline reports its stage boundaries as child spans.

Design constraints:

  * **Zero overhead when disabled.** `search.tracing.sample_rate = 0`
    (the default) must add nothing measurable to the hostpath: every
    instrumentation helper's disabled path is one thread-local read plus
    a None check, allocating nothing.
  * **Bounded memory.** Finished spans land in a deque ring
    (`search.tracing.max_spans`); old traces fall off the end.
  * **Head sampling.** The root makes the sampling decision; the
    decision travels in the `traceparent` flags byte, so a fan-out child
    never re-rolls the dice (one trace is complete or absent, never
    partial by chance).

Slow traces: a root span finishing above
`search.tracing.slow_threshold_ms` is emitted through the slowlog
channel (`elasticsearch_tpu.trace.slowlog`) with its per-stage
breakdown, same spirit as the per-shard search slowlog.
"""

from __future__ import annotations

import contextlib
import logging
import random
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

slowlog = logging.getLogger("elasticsearch_tpu.trace.slowlog")

#: wire context: (trace_id, parent span_id, sampled)
WireContext = Tuple[str, str, bool]

_tls = threading.local()


# ---------------------------------------------------------------------------
# traceparent encoding (W3C trace-context shaped: 00-<trace>-<span>-<flags>)
# ---------------------------------------------------------------------------

def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(header: Optional[str]) -> Optional[WireContext]:
    """→ (trace_id, span_id, sampled), or None for anything malformed
    (a bad header must never fail the request it rode in on)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id, flags == "01"


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class Span:
    """One timed operation. Mutated only by the thread that runs the
    operation; `end()` hands the finished record to the tracer ring."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "_start_pc", "duration_ms", "attributes",
                 "events", "root", "_ended")

    is_recording = True

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 attributes: Optional[Dict[str, Any]] = None,
                 root: bool = False,
                 start: Optional[float] = None,
                 duration_s: Optional[float] = None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = time.time() if start is None else start
        self._start_pc = time.perf_counter()
        self.duration_ms: Optional[float] = (
            None if duration_s is None else duration_s * 1000.0)
        self.attributes: Dict[str, Any] = dict(attributes) if attributes \
            else {}
        self.events: List[Dict[str, Any]] = []
        self.root = root
        self._ended = False

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        self.events.append({"name": name, "time": time.time(),
                            **attributes})

    def context(self) -> WireContext:
        return self.trace_id, self.span_id, True

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, True)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        if self.duration_ms is None:
            self.duration_ms = (time.perf_counter()
                                - self._start_pc) * 1000.0
        self.tracer._finish(self)

    # context-manager form: exceptions annotate the span, then reraise
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.set_attribute("error", f"{type(exc).__name__}: {exc}")
        self.end()
        return False

    def to_dict(self) -> Dict[str, Any]:
        out = {"trace_id": self.trace_id, "span_id": self.span_id,
               "parent_id": self.parent_id, "name": self.name,
               "start": self.start,
               "duration_ms": round(self.duration_ms or 0.0, 3),
               "node": self.tracer.node_name}
        if self.attributes:
            out["attributes"] = dict(self.attributes)
        if self.events:
            out["events"] = list(self.events)
        return out


class _NoopSpan:
    """Shared do-nothing span: the disabled/unsampled path. All mutators
    are no-ops and `is_recording` is False so callers can skip work."""

    __slots__ = ()
    is_recording = False
    trace_id = span_id = parent_id = name = ""
    attributes: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attributes: Any) -> None:
        pass

    def context(self) -> None:
        return None

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

class Tracer:
    """Per-node span factory + bounded ring of finished spans."""

    def __init__(self, sample_rate: float = 0.0, max_spans: int = 4096,
                 slow_threshold_ms: Optional[float] = None,
                 node_name: str = "",
                 rng: Optional[random.Random] = None):
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self.slow_threshold_ms = slow_threshold_ms
        self.node_name = node_name
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max(1, int(max_spans)))

    @property
    def enabled(self) -> bool:
        return self.sample_rate > 0.0

    def start_span(self, name: str,
                   parent: Any = None,
                   attributes: Optional[Dict[str, Any]] = None,
                   root: bool = False,
                   start: Optional[float] = None,
                   duration_s: Optional[float] = None):
        """`parent`: a live Span (local child), a WireContext tuple
        (continuation of a remote span — the remote sampling decision
        wins, even over a local sample_rate of 0), or None (a new root,
        subject to this tracer's sample_rate)."""
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, _NoopSpan):
            return NOOP_SPAN
        elif isinstance(parent, tuple):
            trace_id, parent_id, sampled = parent
            if not sampled:
                return NOOP_SPAN
        elif parent is None:
            if self.sample_rate <= 0.0 or (
                    self.sample_rate < 1.0
                    and self._rng.random() >= self.sample_rate):
                return NOOP_SPAN
            trace_id, parent_id = uuid.uuid4().hex, None
        else:
            return NOOP_SPAN
        return Span(self, trace_id, uuid.uuid4().hex[:16], parent_id,
                    name, attributes, root=root, start=start,
                    duration_s=duration_s)

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        if (span.root and self.slow_threshold_ms is not None
                and span.duration_ms is not None
                and span.duration_ms >= self.slow_threshold_ms
                and slowlog.isEnabledFor(logging.WARNING)):
            self._emit_slow(span)

    def _emit_slow(self, span: Span) -> None:
        children = sorted(
            (s for s in self.spans(trace_id=span.trace_id, limit=0)
             if s["span_id"] != span.span_id),
            key=lambda s: -s["duration_ms"])[:8]
        breakdown = ", ".join(f"{s['name']}={s['duration_ms']:.1f}ms"
                              for s in children) or "no child spans"
        tenant = span.attributes.get("tenant")
        if tenant:
            breakdown = f"tenant=[{tenant}] {breakdown}"
        slowlog.warning(
            "slow trace [%s] [%s] took %.1fms (threshold %.0fms): %s",
            span.trace_id, span.name, span.duration_ms,
            self.slow_threshold_ms, breakdown)

    def spans(self, trace_id: Optional[str] = None,
              min_duration_ms: float = 0.0,
              limit: int = 200,
              tenant: Optional[str] = None) -> List[Dict[str, Any]]:
        """Finished spans, NEWEST first. limit=0 → no cap. A tenant
        filter matches the `tenant` attribute root spans are stamped
        with (per-tenant slow-query forensics)."""
        with self._lock:
            snap = list(self._spans)
        out = []
        for span in reversed(snap):
            if trace_id is not None and span.trace_id != trace_id:
                continue
            if min_duration_ms and (span.duration_ms or 0.0) \
                    < min_duration_ms:
                continue
            if tenant is not None and \
                    span.attributes.get("tenant") != tenant:
                continue
            out.append(span.to_dict())
            if limit and len(out) >= limit:
                break
        return out

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """Every retained span of one trace, in start order."""
        got = self.spans(trace_id=trace_id, limit=0)
        got.sort(key=lambda s: s["start"])
        return got

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


# ---------------------------------------------------------------------------
# thread-local current span + instrumentation helpers
#
# The helpers below are the only API instrumented code needs: they read
# the CURRENT span from a thread-local, so deep call stacks (coordinator
# → planner → kernel service) need no tracer plumbing, and a node's
# handler threads never mix spans across concurrent requests. Every
# disabled-path costs one getattr + None check.
# ---------------------------------------------------------------------------

def current_span() -> Optional[Span]:
    """The thread's current RECORDING span, or None."""
    span = getattr(_tls, "span", None)
    if span is None or not span.is_recording:
        return None
    return span


@contextlib.contextmanager
def use_span(span) -> Iterator[Any]:
    """Make `span` current for the block. Does NOT end the span — the
    owner ends it (lets a span outlive the block that populated it)."""
    prev = getattr(_tls, "span", None)
    _tls.span = span
    try:
        yield span
    finally:
        _tls.span = prev


class _NoopCtx:
    __slots__ = ()

    def __enter__(self):
        return NOOP_SPAN

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_CTX = _NoopCtx()


class _ChildCtx:
    """Starts a child of `parent`, makes it current, ends it on exit."""

    __slots__ = ("span", "_prev")

    def __init__(self, span: Span):
        self.span = span

    def __enter__(self) -> Span:
        self._prev = getattr(_tls, "span", None)
        _tls.span = self.span
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tls.span = self._prev
        if exc is not None:
            self.span.set_attribute("error",
                                    f"{type(exc).__name__}: {exc}")
        self.span.end()
        return False


def child_span(name: str, **attributes: Any):
    """Context manager: a child span of the thread's current span
    (no-op — shared singleton, zero allocation — when not tracing)."""
    cur = getattr(_tls, "span", None)
    if cur is None or not cur.is_recording:
        return _NOOP_CTX
    return _ChildCtx(cur.tracer.start_span(
        name, parent=cur, attributes=attributes or None))


def span_under(parent: Optional[Span], name: str, **attributes: Any):
    """Like `child_span` but under an EXPLICIT parent — for work that
    hops threads (micro-batcher workers) where the thread-local of the
    submitting request is unavailable."""
    if parent is None or not parent.is_recording:
        return _NOOP_CTX
    return _ChildCtx(parent.tracer.start_span(
        name, parent=parent, attributes=attributes or None))


def record_stage(name: str, seconds: float, n: int = 1,
                 **attributes: Any) -> None:
    """Record an ALREADY-MEASURED duration as a completed child span of
    the current span (start back-dated by the duration). This is how
    stage timers (StageTimes) reconcile with traces: the span duration
    is the same dt the stats ring recorded."""
    cur = getattr(_tls, "span", None)
    if cur is None or not cur.is_recording:
        return
    if n > 1:
        attributes = dict(attributes or {})
        attributes["count"] = n
    span = cur.tracer.start_span(
        name, parent=cur, attributes=attributes or None,
        start=time.time() - seconds, duration_s=seconds)
    span.end()


def add_event(name: str, **attributes: Any) -> None:
    """Attach an event to the current span (no-op when not tracing)."""
    cur = getattr(_tls, "span", None)
    if cur is None or not cur.is_recording:
        return
    cur.add_event(name, **attributes)


def inject_context(payload: Dict[str, Any],
                   span: Optional[Span] = None) -> Dict[str, Any]:
    """Attach the trace context to a transport payload (in place) so the
    remote handler can continue the trace. No-op when not tracing."""
    if span is None:
        span = getattr(_tls, "span", None)
    if span is not None and span.is_recording:
        payload["_trace"] = span.traceparent()
    return payload


def extract_context(payload: Optional[Dict[str, Any]]
                    ) -> Optional[WireContext]:
    """Wire context out of a transport payload, or None."""
    if not payload:
        return None
    return parse_traceparent(payload.get("_trace"))
