"""Stats counters used by every subsystem.

Reference: common/metrics/CounterMetric and MeanMetric, surfaced through the
node/indices stats trees (SURVEY.md §2.1#47, §5.5). Each subsystem owns a
small bag of these and renders them into the stats API response.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict


class CounterMetric:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    @property
    def count(self) -> int:
        return self._value


class LabeledCounters:
    """A family of CounterMetric keyed by a label-value tuple.

    For counter families whose member set is data-driven — e.g. the
    kernel-variant launch counters keyed (kernel, variant) — one object
    owns every member so a single metrics-registry collector can yield
    the whole family. Members are created on first inc and never
    dropped (Prometheus counters must not disappear between scrapes)."""

    __slots__ = ("_label_names", "_members", "_lock")

    def __init__(self, *label_names: str):
        self._label_names = tuple(label_names)
        self._members: Dict[tuple, CounterMetric] = {}
        self._lock = threading.Lock()

    def child(self, *label_values) -> CounterMetric:
        if len(label_values) != len(self._label_names):
            raise ValueError(
                f"expected {len(self._label_names)} label values "
                f"({self._label_names}), got {label_values!r}")
        key = tuple(str(v) for v in label_values)
        member = self._members.get(key)
        if member is None:
            with self._lock:
                member = self._members.setdefault(key, CounterMetric())
        return member

    def inc(self, *label_values, n: int = 1) -> None:
        self.child(*label_values).inc(n)

    def items(self):
        """→ [(labels_dict, CounterMetric), ...] snapshot for collectors."""
        with self._lock:
            snap = list(self._members.items())
        return [(dict(zip(self._label_names, key)), metric)
                for key, metric in snap]

    def counts(self) -> Dict[str, int]:
        """Plain JSON view: "v1,v2" -> count (stats API rendering)."""
        with self._lock:
            snap = list(self._members.items())
        return {",".join(key): m.count for key, m in snap}


class MeanMetric:
    """Tracks a running (count, sum) pair — e.g. query count + total time."""

    __slots__ = ("_count", "_sum", "_lock")

    def __init__(self):
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def inc(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class EWMA:
    """Exponentially-weighted moving average.

    Reference: the adaptive-replica-selection rank in
    node/ResponseCollectorService keeps EWMAs of service time and queue size
    per node (SURVEY.md §2.3 P2)."""

    __slots__ = ("alpha", "_value")

    def __init__(self, alpha: float = 0.3, initial: float = 0.0):
        self.alpha = alpha
        self._value = initial

    def add(self, sample: float) -> None:
        self._value = self.alpha * sample + (1 - self.alpha) * self._value

    @property
    def value(self) -> float:
        return self._value


class SampleRing:
    """Bounded ring of recent float samples; cheap percentile snapshots.

    Per-stage latency distributions for the serving path: totals alone are
    misleading for queue-style stages (summing per-query waits across a
    batch over-counts wall time), so stats report recent-percentile views
    alongside the running totals."""

    __slots__ = ("_buf", "_size", "_next", "_count", "_lock",
                 "_added", "_ex_id", "_ex_value", "_ex_at")

    def __init__(self, size: int = 512):
        self._buf = [0.0] * size
        self._size = size
        self._next = 0
        self._count = 0
        self._lock = threading.Lock()
        # exemplar: trace_id of the slowest sample still inside the
        # retained window — the metrics→trace pivot for /_tpu/stats
        self._added = 0
        self._ex_id = None
        self._ex_value = 0.0
        self._ex_at = 0

    def add(self, sample: float, exemplar: str = None) -> None:
        with self._lock:
            self._buf[self._next] = sample
            self._next = (self._next + 1) % self._size
            if self._count < self._size:
                self._count += 1
            self._added += 1
            if exemplar is not None and (
                    self._ex_id is None
                    or sample >= self._ex_value
                    or self._added - self._ex_at > self._size):
                self._ex_id = exemplar
                self._ex_value = sample
                self._ex_at = self._added

    @property
    def exemplar_trace_id(self):
        """trace_id of the slowest recent traced sample (None when no
        traced sample landed inside the retained window)."""
        with self._lock:
            if (self._ex_id is not None
                    and self._added - self._ex_at > self._size):
                return None  # aged out of the ring
            return self._ex_id

    def samples(self) -> list:
        with self._lock:
            return list(self._buf[: self._count])

    def percentiles(self, pcts=(50.0, 95.0, 99.0)) -> Dict[float, float]:
        """Nearest-rank percentiles over the retained window ({} if empty)."""
        snap = self.samples()
        if not snap:
            return {}
        snap.sort()
        n = len(snap)
        out: Dict[float, float] = {}
        for p in pcts:
            rank = max(0, min(n - 1, math.ceil(p / 100.0 * n) - 1))
            out[p] = snap[rank]
        return out


def percentiles(samples, pcts=(50.0, 95.0, 99.0)) -> Dict[float, float]:
    """Nearest-rank percentiles of an arbitrary sample list ({} if empty)."""
    snap = sorted(samples)
    if not snap:
        return {}
    n = len(snap)
    out: Dict[float, float] = {}
    for p in pcts:
        rank = max(0, min(n - 1, math.ceil(p / 100.0 * n) - 1))
        out[p] = snap[rank]
    return out


class StopWatch:
    __slots__ = ("_start",)

    def __init__(self):
        self._start = time.monotonic()

    def elapsed_seconds(self) -> float:
        return time.monotonic() - self._start

    def elapsed_millis(self) -> float:
        return self.elapsed_seconds() * 1000.0


def stats_to_xcontent(stats: Dict[str, Any]) -> Dict[str, Any]:
    """Render a dict possibly containing metric objects into plain JSON.
    Handles CounterMetric/MeanMetric/EWMA/SampleRing/LabeledCounters and
    recurses into dicts — e.g. the `indexing_pressure` stats block nests
    per-stage counters two levels deep."""
    out: Dict[str, Any] = {}
    for k, v in stats.items():
        if isinstance(v, CounterMetric):
            out[k] = v.count
        elif isinstance(v, LabeledCounters):
            out[k] = v.counts()
        elif isinstance(v, MeanMetric):
            out[k] = {"count": v.count, "total_millis": v.sum, "mean_millis": v.mean}
        elif isinstance(v, EWMA):
            out[k] = v.value
        elif isinstance(v, SampleRing):
            out[k] = {f"p{p:g}": val for p, val in v.percentiles().items()}
            exemplar = v.exemplar_trace_id
            if exemplar is not None:
                out[k]["exemplar_trace_id"] = exemplar
        elif isinstance(v, dict):
            out[k] = stats_to_xcontent(v)
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# unified metrics registry + Prometheus text exposition
# ---------------------------------------------------------------------------

#: quantiles exported for summary-typed families (SampleRing)
SUMMARY_QUANTILES = (50.0, 95.0, 99.0)

_VALID_KINDS = ("counter", "gauge", "summary")


def _infer_kind(metric: Any) -> str:
    if isinstance(metric, CounterMetric):
        return "counter"
    if isinstance(metric, (MeanMetric, SampleRing)):
        return "summary"
    return "gauge"  # EWMA, callables, raw numbers


def _escape_label(value: Any) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """One node-wide catalog of every metric, scraped as Prometheus text.

    Two registration styles:

      * ``register(name, metric, labels=..., help=...)`` — a static entry
        for a metric object that lives as long as the node.
      * ``add_collector(fn)`` — for dynamic families (per-stage rings,
        per-shard failure counters, pools created later). ``fn`` is
        called at scrape time and yields
        ``(dotted_name, labels_dict, metric_or_value)`` or
        ``(dotted_name, labels_dict, value, kind)`` tuples.

    Dotted names become Prometheus families under the ``es_tpu``
    namespace: ``search.plan_cache.hits`` → ``es_tpu_search_plan_cache_
    hits_total`` (counters get the ``_total`` suffix). CounterMetric →
    counter; EWMA/callable/raw number → gauge; MeanMetric → summary
    (_count/_sum); SampleRing → summary with 50/95/99 quantiles.
    """

    def __init__(self, namespace: str = "es_tpu"):
        self.namespace = namespace
        self._lock = threading.Lock()
        #: name -> list of (labels, metric, kind, help)
        self._static: Dict[str, list] = {}
        self._help: Dict[str, str] = {}
        self._collectors: list = []

    # -- registration -----------------------------------------------------

    def register(self, name: str, metric: Any, *,
                 labels: Dict[str, Any] = None,
                 kind: str = None, help: str = "") -> Any:
        kind = kind or _infer_kind(metric)
        if kind not in _VALID_KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        with self._lock:
            prior = self._static.get(name)
            if prior and prior[0][2] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{prior[0][2]}, cannot re-register as {kind}")
            self._static.setdefault(name, []).append(
                (dict(labels or {}), metric, kind))
            if help and name not in self._help:
                self._help[name] = help
        return metric

    def set_help(self, name: str, help: str) -> None:
        with self._lock:
            self._help.setdefault(name, help)

    def add_collector(self, fn) -> None:
        """fn() yields (name, labels, metric_or_value[, kind]) tuples at
        scrape time — for families whose member set changes at runtime."""
        with self._lock:
            self._collectors.append(fn)

    # -- scraping ---------------------------------------------------------

    def _samples(self):
        """→ list of (name, labels, metric_or_value, kind)."""
        with self._lock:
            static = [(n, lb, m, k)
                      for n, entries in self._static.items()
                      for (lb, m, k) in entries]
            collectors = list(self._collectors)
        out = list(static)
        for fn in collectors:
            try:
                rows = list(fn())
            except Exception:
                continue  # a broken subsystem must not break the scrape
            for row in rows:
                if len(row) == 4:
                    name, labels, metric, kind = row
                else:
                    name, labels, metric = row
                    kind = _infer_kind(metric)
                out.append((name, dict(labels or {}), metric, kind))
        return out

    def registered_objects(self) -> set:
        """ids of every *metric object* (not raw values) the registry can
        see — static and collector-yielded. Used by the completeness test
        to catch subsystems that expose metrics without registering."""
        ids = set()
        for _name, _labels, metric, _kind in self._samples():
            if isinstance(metric, (CounterMetric, MeanMetric, EWMA,
                                   SampleRing)):
                ids.add(id(metric))
        return ids

    def families(self) -> Dict[str, str]:
        """dotted name -> kind, for every currently-visible family."""
        fams: Dict[str, str] = {}
        for name, _labels, _metric, kind in self._samples():
            prior = fams.setdefault(name, kind)
            if prior != kind:
                raise ValueError(
                    f"metric {name!r} exposed as both {prior} and {kind}")
        return fams

    def _family_name(self, dotted: str, kind: str) -> str:
        base = f"{self.namespace}_" + dotted.replace(".", "_")
        if kind == "counter" and not base.endswith("_total"):
            base += "_total"
        return base

    @staticmethod
    def _value_of(metric: Any) -> float:
        if isinstance(metric, CounterMetric):
            return metric.count
        if isinstance(metric, EWMA):
            return metric.value
        if callable(metric):
            return float(metric())
        return float(metric)

    def export_snapshot(self) -> list:
        """Resolve every sample into plain JSON-serializable rows
        ``[name, labels, value, kind]`` for cross-process shipping (the
        serving fronts publish these through their stats block; the
        batcher re-emits them as collector rows with a ``process``
        label). Composite metrics flatten: MeanMetric → ``.count`` /
        ``.sum`` counters, SampleRing → per-quantile gauges + a
        ``.count`` counter."""
        rows = []
        for name, labels, metric, kind in self._samples():
            if isinstance(metric, SampleRing):
                snap = metric.samples()
                for p, val in percentiles(snap, SUMMARY_QUANTILES).items():
                    rows.append([f"{name}.p{p:g}", labels, val, "gauge"])
                rows.append([f"{name}.count", labels, len(snap),
                             "counter"])
            elif isinstance(metric, MeanMetric):
                rows.append([f"{name}.count", labels, metric.count,
                             "counter"])
                rows.append([f"{name}.sum", labels, metric.sum,
                             "counter"])
            else:
                try:
                    rows.append([name, labels, self._value_of(metric),
                                 kind])
                except (TypeError, ValueError):
                    continue
        return rows

    def prometheus_text(self) -> str:
        """Standard text exposition: one # HELP / # TYPE per family, then
        its samples; families sorted by name for stable scrapes."""
        groups: Dict[str, list] = {}
        kinds: Dict[str, str] = {}
        helps: Dict[str, str] = {}
        with self._lock:
            help_snapshot = dict(self._help)
        for name, labels, metric, kind in self._samples():
            fam = self._family_name(name, kind)
            if kinds.setdefault(fam, kind) != kind:
                raise ValueError(
                    f"metric family {fam!r} exposed as both "
                    f"{kinds[fam]} and {kind}")
            helps.setdefault(fam, help_snapshot.get(name, name))
            groups.setdefault(fam, []).append((labels, metric, kind))
        lines = []
        for fam in sorted(groups):
            kind = kinds[fam]
            lines.append(f"# HELP {fam} {helps[fam]}")
            lines.append(f"# TYPE {fam} {kind}")
            for labels, metric, _k in groups[fam]:
                if kind == "summary" and isinstance(metric, SampleRing):
                    pcts = metric.percentiles(SUMMARY_QUANTILES)
                    snap = metric.samples()
                    for q in SUMMARY_QUANTILES:
                        ql = dict(labels)
                        ql["quantile"] = f"{q / 100.0:g}"
                        lines.append(
                            f"{fam}{_fmt_labels(ql)} "
                            f"{_fmt_value(pcts.get(q, float('nan')))}")
                    lines.append(f"{fam}_count{_fmt_labels(labels)} "
                                 f"{len(snap)}")
                    lines.append(f"{fam}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(sum(snap))}")
                elif kind == "summary" and isinstance(metric, MeanMetric):
                    lines.append(f"{fam}_count{_fmt_labels(labels)} "
                                 f"{metric.count}")
                    lines.append(f"{fam}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(metric.sum)}")
                else:
                    lines.append(f"{fam}{_fmt_labels(labels)} "
                                 f"{_fmt_value(self._value_of(metric))}")
        return "\n".join(lines) + ("\n" if lines else "")
