"""Stats counters used by every subsystem.

Reference: common/metrics/CounterMetric and MeanMetric, surfaced through the
node/indices stats trees (SURVEY.md §2.1#47, §5.5). Each subsystem owns a
small bag of these and renders them into the stats API response.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict


class CounterMetric:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    @property
    def count(self) -> int:
        return self._value


class MeanMetric:
    """Tracks a running (count, sum) pair — e.g. query count + total time."""

    __slots__ = ("_count", "_sum", "_lock")

    def __init__(self):
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def inc(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class EWMA:
    """Exponentially-weighted moving average.

    Reference: the adaptive-replica-selection rank in
    node/ResponseCollectorService keeps EWMAs of service time and queue size
    per node (SURVEY.md §2.3 P2)."""

    __slots__ = ("alpha", "_value")

    def __init__(self, alpha: float = 0.3, initial: float = 0.0):
        self.alpha = alpha
        self._value = initial

    def add(self, sample: float) -> None:
        self._value = self.alpha * sample + (1 - self.alpha) * self._value

    @property
    def value(self) -> float:
        return self._value


class SampleRing:
    """Bounded ring of recent float samples; cheap percentile snapshots.

    Per-stage latency distributions for the serving path: totals alone are
    misleading for queue-style stages (summing per-query waits across a
    batch over-counts wall time), so stats report recent-percentile views
    alongside the running totals."""

    __slots__ = ("_buf", "_size", "_next", "_count", "_lock")

    def __init__(self, size: int = 512):
        self._buf = [0.0] * size
        self._size = size
        self._next = 0
        self._count = 0
        self._lock = threading.Lock()

    def add(self, sample: float) -> None:
        with self._lock:
            self._buf[self._next] = sample
            self._next = (self._next + 1) % self._size
            if self._count < self._size:
                self._count += 1

    def samples(self) -> list:
        with self._lock:
            return list(self._buf[: self._count])

    def percentiles(self, pcts=(50.0, 95.0, 99.0)) -> Dict[float, float]:
        """Nearest-rank percentiles over the retained window ({} if empty)."""
        snap = self.samples()
        if not snap:
            return {}
        snap.sort()
        n = len(snap)
        out: Dict[float, float] = {}
        for p in pcts:
            rank = max(0, min(n - 1, math.ceil(p / 100.0 * n) - 1))
            out[p] = snap[rank]
        return out


def percentiles(samples, pcts=(50.0, 95.0, 99.0)) -> Dict[float, float]:
    """Nearest-rank percentiles of an arbitrary sample list ({} if empty)."""
    snap = sorted(samples)
    if not snap:
        return {}
    n = len(snap)
    out: Dict[float, float] = {}
    for p in pcts:
        rank = max(0, min(n - 1, math.ceil(p / 100.0 * n) - 1))
        out[p] = snap[rank]
    return out


class StopWatch:
    __slots__ = ("_start",)

    def __init__(self):
        self._start = time.monotonic()

    def elapsed_seconds(self) -> float:
        return time.monotonic() - self._start

    def elapsed_millis(self) -> float:
        return self.elapsed_seconds() * 1000.0


def stats_to_xcontent(stats: Dict[str, Any]) -> Dict[str, Any]:
    """Render a dict possibly containing metric objects into plain JSON."""
    out: Dict[str, Any] = {}
    for k, v in stats.items():
        if isinstance(v, CounterMetric):
            out[k] = v.count
        elif isinstance(v, MeanMetric):
            out[k] = {"count": v.count, "total_millis": v.sum, "mean_millis": v.mean}
        elif isinstance(v, EWMA):
            out[k] = v.value
        elif isinstance(v, dict):
            out[k] = stats_to_xcontent(v)
        else:
            out[k] = v
    return out
