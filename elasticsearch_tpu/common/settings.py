"""Typed, validated, scoped, dynamically-updatable settings.

Reference: common/settings/ — Settings (immutable flat key→value map),
Setting<T> (typed accessor with default/parser/validator/properties),
ClusterSettings#applySettings (dynamic update dispatch to registered
consumers), IndexScopedSettings (SURVEY.md §2.1#4, §5.6).

Precedence (reference: §5.6): transient > persistent > config file > default.
Unknown registered-scope settings fail validation, as upstream fails node
start on unknown settings.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, Generic, Iterable, List, Optional, TypeVar

from elasticsearch_tpu.common.errors import SettingsException
from elasticsearch_tpu.common.units import ByteSizeValue, TimeValue

T = TypeVar("T")


class Property(enum.Flag):
    NODE_SCOPE = enum.auto()
    INDEX_SCOPE = enum.auto()
    DYNAMIC = enum.auto()
    FINAL = enum.auto()
    DEPRECATED = enum.auto()
    FILTERED = enum.auto()  # redacted from API output


class Setting(Generic[T]):
    def __init__(
        self,
        key: str,
        default: Any,
        parser: Callable[[Any], T],
        properties: Property = Property.NODE_SCOPE,
        validator: Optional[Callable[[T], None]] = None,
    ):
        self.key = key
        self._default = default
        self._parser = parser
        self.properties = properties
        self._validator = validator

    # -- constructors mirroring the reference's Setting.intSetting etc. -----

    @staticmethod
    def bool_setting(key: str, default: bool, properties=Property.NODE_SCOPE) -> "Setting[bool]":
        def parse(v):
            if isinstance(v, bool):
                return v
            s = str(v).lower()
            if s in ("true", "1"):
                return True
            if s in ("false", "0"):
                return False
            raise SettingsException(f"cannot parse boolean [{v}] for setting [{key}]")

        return Setting(key, default, parse, properties)

    @staticmethod
    def int_setting(
        key: str, default: int, min_value: Optional[int] = None,
        max_value: Optional[int] = None, properties=Property.NODE_SCOPE,
    ) -> "Setting[int]":
        def validate(v: int):
            if min_value is not None and v < min_value:
                raise SettingsException(f"[{key}] must be >= {min_value}, got {v}")
            if max_value is not None and v > max_value:
                raise SettingsException(f"[{key}] must be <= {max_value}, got {v}")

        return Setting(key, default, lambda v: int(v), properties, validate)

    @staticmethod
    def float_setting(
        key: str, default: float, min_value: Optional[float] = None,
        properties=Property.NODE_SCOPE,
    ) -> "Setting[float]":
        def validate(v: float):
            if min_value is not None and v < min_value:
                raise SettingsException(f"[{key}] must be >= {min_value}, got {v}")

        return Setting(key, default, lambda v: float(v), properties, validate)

    @staticmethod
    def string_setting(key: str, default: str = "", properties=Property.NODE_SCOPE,
                       validator=None) -> "Setting[str]":
        return Setting(key, default, str, properties, validator)

    @staticmethod
    def byte_size_setting(key: str, default: str, properties=Property.NODE_SCOPE) -> "Setting[ByteSizeValue]":
        return Setting(key, default, ByteSizeValue.parse, properties)

    @staticmethod
    def time_setting(key: str, default: str, properties=Property.NODE_SCOPE) -> "Setting[TimeValue]":
        return Setting(key, default, TimeValue.parse, properties)

    @staticmethod
    def list_setting(key: str, default: Optional[List[str]] = None,
                     properties=Property.NODE_SCOPE) -> "Setting[List[str]]":
        def parse(v):
            if isinstance(v, (list, tuple)):
                return [str(x) for x in v]
            s = str(v).strip()
            return [p.strip() for p in s.split(",") if p.strip()] if s else []

        return Setting(key, default or [], parse, properties)

    # -----------------------------------------------------------------------

    @property
    def dynamic(self) -> bool:
        return bool(self.properties & Property.DYNAMIC)

    @property
    def final(self) -> bool:
        return bool(self.properties & Property.FINAL)

    def default_value(self, settings: "Settings") -> T:
        d = self._default(settings) if callable(self._default) else self._default
        if d is None:
            return d
        value = self._parser(d)
        if self._validator:
            self._validator(value)
        return value

    def get(self, settings: "Settings") -> T:
        raw = settings.raw_get(self.key)
        if raw is None:
            return self.default_value(settings)
        value = self._parser(raw)
        if self._validator:
            self._validator(value)
        return value

    def exists(self, settings: "Settings") -> bool:
        return settings.raw_get(self.key) is not None


class Settings:
    """Immutable flat key→value map. Nested dicts flatten to dotted keys."""

    EMPTY: "Settings"

    def __init__(self, flat: Optional[Dict[str, Any]] = None):
        self._map: Dict[str, Any] = dict(flat or {})

    @staticmethod
    def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in d.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                out.update(Settings._flatten(v, key + "."))
            else:
                out[key] = v
        return out

    @classmethod
    def of(cls, d: Optional[Dict[str, Any]] = None, **kwargs: Any) -> "Settings":
        merged = dict(d or {})
        merged.update(kwargs)
        return cls(cls._flatten(merged))

    @staticmethod
    def normalize_index_settings(d: Optional[Dict[str, Any]]
                                 ) -> Dict[str, Any]:
        """Flatten an index-settings body accepting BOTH reference
        spellings — bare keys ("number_of_shards") and prefixed
        ("index.number_of_shards") — into the canonical index.-prefixed
        flat form. Shared by every create/update path so single-node and
        cluster mode treat identical bodies identically."""
        out: Dict[str, Any] = {}
        for k, v in Settings._flatten(d or {}).items():
            out[k if k.startswith("index.") else f"index.{k}"] = v
        return out

    def replace_all(self, flat: Dict[str, Any]) -> None:
        """Swap the full map in place (dynamic-settings recompute: base
        node config + persistent + transient). In-place so every holder
        of this Settings object observes the change."""
        self._map.clear()
        self._map.update(flat)

    def update_dynamic(self, changes: Dict[str, Any]) -> None:
        """Apply runtime setting changes in place — the one sanctioned
        mutation hook for the dynamic-settings API (reference:
        ClusterSettings#applySettings). A None value clears the key."""
        for key, value in Settings._flatten(changes).items():
            if value is None:
                self._map.pop(key, None)
            else:
                self._map[key] = value

    def raw_get(self, key: str) -> Any:
        return self._map.get(key)

    def get(self, key: str, default: Any = None) -> Any:
        return self._map.get(key, default)

    def get_as_dict(self) -> Dict[str, Any]:
        return dict(self._map)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._map.get(key)
        return default if v is None else int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._map.get(key)
        return default if v is None else float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        """Strict boolean parsing like the reference (Booleans#parseBoolean
        post-6.x: only true/false accepted — typos must not silently
        disable features)."""
        v = self._map.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        s = str(v).lower()
        if s == "true":
            return True
        if s == "false":
            return False
        raise SettingsException(
            f"Failed to parse value [{v}] for setting [{key}]: "
            f"only [true] or [false] are allowed")

    def keys(self) -> Iterable[str]:
        return self._map.keys()

    def filter_prefix(self, prefix: str) -> "Settings":
        return Settings({k: v for k, v in self._map.items() if k.startswith(prefix)})

    def merged_with(self, other: "Settings") -> "Settings":
        """`other` wins on conflicts (used for precedence chains)."""
        m = dict(self._map)
        m.update(other._map)
        return Settings(m)

    def with_removed(self, keys: Iterable[str]) -> "Settings":
        drop = set(keys)
        return Settings({k: v for k, v in self._map.items() if k not in drop})

    def to_xcontent(self, filtered_keys: Iterable[str] = ()) -> Dict[str, Any]:
        """Re-nest dotted keys into a JSON tree (the _settings API shape)."""
        drop = set(filtered_keys)
        tree: Dict[str, Any] = {}
        for k, v in sorted(self._map.items()):
            if k in drop:
                continue
            parts = k.split(".")
            node = tree
            for p in parts[:-1]:
                nxt = node.get(p)
                if not isinstance(nxt, dict):
                    nxt = {}
                    node[p] = nxt
                node = nxt
            node[parts[-1]] = v
        return tree

    def __eq__(self, other):
        return isinstance(other, Settings) and other._map == self._map

    def __hash__(self):
        # values may be unhashable (e.g. list settings) — hash a stable repr
        return hash(tuple(sorted((k, repr(v)) for k, v in self._map.items())))

    def __len__(self):
        return len(self._map)

    def __repr__(self):
        return f"Settings({self._map!r})"


Settings.EMPTY = Settings()


class AbstractScopedSettings:
    """Registry + validator + dynamic-update dispatcher for one scope.

    Reference: common/settings/AbstractScopedSettings;
    ClusterSettings#applySettings drives registered update consumers."""

    def __init__(self, scope: Property, registered: Iterable[Setting]):
        self.scope = scope
        self._registry: Dict[str, Setting] = {}
        self._consumers: List[tuple] = []  # (setting, callback)
        for s in registered:
            self.register(s)

    def register(self, setting: Setting) -> None:
        if not (setting.properties & self.scope):
            raise SettingsException(
                f"setting [{setting.key}] is not scoped {self.scope}"
            )
        if setting.key in self._registry:
            raise SettingsException(f"setting [{setting.key}] already registered")
        self._registry[setting.key] = setting

    def get_setting(self, key: str) -> Optional[Setting]:
        return self._registry.get(key)

    def validate(self, settings: Settings, allow_unknown: bool = False) -> None:
        for key in settings.keys():
            setting = self._registry.get(key)
            if setting is None:
                if not allow_unknown:
                    raise SettingsException(f"unknown setting [{key}]")
                continue
            setting.get(settings)  # parse + validate

    def validate_dynamic(self, settings: Settings) -> None:
        """Reject updates to non-dynamic or unknown settings."""
        for key in settings.keys():
            setting = self._registry.get(key)
            if setting is None:
                raise SettingsException(f"unknown setting [{key}]")
            if setting.final:
                raise SettingsException(f"final setting [{key}] cannot be updated")
            if not setting.dynamic:
                raise SettingsException(f"setting [{key}] is not dynamically updateable")
            setting.get(settings)

    def add_settings_update_consumer(self, setting: Setting, consumer: Callable[[Any], None]) -> None:
        if setting.key not in self._registry:
            raise SettingsException(f"setting [{setting.key}] not registered")
        if not setting.dynamic:
            raise SettingsException(f"setting [{setting.key}] is not dynamic")
        self._consumers.append((setting, consumer))

    def apply_settings(self, current: Settings, updates: Settings) -> Settings:
        """Validate `updates`, merge over `current`, fire changed consumers.
        Returns the new effective Settings. A value of None removes a key
        (reset to default), mirroring `"setting": null` in the REST API."""
        self.validate_dynamic(
            Settings({k: v for k, v in updates.get_as_dict().items() if v is not None})
        )
        removed = [k for k, v in updates.get_as_dict().items() if v is None]
        for k in removed:
            s = self._registry.get(k)
            if s is None:
                raise SettingsException(f"unknown setting [{k}]")
            if not s.dynamic:
                raise SettingsException(f"setting [{k}] is not dynamically updateable")
        effective = current.merged_with(
            Settings({k: v for k, v in updates.get_as_dict().items() if v is not None})
        ).with_removed(removed)
        for setting, consumer in self._consumers:
            old = setting.get(current)
            new = setting.get(effective)
            if old != new:
                consumer(new)
        return effective


class ClusterSettings(AbstractScopedSettings):
    def __init__(self, registered: Iterable[Setting]):
        super().__init__(Property.NODE_SCOPE, registered)


class IndexScopedSettings(AbstractScopedSettings):
    def __init__(self, registered: Iterable[Setting]):
        super().__init__(Property.INDEX_SCOPE, registered)
