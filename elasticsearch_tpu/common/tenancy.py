"""Per-tenant QoS: weighted admission shares over the node's budgets.

The north star is many users on one node; PR 5 (indexing pressure,
search backpressure) and PR 10 (batcher supervision) protect the NODE
from overload, but nothing stops one noisy tenant from eating the whole
budget while every other tenant eats the 429s. This module carves the
existing budgets into weighted per-tenant shares:

  * tenant identity rides the `X-Tenant-Id` header (or the `tenant_id`
    param) into REST dispatch, which binds it to the request thread;
    everything downstream — indexing-pressure charges, search
    admission, batch-lane composition, task stamping — reads the
    thread-local instead of threading a parameter through every call
    signature. Requests without a tenant belong to `_default`.
  * search admission: each tenant may hold at most its weighted share
    of `tenancy.search_slots` concurrent searches (the read-side
    concurrency budget; defaults to a multiple of the search pool so a
    single-tenant node never notices the carve).
  * write admission: each tenant may hold at most its weighted share of
    `indexing_pressure.memory.limit` in-flight coordinating bytes. The
    charge composes with the node-level check inside
    `IndexingPressure.mark_coordinating`, so every release path the
    pressure accounting already guarantees covers the tenant charge
    too — that is what makes the zero-drain chaos tests hold.

Both carves are in-flight accounting (grant + idempotent release), not
rate tokens — matching the pressure semantics and keeping "all counters
drain to zero after chaos" assertable. Rejections raise the typed
`TenantThrottledException` (429) with a Retry-After hint.

Weights come from flat settings keys `tenancy.weight.<tenant>`; tenants
without a configured weight collectively share one `default_weight`
slice, so adding a weight never silently zeroes unconfigured tenants.
With NO tenancy settings at all the default tenant's share is 1.0 —
full budget, zero behavior change.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, Optional

from elasticsearch_tpu.common import events, tracing
from elasticsearch_tpu.common.errors import (IllegalArgumentException,
                                             TenantThrottledException)
from elasticsearch_tpu.common.metrics import LabeledCounters

DEFAULT_TENANT = "_default"
TENANT_HEADER = "X-Tenant-Id"
TENANT_PARAM = "tenant_id"

WEIGHT_PREFIX = "tenancy.weight."

_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

_tls = threading.local()


def current_tenant() -> str:
    """The tenant bound to this request thread (REST dispatch binds it)."""
    return getattr(_tls, "tenant", DEFAULT_TENANT)


def bind_tenant(tenant: Optional[str]) -> str:
    """Bind `tenant` to this thread; → the prior binding. Callers must
    restore the prior binding in a finally — front supervisors and the
    thread pools reuse request threads across tenants."""
    prev = getattr(_tls, "tenant", DEFAULT_TENANT)
    _tls.tenant = tenant if tenant else DEFAULT_TENANT
    return prev


def resolve_tenant(value) -> str:
    """Validate a wire-supplied tenant id; missing/empty → default."""
    if value is None:
        return DEFAULT_TENANT
    value = str(value).strip()
    if not value:
        return DEFAULT_TENANT
    if value != DEFAULT_TENANT and not _TENANT_RE.match(value):
        raise IllegalArgumentException(
            f"invalid tenant id [{value[:80]}]: must match "
            "[A-Za-z0-9][A-Za-z0-9._-]{0,63}")
    return value


class _TenantState:
    __slots__ = ("search_inflight", "write_bytes")

    def __init__(self):
        self.search_inflight = 0
        self.write_bytes = 0


class TenantQuotaService:
    """Weighted in-flight admission quotas, one instance per node.

    Wired onto `IndexingPressure.tenants` (write carve),
    `SearchBackpressureService.tenants` (dominant-tenant shedding) and
    `MicroBatcher.tenants` (weighted round-robin lanes)."""

    def __init__(self, settings=None, *, write_limit_bytes: int = 0,
                 search_slots: int = 32):
        def opt(getter, key, default):
            return getter(key, default) if settings is not None else default
        get_bool = getattr(settings, "get_bool", None)
        get_int = getattr(settings, "get_int", None)
        get_float = getattr(settings, "get_float", None)
        self.enabled = opt(get_bool, "tenancy.enabled", True)
        self.default_weight = max(
            1e-6, opt(get_float, "tenancy.default_weight", 1.0))
        # read-side concurrency budget being carved; 0 → use the
        # node-derived default (a multiple of the search pool size, so
        # the default tenant's share always exceeds what the pool can
        # run concurrently and an unconfigured node behaves as before)
        self.search_slots = (opt(get_int, "tenancy.search_slots", 0)
                             or max(1, int(search_slots)))
        # write-side byte budget being carved (the indexing-pressure
        # limit); <= 0 disables the write carve, like the pressure limit
        self.write_limit = max(0, int(write_limit_bytes))
        self.weights: Dict[str, float] = {}
        if settings is not None:
            for key, value in settings.get_as_dict().items():
                if not key.startswith(WEIGHT_PREFIX):
                    continue
                name = key[len(WEIGHT_PREFIX):]
                try:
                    self.weights[name] = max(1e-6, float(value))
                except (TypeError, ValueError):
                    raise IllegalArgumentException(
                        f"[{key}] must be a positive number, "
                        f"got [{value}]")
        # unconfigured tenants (including `_default`) collectively get
        # one default_weight slice of the total
        self.total_weight = sum(self.weights.values()) + self.default_weight
        self._lock = threading.Lock()
        self._states: Dict[str, _TenantState] = {}
        self.search_admitted = LabeledCounters("tenant")
        self.search_rejections = LabeledCounters("tenant")
        self.write_bytes_total = LabeledCounters("tenant")
        self.write_rejections = LabeledCounters("tenant")
        # the es_tpu_tenant_* families must exist from the first scrape,
        # not only after the first admission/rejection
        for family in (self.search_admitted, self.search_rejections,
                       self.write_bytes_total, self.write_rejections):
            family.child(DEFAULT_TENANT)
        self._state(DEFAULT_TENANT)

    def _state(self, tenant: str) -> _TenantState:
        state = self._states.get(tenant)
        if state is None:
            with self._lock:
                state = self._states.setdefault(tenant, _TenantState())
        return state

    # -- share math --------------------------------------------------------

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)

    def share(self, tenant: str) -> float:
        return self.weight(tenant) / self.total_weight

    def search_cap(self, tenant: str) -> int:
        return max(1, int(round(self.share(tenant) * self.search_slots)))

    def write_cap_bytes(self, tenant: str) -> int:
        """0 → write carve disabled (no indexing-pressure limit)."""
        if self.write_limit <= 0:
            return 0
        return max(1, int(self.share(tenant) * self.write_limit))

    # -- admission ---------------------------------------------------------

    def admit_search(self, tenant: Optional[str] = None
                     ) -> Callable[[], None]:
        """Grant one search admission slot to `tenant` (thread-bound
        tenant when None) or raise the typed 429; → IDEMPOTENT release."""
        tenant = tenant or current_tenant()
        if not self.enabled:
            return lambda: None
        cap = self.search_cap(tenant)
        state = self._state(tenant)
        with self._lock:
            inflight = state.search_inflight
            rejected = inflight >= cap
            if not rejected:
                state.search_inflight += 1
        if rejected:
            self.search_rejections.inc(tenant)
            tracing.add_event("tenant.search.reject", tenant=tenant,
                              inflight=inflight, cap=cap)
            events.emit("tenant.throttle", severity="warning",
                        tenant=tenant, kind="search",
                        inflight=inflight, cap=cap)
            raise TenantThrottledException(
                f"tenant [{tenant}] exceeded its search admission share "
                f"[inflight={inflight}, cap={cap}, "
                f"weight={self.weight(tenant):g}/{self.total_weight:g}]; "
                "retry with backoff", tenant=tenant)
        self.search_admitted.inc(tenant)
        return self._search_releaser(state)

    def _search_releaser(self, state: _TenantState) -> Callable[[], None]:
        done = {"released": False}

        def release() -> None:
            with self._lock:
                if done["released"]:
                    return
                done["released"] = True
                state.search_inflight -= 1
        return release

    def charge_write(self, nbytes: int, tenant: Optional[str] = None
                     ) -> Callable[[], None]:
        """Charge `nbytes` against `tenant`'s share of the coordinating
        write budget or raise the typed 429; → IDEMPOTENT release."""
        tenant = tenant or current_tenant()
        nbytes = max(0, int(nbytes))
        if not self.enabled:
            return lambda: None
        cap = self.write_cap_bytes(tenant)
        state = self._state(tenant)
        with self._lock:
            current = state.write_bytes
            rejected = 0 < cap < current + nbytes
            if not rejected:
                state.write_bytes += nbytes
        if rejected:
            self.write_rejections.inc(tenant)
            tracing.add_event("tenant.write.reject", tenant=tenant,
                              operation_bytes=nbytes, current_bytes=current,
                              cap_bytes=cap)
            events.emit("tenant.throttle", severity="warning",
                        tenant=tenant, kind="write",
                        operation_bytes=nbytes, current_bytes=current,
                        cap_bytes=cap)
            raise TenantThrottledException(
                f"tenant [{tenant}] exceeded its indexing-pressure share "
                f"[current_bytes={current}, operation_bytes={nbytes}, "
                f"cap_bytes={cap}, "
                f"weight={self.weight(tenant):g}/{self.total_weight:g}]; "
                "retry with backoff", tenant=tenant)
        self.write_bytes_total.inc(tenant, n=nbytes)
        return self._write_releaser(state, nbytes)

    def _write_releaser(self, state: _TenantState, nbytes: int
                        ) -> Callable[[], None]:
        done = {"released": False}

        def release() -> None:
            with self._lock:
                if done["released"]:
                    return
                done["released"] = True
                state.write_bytes -= nbytes
        return release

    # -- duress integration ------------------------------------------------

    def _ratio(self, tenant: str, state: _TenantState) -> float:
        ratio = state.search_inflight / max(1, self.search_cap(tenant))
        cap = self.write_cap_bytes(tenant)
        if cap > 0:
            ratio = max(ratio, state.write_bytes / cap)
        return ratio

    def dominant_tenant(self) -> Optional[str]:
        """The tenant using the largest fraction of its own shares right
        now (None when nothing is in flight) — the one the backpressure
        service sheds/declines first under duress."""
        with self._lock:
            snap = list(self._states.items())
        best, best_ratio = None, 0.0
        for tenant, state in snap:
            ratio = self._ratio(tenant, state)
            if ratio > best_ratio:
                best, best_ratio = tenant, ratio
        return best

    def over_share(self, tenant: str) -> bool:
        """True when `tenant` holds at least its full share of some
        budget — the decline-under-duress trigger (never fires for a
        tenant comfortably inside its carve)."""
        state = self._states.get(tenant)
        if state is None:
            return False
        with self._lock:
            return self._ratio(tenant, state) >= 1.0

    # -- views -------------------------------------------------------------

    def usage(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant in-flight snapshot (the zero-drain assertion)."""
        with self._lock:
            return {t: {"search_inflight": s.search_inflight,
                        "write_bytes": s.write_bytes}
                    for t, s in self._states.items()}

    def stats(self) -> Dict[str, object]:
        """The `_nodes/stats` `tenants` section."""
        rejections = self.search_rejections.counts()
        write_rejections = self.write_rejections.counts()
        admitted = self.search_admitted.counts()
        out: Dict[str, object] = {
            "enabled": self.enabled,
            "default_weight": self.default_weight,
            "search_slots": self.search_slots,
            "write_limit_in_bytes": self.write_limit,
        }
        tenants = {}
        with self._lock:
            snap = list(self._states.items())
        for tenant, state in snap:
            tenants[tenant] = {
                "weight": self.weight(tenant),
                "search_cap": self.search_cap(tenant),
                "search_inflight": state.search_inflight,
                "search_admitted": admitted.get(tenant, 0),
                "search_rejections": rejections.get(tenant, 0),
                "write_cap_in_bytes": self.write_cap_bytes(tenant),
                "write_bytes_in_flight": state.write_bytes,
                "write_rejections": write_rejections.get(tenant, 0),
            }
        out["tenants"] = tenants
        return out
