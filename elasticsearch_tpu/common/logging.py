"""Logging infrastructure: node-wide configuration + the slow-log
channels.

Reference: `common/logging/**` (LogConfigurator) + `index/Search
SlowLog` / `IndexingSlowLog` (SURVEY.md §2.1#48, §5.1). Kept contracts:
one process-wide configuration from node settings (`logger.<name>:
LEVEL` overrides), dedicated `index.search.slowlog` /
`index.indexing.slowlog` channels, and threshold-tiered slow-log
records (warn/info/debug/trace picked by elapsed time).
"""

from __future__ import annotations

import logging
import sys
from typing import Any, Dict, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.units import TimeValue

ROOT = "elasticsearch_tpu"
SEARCH_SLOWLOG = "elasticsearch_tpu.index.search.slowlog"
INDEXING_SLOWLOG = "elasticsearch_tpu.index.indexing.slowlog"

_FORMAT = "[%(asctime)s][%(levelname)-5s][%(name)s] %(message)s"


# logger name → owner token of the configure() call that set it; resets
# only apply to the same owner so two embedded nodes in one process
# can't clobber each other's overrides
_configured_loggers: Dict[str, Any] = {}


def configure(settings=None, owner: Any = None) -> None:
    """Install the node's logging config (reference: LogConfigurator).
    `logger.<name>` settings override per-logger levels, e.g.
    -E logger.elasticsearch_tpu.cluster=DEBUG. Re-configuration with
    the same `owner` (the dynamic-settings path) resets overrides that
    owner removed; other owners' overrides are left alone."""
    root = logging.getLogger(ROOT)
    if not any(isinstance(h, logging.StreamHandler)
               for h in root.handlers):
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(handler)
    if root.level == logging.NOTSET:
        root.setLevel(logging.INFO)
    wanted: Dict[str, int] = {}
    if settings is not None:
        for key, value in settings.get_as_dict().items():
            if key.startswith("logger."):
                wanted[key[len("logger."):]] = _level(value)
    for name, level in wanted.items():
        logging.getLogger(name).setLevel(level)
        _configured_loggers[name] = owner
    if owner is None:
        return  # ad-hoc call: never resets anything
    # this owner's removed overrides revert to inheritance
    for name, owned_by in list(_configured_loggers.items()):
        if owned_by == owner and name not in wanted:
            logging.getLogger(name).setLevel(
                logging.INFO if name == ROOT else logging.NOTSET)
            del _configured_loggers[name]


def _level(value: Any) -> int:
    """ES-style level names → python levels (TRACE has no python
    equivalent; it maps to DEBUG like log4j-to-python bridges do)."""
    name = str(value).upper()
    mapping = {"TRACE": logging.DEBUG, "DEBUG": logging.DEBUG,
               "INFO": logging.INFO, "WARN": logging.WARNING,
               "WARNING": logging.WARNING, "ERROR": logging.ERROR,
               "FATAL": logging.CRITICAL, "CRITICAL": logging.CRITICAL}
    level = mapping.get(name)
    if level is None:
        raise IllegalArgumentException(
            f"unknown log level [{value}] (use trace|debug|info|warn|"
            f"error|fatal)")
    return level


class SlowLog:
    """Threshold-tiered slow logging for one index (reference:
    SearchSlowLog — thresholds are per-index settings; -1 disables)."""

    LEVELS = ("warn", "info", "debug", "trace")
    _LOG_FN = {"warn": "warning", "info": "info", "debug": "debug",
               "trace": "debug"}

    def __init__(self, index_name: str, settings,
                 phase: str = "query",
                 prefix: str = "index.search.slowlog.threshold",
                 channel: str = SEARCH_SLOWLOG):
        self.index_name = index_name
        self.logger = logging.getLogger(channel)
        self.phase = phase
        self.thresholds: Dict[str, float] = {}
        for level in self.LEVELS:
            raw = settings.get(f"{prefix}.{self.phase}.{level}")
            if raw is None:
                continue
            seconds = TimeValue.parse(raw).seconds
            if seconds >= 0:
                self.thresholds[level] = seconds
        # a configured debug/trace tier must actually emit: the channel
        # inherits the package INFO level unless opened up here (an
        # explicit logger.* setting still overrides afterwards)
        if any(lvl in self.thresholds for lvl in ("debug", "trace")) \
                and self.logger.level == logging.NOTSET:
            self.logger.setLevel(logging.DEBUG)

    @property
    def enabled(self) -> bool:
        return bool(self.thresholds)

    def maybe_log(self, took_s: float, shard: Any,
                  source: Optional[Dict[str, Any]] = None,
                  total_hits: Optional[int] = None) -> Optional[str]:
        """Log at the most severe tier whose threshold `took_s` crosses;
        returns the level used (for tests) or None. `shard` is the shard
        number, or "kernel" for the TPU fast path (one launch covers
        every shard of the index)."""
        hit_level = None
        for level in self.LEVELS:  # warn first = most severe
            t = self.thresholds.get(level)
            if t is not None and took_s >= t:
                hit_level = level
                break
        if hit_level is None:
            return None
        import json

        # every slowlog line carries the live trace id (or "-") so a
        # slow line links to /_tpu/traces and the flamegraph's
        # ?trace_id= sample filter (cold path: only slow queries pay)
        from elasticsearch_tpu.common import tracing
        span = tracing.current_span()
        trace_id = span.trace_id if span is not None \
            and getattr(span, "is_recording", False) else "-"
        msg = (f"[{self.index_name}][{shard}] took[{took_s * 1000:.1f}ms]"
               f", took_millis[{int(took_s * 1000)}]"
               f", total_hits[{total_hits if total_hits is not None else '-'}]"
               f", search_type[QUERY_THEN_FETCH]"
               f", trace_id[{trace_id}]"
               f", source[{json.dumps(source or {}, sort_keys=True)[:1000]}]")
        getattr(self.logger, self._LOG_FN[hit_level])(msg)
        return hit_level
