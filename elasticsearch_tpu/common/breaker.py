"""Hierarchical circuit breakers — memory admission control.

Reference: common/breaker/CircuitBreaker, ChildMemoryCircuitBreaker and
indices/breaker/HierarchyCircuitBreakerService (SURVEY.md §2.1#45): reject
work *before* running out of memory. Child breakers (request, fielddata,
in-flight) account their own reservations; the parent enforces a global
limit over the sum.

TPU mapping (SURVEY.md §7.1): the same accounting guards HBM residency —
segment packs charge an `hbm` breaker before device upload, so pack
eviction/readmission is driven by the identical mechanism the reference
uses for fielddata.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from elasticsearch_tpu.common import events
from elasticsearch_tpu.common.errors import CircuitBreakingException


class CircuitBreaker:
    def __init__(self, name: str, limit_bytes: int, overhead: float = 1.0,
                 parent: Optional["HierarchyCircuitBreakerService"] = None):
        self.name = name
        self.limit = limit_bytes
        self.overhead = overhead
        self._used = 0
        self._trips = 0
        self._lock = threading.Lock()
        self._parent = parent

    @property
    def used(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trips

    def add_estimate_bytes_and_maybe_break(self, bytes_wanted: int, label: str = "") -> None:
        with self._lock:
            new_used = self._used + bytes_wanted
            if bytes_wanted > 0 and new_used * self.overhead > self.limit:
                self._trips += 1
                events.emit("breaker.trip", severity="error",
                            breaker=self.name, label=label,
                            bytes_wanted=int(bytes_wanted),
                            used=int(self._used), limit=int(self.limit))
                raise CircuitBreakingException(
                    f"[{self.name}] data for [{label}] would be [{new_used}/"
                    f"{self.limit}] bytes, which is larger than the limit",
                    bytes_wanted=bytes_wanted, byte_limit=self.limit,
                )
            self._used = new_used
        if self._parent is not None and bytes_wanted > 0:
            try:
                self._parent.check_parent_limit(label)
            except CircuitBreakingException:
                with self._lock:
                    self._used -= bytes_wanted
                raise

    def add_without_breaking(self, bytes_delta: int) -> None:
        with self._lock:
            self._used += bytes_delta

    def release(self, nbytes: int) -> None:
        self.add_without_breaking(-nbytes)

    def stats(self) -> Dict:
        return {
            "limit_size_in_bytes": self.limit,
            "estimated_size_in_bytes": self._used,
            "overhead": self.overhead,
            "tripped": self._trips,
        }


class HierarchyCircuitBreakerService:
    """Parent limit over the sum of child breakers.

    Default child set mirrors the reference (request/fielddata/in_flight/
    accounting) plus the TPU-specific `hbm` breaker."""

    DEFAULT_CHILDREN = {
        "request": 0.6,
        "fielddata": 0.4,
        "in_flight_requests": 1.0,
        "accounting": 1.0,
        "hbm": 0.9,
    }

    def __init__(self, total_limit_bytes: int,
                 child_limits: Optional[Dict[str, int]] = None):
        self.total_limit = total_limit_bytes
        self._parent_trips = 0
        self._parent_lock = threading.Lock()
        self.breakers: Dict[str, CircuitBreaker] = {}
        child_limits = child_limits or {
            name: int(total_limit_bytes * frac)
            for name, frac in self.DEFAULT_CHILDREN.items()
        }
        for name, limit in child_limits.items():
            self.breakers[name] = CircuitBreaker(name, limit, parent=self)

    def get_breaker(self, name: str) -> CircuitBreaker:
        return self.breakers[name]

    def check_parent_limit(self, label: str = "") -> None:
        total = sum(b.used for b in self.breakers.values())
        if total > self.total_limit:
            with self._parent_lock:
                self._parent_trips += 1
            events.emit("breaker.trip", severity="error",
                        breaker="parent", label=label, used=int(total),
                        limit=int(self.total_limit))
            raise CircuitBreakingException(
                f"[parent] data for [{label}] would be [{total}/{self.total_limit}]"
                " bytes, which is larger than the limit",
                bytes_wanted=0, byte_limit=self.total_limit,
            )

    def stats(self) -> Dict:
        out = {name: b.stats() for name, b in self.breakers.items()}
        out["parent"] = {
            "limit_size_in_bytes": self.total_limit,
            "estimated_size_in_bytes": sum(b.used for b in self.breakers.values()),
            "tripped": self._parent_trips,
        }
        return out
